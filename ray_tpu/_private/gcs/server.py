"""GCS — the cluster control plane (one per cluster).

TPU-native counterpart of the reference's gcs_server
(reference: src/ray/gcs/gcs_server/gcs_server.h:78): node membership and
health, the actor directory with restart fault-tolerance, placement groups
with two-phase reserve/commit, jobs, a namespaced KV (which also backs the
function table), long-poll batched pubsub (reference: src/ray/pubsub/), task
events, and the cluster resource view that feeds scheduling/spillback and the
autoscaler. Everything runs on one asyncio loop, like the reference's single
asio io_context.

State is in-memory, persisted through a msgpack append log
(``persistence.GcsLog``) covering the KV/job/actor/named-actor/placement-
group/node tables. On restart the log replays and the cluster resumes:
raylets re-register on their next heartbeat, pubsub subscribers re-subscribe
when they observe a new server epoch (reference uses Redis for this —
src/ray/gcs/store_client/redis_store_client.h).
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import sys
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import flight_recorder as _fr
from ray_tpu._private.config import RTPU_CONFIG
from ray_tpu._private.gcs.persistence import GcsLog
from ray_tpu._private.ids import ActorID, JobID, NodeID, PlacementGroupID
from ray_tpu._private.rpc import ClientPool, RpcServer

logger = logging.getLogger("ray_tpu.gcs")

# Actor lifecycle states (reference: protobuf gcs.proto ActorTableData.ActorState)
DEPENDENCIES_UNREADY = "DEPENDENCIES_UNREADY"
PENDING_CREATION = "PENDING_CREATION"
ALIVE = "ALIVE"
RESTARTING = "RESTARTING"
DEAD = "DEAD"


class KVStore:
    def __init__(self):
        self._data: Dict[str, Dict[bytes, bytes]] = {}

    def _ns(self, ns: str) -> Dict[bytes, bytes]:
        return self._data.setdefault(ns or "", {})

    def put(self, ns, key, value, overwrite=True) -> bool:
        table = self._ns(ns)
        if not overwrite and key in table:
            return False
        table[key] = value
        return True

    def get(self, ns, key):
        return self._ns(ns).get(key)

    def delete(self, ns, key) -> bool:
        return self._ns(ns).pop(key, None) is not None

    def keys(self, ns, prefix=b""):
        return [k for k in self._ns(ns) if k.startswith(prefix)]

    def exists(self, ns, key) -> bool:
        return key in self._ns(ns)


class PubSub:
    """Long-poll batched pubsub, one queue per subscriber.

    The reference replaced per-key long-polling with batched channel polling
    (reference: src/ray/pubsub/README.md); same design here: subscribers poll
    and receive every buffered (channel, message) batch at once.
    """

    def __init__(self):
        self._subs: Dict[bytes, Dict[str, Any]] = {}
        # Exact-channel and wildcard-prefix indexes: publish() must not scan
        # every subscriber's channel set (a driver watching N actors holds N
        # channels — the scan made actor-burst publishing O(N^2)).
        self._exact: Dict[str, set] = {}
        self._prefix: Dict[str, set] = {}

    def subscribe(self, sub_id: bytes, channel: str):
        sub = self._subs.setdefault(
            sub_id, {"channels": set(), "queue": [], "event": asyncio.Event()}
        )
        sub["channels"].add(channel)
        if channel.endswith("*"):
            self._prefix.setdefault(channel[:-1], set()).add(sub_id)
        else:
            self._exact.setdefault(channel, set()).add(sub_id)

    def _unindex(self, sub_id: bytes, channel: str):
        table, key = (
            (self._prefix, channel[:-1]) if channel.endswith("*")
            else (self._exact, channel)
        )
        ids = table.get(key)
        if ids is not None:
            ids.discard(sub_id)
            if not ids:
                del table[key]

    def unsubscribe(self, sub_id: bytes, channel: Optional[str]):
        sub = self._subs.get(sub_id)
        if not sub:
            return
        if channel is None:
            for ch in sub["channels"]:
                self._unindex(sub_id, ch)
            del self._subs[sub_id]
        else:
            sub["channels"].discard(channel)
            self._unindex(sub_id, channel)

    def publish(self, channel: str, message):
        targets = set(self._exact.get(channel, ()))
        for prefix, ids in self._prefix.items():
            if channel.startswith(prefix):
                targets |= ids
        for sub_id in targets:
            sub = self._subs.get(sub_id)
            if sub is None:
                continue
            q = sub["queue"]
            q.append([channel, message])
            if len(q) > RTPU_CONFIG.pubsub_max_batch:
                del q[: len(q) - RTPU_CONFIG.pubsub_max_batch]
            sub["event"].set()

    async def poll(self, sub_id: bytes, timeout: float):
        sub = self._subs.setdefault(
            sub_id, {"channels": set(), "queue": [], "event": asyncio.Event()}
        )
        if not sub["queue"]:
            sub["event"].clear()
            try:
                await asyncio.wait_for(sub["event"].wait(), timeout)
            except asyncio.TimeoutError:
                pass
        batch = sub["queue"]
        sub["queue"] = []
        return batch


class GcsServer:
    def __init__(self, host="127.0.0.1", session_dir: str = "", persist_path: str = ""):
        self.host = host
        self.session_dir = session_dir
        self.server = RpcServer(host)
        from ray_tpu._private import schema as _schema

        self.server.set_validator(_schema.make_validator(_schema.GCS_SCHEMAS))
        self.kv = KVStore()
        self.pubsub = PubSub()
        self.pool = ClientPool()  # clients to raylets / workers
        self.start_time = time.time()
        # A fresh epoch per server process: clients detect a restart by the
        # epoch changing and re-subscribe their pubsub channels.
        self.epoch = uuid.uuid4().hex
        if not persist_path and session_dir and RTPU_CONFIG.gcs_persistence:
            persist_path = os.path.join(session_dir, "gcs.log")
        self.log: Optional[GcsLog] = (
            GcsLog(persist_path, fsync=RTPU_CONFIG.gcs_log_fsync)
            if persist_path
            else None
        )
        self._compacting = False
        self._compact_buffer: List[Tuple[str, Any]] = []

        # node_id(bytes) -> info dict
        self.nodes: Dict[bytes, dict] = {}
        self.node_last_beat: Dict[bytes, float] = {}
        # actor_id(bytes) -> record
        self.actors: Dict[bytes, dict] = {}
        self.named_actors: Dict[Tuple[str, str], bytes] = {}  # (ns, name) -> actor_id
        self.pending_actor_queue: List[bytes] = []
        # Concurrent actor creation: the pump leases workers for many pending
        # actors at once (reference: gcs_actor_scheduler.cc leases in parallel
        # per actor); the semaphore bounds in-flight creations and
        # _actor_inflight stops concurrent picks from over-committing a node
        # before its next resource report lands.
        self._actor_create_sem = asyncio.Semaphore(
            RTPU_CONFIG.actor_creation_parallelism
        )
        self._actor_inflight: Dict[bytes, Dict[str, float]] = {}
        # kill() seen before the (async-batched) registration arrived
        self._kill_tombstones: set = set()
        # pg_id(bytes) -> record
        self.placement_groups: Dict[bytes, dict] = {}
        self.pending_pg_queue: List[bytes] = []
        self.jobs: Dict[bytes, dict] = {}
        self.task_events: List[dict] = []
        self._worker_failures: List[dict] = []
        # Incident table (stall watchdog + forensics): bounded append log of
        # stall/hang reports with captured stacks and flight-recorder rings.
        self.incidents: List[dict] = []
        # (name, sorted-label-items) -> aggregated user-metric record
        self.user_metrics: Dict[Tuple[str, tuple], dict] = {}
        self.metrics_port = 0
        self._bg_tasks = []

    # ------------------------------------------------------------------ util

    def _raylet_client(self, node_id: bytes):
        info = self.nodes[node_id]
        return self.pool.get(info["ip"], info["raylet_port"])

    def alive_nodes(self) -> List[bytes]:
        return [nid for nid, n in self.nodes.items() if n["state"] == "ALIVE"]

    # ---------------------------------------------------------- persistence

    def _persist(self, kind: str, data):
        if self.log is None:
            return
        if self._compacting:
            # A snapshot write is in flight off-loop; appends to the old file
            # would be clobbered by the rename. Buffer and flush after.
            self._compact_buffer.append((kind, data))
            return
        try:
            self.log.append(kind, data)
        except Exception:
            logger.exception("gcs log append failed")

    def _persist_actor(self, rec: dict):
        self._persist("actor", rec)

    def _persist_pg(self, pg: dict):
        self._persist("pg", {k: v for k, v in pg.items() if k != "ready_event"})

    def _restore(self):
        """Replay the append log into the in-memory tables, then compact.

        A malformed record (version skew, partial corruption past the frame
        check) is skipped, never fatal: a GCS that cannot start is strictly
        worse than one missing a record, and the node monitor would respawn
        a crashing GCS forever.
        """
        if self.log is None:
            return
        n = 0
        try:
            replay = list(self.log.replay())
        except Exception:
            logger.exception("gcs log unreadable; starting empty")
            return
        for kind, data in replay:
            try:
                n += 1
                if kind == "kv":
                    ns, key, value = data
                    if value is None:
                        self.kv.delete(ns, key)
                    else:
                        self.kv.put(ns, key, value)
                elif kind == "job":
                    self.jobs[data["job_id"]] = data
                elif kind == "actor":
                    self.actors[data["actor_id"]] = data
                elif kind == "named":
                    ns, name, actor_id = data
                    if actor_id is None:
                        self.named_actors.pop((ns, name), None)
                    else:
                        self.named_actors[(ns, name)] = actor_id
                elif kind == "pg":
                    data["ready_event"] = None
                    self.placement_groups[data["pg_id"]] = data
                elif kind == "node":
                    self.nodes[data["node_id"]] = data
            except Exception:
                logger.exception("skipping malformed gcs log record kind=%r", kind)
        if n == 0:
            return
        now = time.time()
        for node_id, info in self.nodes.items():
            # Give restored nodes a full grace window to heartbeat back in.
            self.node_last_beat[node_id] = now
        for actor_id, rec in self.actors.items():
            if rec["state"] in (PENDING_CREATION, RESTARTING):
                self.pending_actor_queue.append(actor_id)
        for pg_id, pg in self.placement_groups.items():
            if pg["state"] in ("PENDING", "RESCHEDULING"):
                self.pending_pg_queue.append(pg_id)
        logger.info(
            "GCS restored from %s: %d records, %d nodes, %d actors, %d pgs, %d jobs",
            self.log.path, n, len(self.nodes), len(self.actors),
            len(self.placement_groups), len(self.jobs),
        )
        self._compact()

    def _snapshot_records(self) -> List[Tuple[str, Any]]:
        records: List[Tuple[str, Any]] = []
        for ns, table in self.kv._data.items():
            for key, value in table.items():
                records.append(("kv", [ns, key, value]))
        for job in self.jobs.values():
            records.append(("job", job))
        for rec in self.actors.values():
            records.append(("actor", rec))
        for (ns, name), actor_id in self.named_actors.items():
            records.append(("named", [ns, name, actor_id]))
        for pg in self.placement_groups.values():
            records.append(
                ("pg", {k: v for k, v in pg.items() if k != "ready_event"})
            )
        for info in self.nodes.values():
            records.append(("node", info))
        return records

    def _compact(self):
        if self.log is None:
            return
        try:
            self.log.compact(self._snapshot_records())
        except Exception:
            logger.exception("gcs log compaction failed")

    async def _compaction_loop(self):
        """Compact off-loop: the snapshot is captured synchronously (cheap,
        point-in-time consistent) but the serialize+fsync runs in a thread so
        a large state dump cannot stall heartbeat handling past the health
        threshold and wrongly kill every node."""
        limit = RTPU_CONFIG.gcs_log_compact_bytes
        while True:
            await asyncio.sleep(5.0)
            if self.log is None or self.log.size() <= limit or self._compacting:
                continue
            # Pack on the loop (consistent point-in-time view of the live
            # table dicts); only the write+fsync goes to the thread.
            blob = GcsLog.pack(self._snapshot_records())
            self._compacting = True
            self._compact_buffer = []
            try:
                await asyncio.get_running_loop().run_in_executor(
                    None, self.log.compact_packed, blob
                )
            except Exception:
                logger.exception("gcs log compaction failed")
            finally:
                self._compacting = False
                buffered, self._compact_buffer = self._compact_buffer, []
                for kind, data in buffered:
                    self._persist(kind, data)

    # ------------------------------------------------------------- lifecycle

    async def start(self, port: int = 0) -> int:
        self._restore()
        self.server.register_all(self)
        port = await self.server.start(port)
        try:
            from ray_tpu._private.metrics import start_metrics_http_server

            self.metrics_server, self.metrics_port = await start_metrics_http_server(
                self.host, self._collect_metrics
            )
        except Exception:
            logger.exception("metrics endpoint failed to start")
            self.metrics_port = 0
        self._bg_tasks.append(asyncio.ensure_future(self._health_check_loop()))
        self._bg_tasks.append(asyncio.ensure_future(self._compaction_loop()))
        if self.session_dir:
            try:
                _fr.install_exit_dump(os.path.join(
                    self.session_dir, "logs", f"flight_gcs-{os.getpid()}.jsonl"))
            except Exception:
                pass
        if self.pending_actor_queue:
            asyncio.ensure_future(self._schedule_pending_actors())
        if self.pending_pg_queue:
            asyncio.ensure_future(self._schedule_pending_pgs())
        logger.info("GCS listening on %s:%s", self.host, port)
        return port

    async def _health_check_loop(self):
        period = RTPU_CONFIG.health_check_period_ms / 1000.0
        threshold = RTPU_CONFIG.health_check_failure_threshold
        while True:
            await asyncio.sleep(period)
            now = time.time()
            for node_id, info in list(self.nodes.items()):
                if info["state"] != "ALIVE":
                    continue
                last = self.node_last_beat.get(node_id, now)
                if now - last > period * threshold:
                    await self._mark_node_dead(node_id, "missed heartbeats")

    async def _mark_node_dead(self, node_id: bytes, reason: str):
        info = self.nodes.get(node_id)
        if info is None or info["state"] == "DEAD":
            return
        info["state"] = "DEAD"
        info["end_time"] = time.time()
        _fr.record("node.dead", node_id, reason[:120])
        logger.warning("node %s dead: %s", node_id.hex(), reason)
        self._persist("node", info)
        self.pubsub.publish("node", {"node_id": node_id, "state": "DEAD"})
        # Fail/restart actors that lived on this node.
        for actor_id, rec in list(self.actors.items()):
            if rec.get("node_id") == node_id and rec["state"] in (ALIVE, PENDING_CREATION):
                await self._on_actor_worker_lost(actor_id, f"node died: {reason}")
        # Re-schedule placement groups that had bundles there.
        for pg_id, pg in list(self.placement_groups.items()):
            if pg["state"] == "CREATED" and any(
                b.get("node_id") == node_id for b in pg["bundles"]
            ):
                pg["state"] = "RESCHEDULING"
                for b in pg["bundles"]:
                    if b.get("node_id") == node_id:
                        b["node_id"] = None
                self._persist_pg(pg)
                self.pending_pg_queue.append(pg_id)
                asyncio.ensure_future(self._schedule_pending_pgs())

    # ------------------------------------------------------------ node table

    async def handle_RegisterNode(self, req):
        node_id = req["node_id"]
        self.nodes[node_id] = {
            "node_id": node_id,
            "ip": req["ip"],
            "raylet_port": req["raylet_port"],
            "object_manager_port": req.get("object_manager_port", req["raylet_port"]),
            "plasma_name": req.get("plasma_name", ""),
            "resources_total": dict(req.get("resources", {})),
            "resources_available": dict(req.get("resources", {})),
            "labels": dict(req.get("labels", {})),
            "state": "ALIVE",
            "start_time": time.time(),
            "is_head": bool(req.get("is_head")),
            "metrics_port": req.get("metrics_port", 0),
        }
        self.node_last_beat[node_id] = time.time()
        self._persist("node", self.nodes[node_id])
        self.pubsub.publish("node", {"node_id": node_id, "state": "ALIVE"})
        # New capacity: retry pending actors/PGs.
        asyncio.ensure_future(self._schedule_pending_actors())
        asyncio.ensure_future(self._schedule_pending_pgs())
        return {"ok": True}

    async def handle_UnregisterNode(self, req):
        await self._mark_node_dead(req["node_id"], "unregistered")
        return {"ok": True}

    def _autoscaler_active_now(self) -> bool:
        """True while an autoscaler heartbeat (timestamped KV) is fresh — a
        crashed autoscaler must not leave raylets queueing infeasible work
        forever."""
        v = self.kv.get("", b"__autoscaler_active__")
        if not v:
            return False
        try:
            return time.time() - float(v) < 30.0
        except (TypeError, ValueError):
            return True  # legacy non-timestamped value

    async def handle_GetAutoscalerActive(self, req):
        return {"active": self._autoscaler_active_now()}

    async def handle_Heartbeat(self, req):
        node_id = req["node_id"]
        self.node_last_beat[node_id] = time.time()
        # "known" lets a raylet detect a GCS that restarted without its
        # registration (e.g. persistence disabled) and re-register.
        info = self.nodes.get(node_id)
        return {
            "known": info is not None and info["state"] == "ALIVE",
            "autoscaler_active": self._autoscaler_active_now(),
        }

    async def handle_ReportResources(self, req):
        node = self.nodes.get(req["node_id"])
        if node is None:
            return
        node["resources_available"] = req["available"]
        node["resources_total"] = req["total"]
        node["pending_demands"] = req.get("pending_demands", [])
        node["num_leases"] = req.get("num_leases", 0)
        node["num_workers"] = req.get("num_workers", 0)
        self.node_last_beat[req["node_id"]] = time.time()
        # Push the delta to every raylet's cluster view (the RaySyncer
        # broadcast plane, reference: common/ray_syncer/ray_syncer.h:88 —
        # here a pubsub channel drained by batched long-polls).
        self.pubsub.publish("resources", {
            "node_id": req["node_id"],
            "available": req["available"],
            "total": req["total"],
            "num_leases": node["num_leases"],
            "num_workers": node["num_workers"],
        })
        if self.pending_actor_queue:
            asyncio.ensure_future(self._schedule_pending_actors())
        if self.pending_pg_queue:
            asyncio.ensure_future(self._schedule_pending_pgs())

    async def handle_GetAllNodeInfo(self, req):
        nodes = list(self.nodes.values())
        limit = req.get("limit")
        return {"nodes": nodes[:limit] if limit else nodes}

    async def handle_GetClusterResources(self, req):
        total: Dict[str, float] = {}
        avail: Dict[str, float] = {}
        for nid in self.alive_nodes():
            n = self.nodes[nid]
            for k, v in n["resources_total"].items():
                total[k] = total.get(k, 0) + v
            for k, v in n["resources_available"].items():
                avail[k] = avail.get(k, 0) + v
        return {"total": total, "available": avail}

    async def handle_GetInternalConfig(self, req):
        return {"config": RTPU_CONFIG.dump(), "session_dir": self.session_dir}

    async def handle_GetClusterLoad(self, req):
        """Autoscaler input: everything waiting for resources right now
        (reference: GcsAutoscalerStateManager::HandleGetClusterResourceState,
        gcs_autoscaler_state_manager.h:30 — pending task shapes, pending
        actors, unplaced placement-group bundles, per-node utilization)."""
        pending_tasks: List[dict] = []
        for nid in self.alive_nodes():
            pending_tasks.extend(self.nodes[nid].get("pending_demands", []))
        pending_actors = []
        for actor_id in self.pending_actor_queue:
            rec = self.actors.get(actor_id)
            if rec is not None and rec["state"] in (PENDING_CREATION, RESTARTING):
                pending_actors.append(dict(rec["creation_spec"].get("resources", {})))
        pending_pg_bundles = []
        for pg_id in self.pending_pg_queue:
            pg = self.placement_groups.get(pg_id)
            if pg is not None and pg["state"] in ("PENDING", "RESCHEDULING"):
                for b in pg["bundles"]:
                    if b.get("node_id") is None:
                        pending_pg_bundles.append(
                            {"resources": dict(b["resources"]), "strategy": pg["strategy"]}
                        )
        nodes = [
            {
                "node_id": nid,
                "resources_total": self.nodes[nid]["resources_total"],
                "resources_available": self.nodes[nid]["resources_available"],
                "num_leases": self.nodes[nid].get("num_leases", 0),
                "num_workers": self.nodes[nid].get("num_workers", 0),
                "labels": self.nodes[nid].get("labels", {}),
                "is_head": self.nodes[nid].get("is_head", False),
            }
            for nid in self.alive_nodes()
        ]
        return {
            "pending_tasks": pending_tasks,
            "pending_actors": pending_actors,
            "pending_pg_bundles": pending_pg_bundles,
            "nodes": nodes,
        }

    # --------------------------------------------------------------------- kv

    async def handle_KVPut(self, req):
        added = self.kv.put(req["ns"], req["key"], req["value"], req.get("overwrite", True))
        if added:
            self._persist("kv", [req["ns"], req["key"], req["value"]])
        return {"added": added}

    async def handle_KVGet(self, req):
        return {"value": self.kv.get(req["ns"], req["key"])}

    async def handle_KVDel(self, req):
        deleted = self.kv.delete(req["ns"], req["key"])
        if deleted:
            self._persist("kv", [req["ns"], req["key"], None])
        return {"deleted": deleted}

    async def handle_KVKeys(self, req):
        return {"keys": self.kv.keys(req["ns"], req.get("prefix", b""))}

    async def handle_KVExists(self, req):
        return {"exists": self.kv.exists(req["ns"], req["key"])}

    # ------------------------------------------------------------------ pubsub

    async def handle_Subscribe(self, req):
        self.pubsub.subscribe(req["sub_id"], req["channel"])
        # Epoch lets the subscriber baseline restart detection atomically
        # with the subscription (a restart between Subscribe and the first
        # poll would otherwise go unnoticed forever).
        return {"ok": True, "epoch": self.epoch}

    async def handle_SubscribeMany(self, req):
        """Batch subscribe: one round-trip for a burst of channels (the
        driver's batched actor registration subscribes N watch channels at
        once)."""
        for ch in req["channels"]:
            self.pubsub.subscribe(req["sub_id"], ch)
        return {"ok": True, "epoch": self.epoch}

    async def handle_Unsubscribe(self, req):
        self.pubsub.unsubscribe(req["sub_id"], req.get("channel"))
        return {"ok": True}

    async def handle_PubsubPoll(self, req):
        timeout = min(req.get("timeout", 30.0), RTPU_CONFIG.pubsub_poll_timeout_s)
        batch = await self.pubsub.poll(req["sub_id"], timeout)
        # Epoch lets pollers detect a GCS restart (subscriber state is
        # process-local) and re-subscribe their channels.
        return {"batch": batch, "epoch": self.epoch}

    async def handle_Publish(self, req):
        self.pubsub.publish(req["channel"], req["message"])
        return {"ok": True}

    # -------------------------------------------------------------------- jobs

    async def handle_AddJob(self, req):
        self.jobs[req["job_id"]] = {
            "job_id": req["job_id"],
            "driver_addr": req.get("driver_addr"),
            "start_time": time.time(),
            "end_time": None,
            "state": "RUNNING",
            "entrypoint": req.get("entrypoint", ""),
            "metadata": req.get("metadata", {}),
            "driver_sys_path": req.get("driver_sys_path", []),
        }
        self._persist("job", self.jobs[req["job_id"]])
        self.pubsub.publish("job", {"job_id": req["job_id"], "state": "RUNNING"})
        return {"ok": True}

    async def handle_GetJob(self, req):
        job = self.jobs.get(req["job_id"])
        return {"found": job is not None, "job": job or {}}

    async def handle_MarkJobFinished(self, req):
        job = self.jobs.get(req["job_id"])
        if job:
            job["state"] = "FINISHED"
            job["end_time"] = time.time()
            self._persist("job", job)
        self.pubsub.publish("job", {"job_id": req["job_id"], "state": "FINISHED"})
        # Tell raylets to reap this job's workers.
        for nid in self.alive_nodes():
            try:
                client = await self._raylet_client(nid)
                await client.notify("JobFinished", {"job_id": req["job_id"]})
            except Exception:
                pass
        return {"ok": True}

    async def handle_GetAllJobInfo(self, req):
        jobs = list(self.jobs.values())
        limit = req.get("limit")
        return {"jobs": jobs[:limit] if limit else jobs}

    # ------------------------------------------------------------------ actors

    async def handle_RegisterActors(self, req):
        """Batched registration of anonymous actors: one RPC, one pump kick
        (the driver coalesces a `.remote()` burst into this)."""
        for item in req["items"]:
            self._register_actor_record(item)
        asyncio.ensure_future(self._schedule_pending_actors())
        return {"ok": True}

    async def handle_RegisterActor(self, req):
        """Register + asynchronously schedule an actor creation.

        req: {actor_id, creation_spec(task spec dict), name, ray_namespace,
              max_restarts, detached}
        """
        self._register_actor_record(req)
        asyncio.ensure_future(self._schedule_pending_actors())
        return {"ok": True}

    def _register_actor_record(self, req):
        actor_id = req["actor_id"]
        if actor_id in self.actors:
            # Idempotent: a client retry of its own registration (after a
            # dropped reply / GCS failover) must not reset a live actor back
            # to PENDING_CREATION and re-schedule it.
            return
        if actor_id in self._kill_tombstones:
            self._kill_tombstones.discard(actor_id)
            rec = {
                "actor_id": actor_id, "state": DEAD,
                "creation_spec": req["creation_spec"], "name": req.get("name") or "",
                "namespace": req.get("namespace") or "",
                "max_restarts": 0, "num_restarts": 0,
                "detached": req.get("detached", False),
                "owner_worker_id": req["creation_spec"].get("owner_worker_id"),
                "node_id": None, "worker_id": None, "addr": None,
                "job_id": req["creation_spec"]["job_id"],
                "death_cause": "killed via kill()", "start_time": time.time(),
            }
            self.actors[actor_id] = rec
            self._publish_actor(actor_id, rec)
            return
        name = req.get("name") or ""
        ns = req.get("namespace") or ""
        if name:
            if (ns, name) in self.named_actors:
                existing = self.named_actors[(ns, name)]
                # existing == actor_id: a client retry of our own
                # registration after a GCS failover — idempotent, not a
                # collision.
                if existing != actor_id and self.actors.get(existing, {}).get("state") != DEAD:
                    raise ValueError(f"actor name '{name}' already taken")
            self.named_actors[(ns, name)] = actor_id
            self._persist("named", [ns, name, actor_id])
        self.actors[actor_id] = {
            "actor_id": actor_id,
            "state": PENDING_CREATION,
            "creation_spec": req["creation_spec"],
            "name": name,
            "namespace": ns,
            "max_restarts": req.get("max_restarts", 0),
            "num_restarts": 0,
            "detached": req.get("detached", False),
            "owner_worker_id": req["creation_spec"].get("owner_worker_id"),
            "node_id": None,
            "worker_id": None,
            "addr": None,
            "job_id": req["creation_spec"]["job_id"],
            "death_cause": "",
            "start_time": time.time(),
        }
        self._persist_actor(self.actors[actor_id])
        self.pending_actor_queue.append(actor_id)

    def _pick_node(self, resources: Dict[str, float], strategy: dict) -> Optional[bytes]:
        """Hybrid placement for actors/PG bundles at the GCS level.
        node_label strategies filter candidates to hard-label matches and
        prefer soft-label matches (reference:
        raylet/scheduling/policy/node_label_scheduling_policy.cc)."""
        is_label = strategy.get("type") == "node_label"
        hard = (strategy.get("hard") or {}) if is_label else {}
        soft = (strategy.get("soft") or {}) if is_label else {}
        candidates = []
        for nid in self.alive_nodes():
            n = self.nodes[nid]
            if strategy.get("type") == "node_affinity":
                if nid != strategy["node_id"]:
                    continue
            labels = n.get("labels", {})
            if is_label and any(labels.get(k) != v for k, v in hard.items()):
                continue
            avail = n["resources_available"]
            infl = self._actor_inflight.get(nid)
            if infl:
                avail = {k: avail.get(k, 0.0) - infl.get(k, 0.0)
                         for k in set(avail) | set(infl)}
            total = n["resources_total"]
            if all(avail.get(k, 0) >= v for k, v in resources.items()) and all(
                total.get(k, 0) >= v for k, v in resources.items()
            ):
                used = sum(
                    1 - avail.get(k, 0) / total[k] for k in total if total[k] > 0
                )
                soft_ok = bool(soft) and all(
                    labels.get(k) == v for k, v in soft.items()
                )
                candidates.append((used, nid, soft_ok))
        if soft and any(c[2] for c in candidates):
            # soft-label matches exist: restrict to them (soft preference
            # outranks the load score but never makes placement infeasible)
            candidates = [c for c in candidates if c[2]]
        candidates = [(used, nid) for used, nid, _ in candidates]
        if not candidates:
            if strategy.get("type") == "node_affinity" and strategy.get("soft"):
                return self._pick_node(resources, {})
            return None
        candidates.sort(key=lambda c: (c[0], c[1]))
        if strategy.get("type") == "spread":
            return candidates[0][1]  # least utilized
        # default: pack — most utilized feasible node below threshold, else least
        packed = [c for c in candidates if c[0] <= RTPU_CONFIG.scheduler_spread_threshold]
        if packed:
            return packed[-1][1]
        return candidates[0][1]

    async def _schedule_pending_actors(self):
        queue, self.pending_actor_queue = self.pending_actor_queue, []
        if not queue:
            return
        # Pick nodes up front (synchronously — one consistent view), then
        # drive creations grouped per node in batched LeaseWorkersForActors
        # RPCs. Each batch runs as its own coroutine so a burst pipelines
        # instead of paying sequential fork+register round-trips; the shared
        # semaphore bounds total in-flight creations across pumps.
        singles: list = []   # (actor_id, rec) that must go one-at-a-time
        by_node: Dict[bytes, list] = {}
        for actor_id in queue:
            rec = self.actors.get(actor_id)
            if rec is None or rec["state"] not in (PENDING_CREATION, RESTARTING):
                continue
            spec = rec["creation_spec"]
            strategy = spec.get("strategy", {})
            if strategy.get("type") == "placement_group":
                singles.append(actor_id)
                continue
            node_id = self._pick_node(spec["resources"], strategy)
            if node_id is None:
                self.pending_actor_queue.append(actor_id)
                continue
            infl = self._actor_inflight.setdefault(node_id, {})
            for k, v in spec["resources"].items():
                infl[k] = infl.get(k, 0.0) + v
            # carry the reserved resources so the release matches the
            # reservation even if the record mutates before the batch runs
            by_node.setdefault(node_id, []).append(
                (actor_id, dict(spec["resources"]))
            )
        tasks = [self._schedule_one_actor(a) for a in singles]
        batch = RTPU_CONFIG.actor_creation_lease_batch
        for node_id, pairs in by_node.items():
            for i in range(0, len(pairs), batch):
                tasks.append(self._lease_actor_batch(node_id, pairs[i:i + batch]))
        if tasks:
            await asyncio.gather(*tasks)

    def _release_inflight(self, node_id: bytes, resources: Dict[str, float]):
        infl = self._actor_inflight.get(node_id)
        if infl is None:
            return
        for k, v in resources.items():
            infl[k] = infl.get(k, 0.0) - v
            if infl[k] <= 0:
                infl.pop(k, None)
        if not infl:
            self._actor_inflight.pop(node_id, None)

    async def _lease_actor_batch(self, node_id: bytes, pairs: list):
        """One LeaseWorkersForActors RPC creating a batch of actors on one
        node (each still forks its own worker raylet-side, concurrently).
        `pairs` is [(actor_id, reserved_resources)]."""
        async with self._actor_create_sem:
            items, recs = [], []
            for actor_id, reserved in pairs:
                rec = self.actors.get(actor_id)
                if rec is None or rec["state"] not in (PENDING_CREATION, RESTARTING):
                    self._release_inflight(node_id, reserved)
                    continue
                spec = rec["creation_spec"]
                items.append({
                    "actor_id": actor_id,
                    "job_id": spec["job_id"],
                    "resources": spec["resources"],
                    "strategy": spec.get("strategy", {}),
                    "runtime_env": spec.get("runtime_env", {}),
                    "spec": spec,
                })
                recs.append((actor_id, rec, reserved))
            if not items:
                return
            try:
                raylet = await self._raylet_client(node_id)
                reply = await raylet.call(
                    "LeaseWorkersForActors", {"items": items},
                    # margin over the raylet's own per-item startup wait:
                    # if one slow fork hits that limit, the raylet must get
                    # to report the siblings it DID lease, or their grants
                    # and __init__ side effects would leak/duplicate
                    timeout=RTPU_CONFIG.worker_startup_timeout_s + 30.0,
                )
                results = reply["results"]
            except Exception as e:
                logger.warning("actor lease batch on %s failed: %s",
                               node_id.hex(), e)
                results = [{"granted": False}] * len(recs)
            for (actor_id, rec, reserved), res in zip(recs, results):
                self._release_inflight(node_id, reserved)
                done = await self._apply_lease_reply(actor_id, rec, node_id, res)
                if not done and self.actors.get(actor_id, {}).get("state") in (
                    PENDING_CREATION, RESTARTING,
                ):
                    self.pending_actor_queue.append(actor_id)

    async def _schedule_one_actor(self, actor_id: bytes):
        async with self._actor_create_sem:
            rec = self.actors.get(actor_id)
            if rec is None or rec["state"] not in (PENDING_CREATION, RESTARTING):
                return
            ok = await self._try_create_actor(actor_id, rec)
            if not ok and self.actors.get(actor_id, {}).get("state") in (
                PENDING_CREATION,
                RESTARTING,
            ):
                self.pending_actor_queue.append(actor_id)

    async def _try_create_actor(self, actor_id: bytes, rec: dict) -> bool:
        spec = rec["creation_spec"]
        strategy = spec.get("strategy", {})
        if strategy.get("type") == "placement_group":
            pg = self.placement_groups.get(strategy["pg_id"])
            if pg is None or pg["state"] != "CREATED":
                return False
            bundle = pg["bundles"][strategy.get("bundle_index") or 0]
            node_id = bundle["node_id"]
            # PG actors draw from bundle pools already reserved by the 2PC,
            # not from the node's free pool — no inflight tracking needed.
            return await self._create_actor_on(actor_id, rec, node_id)
        node_id = self._pick_node(spec["resources"], strategy)
        if node_id is None:
            return False
        infl = self._actor_inflight.setdefault(node_id, {})
        for k, v in spec["resources"].items():
            infl[k] = infl.get(k, 0.0) + v
        try:
            return await self._create_actor_on(actor_id, rec, node_id)
        finally:
            self._release_inflight(node_id, spec["resources"])

    async def _create_actor_on(self, actor_id: bytes, rec: dict,
                               node_id: bytes) -> bool:
        spec = rec["creation_spec"]
        strategy = spec.get("strategy", {})
        try:
            raylet = await self._raylet_client(node_id)
            reply = await raylet.call(
                "LeaseWorkerForActor",
                {
                    "actor_id": actor_id,
                    "job_id": spec["job_id"],
                    "resources": spec["resources"],
                    "strategy": strategy,
                    "runtime_env": spec.get("runtime_env", {}),
                    # Full creation spec: the raylet initializes the actor
                    # during worker boot and replies created=True, saving the
                    # GCS a per-actor connection + CreateActor round-trip.
                    "spec": spec,
                },
                timeout=RTPU_CONFIG.worker_startup_timeout_s + 30.0,
            )
        except Exception as e:
            logger.warning("actor lease on %s failed: %s", node_id.hex(), e)
            return False
        return await self._apply_lease_reply(actor_id, rec, node_id, reply)

    async def _apply_lease_reply(self, actor_id: bytes, rec: dict,
                                 node_id: bytes, reply: dict) -> bool:
        """Process a (possibly batched) lease reply; True = terminal state
        reached (ALIVE or DEAD), False = retry later."""
        spec = rec["creation_spec"]
        if rec["state"] == DEAD:
            # kill() landed while the lease was in flight: don't resurrect
            # (or overwrite the kill's death_cause with a lease error) —
            # tear down any worker the raylet just granted.
            if reply.get("granted"):
                try:
                    raylet = await self._raylet_client(node_id)
                    await raylet.notify(
                        "KillWorker",
                        {"worker_id": reply["worker_id"],
                         "reason": "actor killed during creation"},
                    )
                except Exception:
                    pass
            return True
        if not reply.get("granted"):
            if reply.get("error"):
                # Deterministic failure (e.g. runtime_env setup): retrying
                # forever would hang the caller silently — kill the actor
                # with the cause instead.
                rec["state"] = DEAD
                rec["death_cause"] = reply["error"]
                self._publish_actor(actor_id, rec)
                return True
            return False
        worker_addr = tuple(reply["worker_addr"])
        worker_id = reply["worker_id"]
        if not reply.get("created"):
            # Fallback (raylet didn't create during the lease): drive
            # CreateActor over a direct connection as before.
            try:
                worker = await self.pool.get(*worker_addr)
                result = await worker.call(
                    "CreateActor", {"spec": spec, "actor_id": actor_id},
                    timeout=RTPU_CONFIG.worker_startup_timeout_s,
                )
            except Exception as e:
                logger.warning("actor creation on %s failed: %s", node_id.hex(), e)
                return False
            if not result.get("ok"):
                # Creation raised in __init__: actor is DEAD with the error
                # recorded.
                rec["state"] = DEAD
                rec["death_cause"] = result.get("error", "creation failed")
                self._publish_actor(actor_id, rec)
                return True
        rec.update(
            state=ALIVE, node_id=node_id, worker_id=worker_id, addr=list(worker_addr)
        )
        self._publish_actor(actor_id, rec)
        return True

    def _publish_actor(self, actor_id: bytes, rec: dict):
        # Every state transition flows through here: persist alongside publish.
        _fr.record("actor.state", actor_id, rec["state"])
        self._persist_actor(rec)
        msg = {
            "actor_id": actor_id,
            "state": rec["state"],
            "addr": rec["addr"],
            "num_restarts": rec["num_restarts"],
            "death_cause": rec.get("death_cause", ""),
        }
        self.pubsub.publish("actor", msg)
        self.pubsub.publish(f"actor:{actor_id.hex()}", msg)

    async def _on_actor_worker_lost(self, actor_id: bytes, reason: str):
        rec = self.actors.get(actor_id)
        if rec is None or rec["state"] == DEAD:
            return
        if rec["num_restarts"] < rec["max_restarts"] or rec["max_restarts"] < 0:
            rec["num_restarts"] += 1
            rec["state"] = RESTARTING
            rec["addr"] = None
            self._publish_actor(actor_id, rec)
            self.pending_actor_queue.append(actor_id)
            asyncio.ensure_future(self._schedule_pending_actors())
        else:
            rec["state"] = DEAD
            rec["death_cause"] = reason
            rec["addr"] = None
            self._publish_actor(actor_id, rec)

    async def handle_ReportWorkerDeath(self, req):
        """Raylet tells us a worker process exited; may host an actor."""
        actor_id = req.get("actor_id")
        # Prune the dead worker's GAUGE series: a frozen instantaneous value
        # exported forever poisons aggregations. Counters/histograms stay —
        # they are cumulative totals that remain true.
        wid = req.get("worker_id")
        if wid:
            wid_short = wid.hex()[:12] if isinstance(wid, bytes) else str(wid)[:12]
            for key, rec in list(self.user_metrics.items()):
                if (
                    rec["kind"] == "gauge"
                    and rec["labels"].get("WorkerId") == wid_short
                ):
                    del self.user_metrics[key]
        _fr.record("worker.death", req.get("worker_id") or b"",
                   req.get("reason", "")[:120])
        self._worker_failures.append(
            {"worker_id": req.get("worker_id"), "node_id": req.get("node_id"),
             "time": time.time(), "reason": req.get("reason", "")}
        )
        if actor_id:
            await self._on_actor_worker_lost(actor_id, req.get("reason", "worker died"))
        await self._reap_owned_by(req.get("worker_id"))
        return {"ok": True}

    async def _reap_owned_by(self, worker_id):
        """Ownership fate-sharing (reference: gcs_actor_manager
        OnWorkerDead → destroy owned non-detached actors; PG manager
        cleans up groups whose creator died): kill actors created by the
        dead worker and remove its placement groups."""
        if not worker_id:
            return
        for aid, rec in list(self.actors.items()):
            if (rec.get("owner_worker_id") == worker_id
                    and not rec.get("detached")
                    and rec["state"] != DEAD):
                rec["max_restarts"] = rec["num_restarts"]  # no restarts
                try:
                    await self.handle_KillActor(
                        {"actor_id": aid, "no_restart": True}
                    )
                except Exception:
                    pass
                rec["death_cause"] = "owner worker died"
        for pg_id, pg in list(self.placement_groups.items()):
            if (pg.get("owner_worker_id") == worker_id
                    and pg["state"] != "REMOVED"):
                try:
                    await self.handle_RemovePlacementGroup({"pg_id": pg_id})
                except Exception:
                    pass

    async def handle_GetActorInfo(self, req):
        rec = self.actors.get(req["actor_id"])
        if rec is None:
            return {"found": False}
        out = {k: v for k, v in rec.items() if k != "creation_spec"}
        return {"found": True, "actor": out}

    async def handle_GetActorByName(self, req):
        actor_id = self.named_actors.get((req.get("namespace") or "", req["name"]))
        if actor_id is None:
            return {"found": False}
        return await self.handle_GetActorInfo({"actor_id": actor_id})

    async def handle_ListActors(self, req):
        out = []
        limit = req.get("limit") or 0
        for rec in self.actors.values():
            out.append({k: v for k, v in rec.items() if k != "creation_spec"})
            if limit and len(out) >= limit:
                break
        return {"actors": out}

    async def handle_KillActor(self, req):
        actor_id = req["actor_id"]
        rec = self.actors.get(actor_id)
        if rec is None:
            # Batched (async) registration can arrive AFTER a kill issued
            # right behind `.remote()` on another connection. Tombstone the
            # id so the late registration lands DEAD instead of leaking a
            # live, unkillable actor.
            if req.get("no_restart", True):
                self._kill_tombstones.add(actor_id)
                while len(self._kill_tombstones) > 10_000:
                    self._kill_tombstones.pop()
            return {"ok": False}
        no_restart = req.get("no_restart", True)
        if no_restart:
            rec["max_restarts"] = rec["num_restarts"]  # exhaust restarts
        if rec.get("addr"):
            try:
                worker = await self.pool.get(*rec["addr"])
                await worker.notify("KillActor", {"actor_id": actor_id})
            except Exception:
                pass
        if no_restart:
            rec["state"] = DEAD
            rec["death_cause"] = "killed via kill()"
            name = rec.get("name")
            if name:
                self.named_actors.pop((rec.get("namespace", ""), name), None)
                self._persist("named", [rec.get("namespace", ""), name, None])
            self._publish_actor(actor_id, rec)
        return {"ok": True}

    # -------------------------------------------------------- placement groups

    async def handle_CreatePlacementGroup(self, req):
        pg_id = req["pg_id"]
        self.placement_groups[pg_id] = {
            "pg_id": pg_id,
            "name": req.get("name", ""),
            "strategy": req.get("strategy", "PACK"),
            "bundles": [
                {"index": i, "resources": dict(b), "node_id": None}
                for i, b in enumerate(req["bundles"])
            ],
            "state": "PENDING",
            "job_id": req.get("job_id"),
            "owner_worker_id": req.get("owner_worker_id"),
            "ready_event": None,
        }
        pg = self.placement_groups[pg_id]
        self._persist_pg(pg)
        # Inline first attempt of THIS group only (draining the whole
        # pending queue here would serialize unrelated stuck groups into
        # every create RPC): the ubiquitous create->ready() sequence learns
        # CREATED from this reply and skips its wait round-trip. Infeasible
        # groups fall through fast (placement returns None) and go pending.
        try:
            ok = await self._try_create_pg(pg_id, pg)
        except Exception:
            logger.exception("pg %s inline creation attempt failed",
                             pg_id.hex())
            ok = False
        if not ok and pg["state"] in ("PENDING", "RESCHEDULING"):
            self.pending_pg_queue.append(pg_id)
        return {"ok": True, "state": pg["state"]}

    def _select_pg_nodes(self, pg) -> Optional[List[bytes]]:
        """Choose a node per bundle according to the PG strategy.

        Strategies per reference common.proto:939: PACK, SPREAD, STRICT_PACK,
        STRICT_SPREAD.
        """
        strategy = pg["strategy"]
        bundles = pg["bundles"]
        nodes = {
            nid: dict(self.nodes[nid]["resources_available"])
            for nid in self.alive_nodes()
        }

        def fits(avail, res):
            return all(avail.get(k, 0) >= v for k, v in res.items())

        def take(avail, res):
            for k, v in res.items():
                avail[k] = avail.get(k, 0) - v

        if strategy == "STRICT_PACK":
            for nid, avail in sorted(nodes.items()):
                trial = dict(avail)
                if all(self._fits_take(trial, b["resources"]) for b in bundles):
                    return [nid] * len(bundles)
            return None

        placement: List[Optional[bytes]] = [None] * len(bundles)
        used_nodes: List[bytes] = []
        # Order node preference: pack→most loaded first reuse; spread→rotate.
        order = sorted(nodes.keys())
        for i, b in enumerate(bundles):
            chosen = None
            if strategy in ("SPREAD", "STRICT_SPREAD"):
                pref = [n for n in order if n not in used_nodes] + (
                    [] if strategy == "STRICT_SPREAD" else [n for n in order if n in used_nodes]
                )
            else:  # PACK: prefer already-used nodes
                pref = [n for n in order if n in used_nodes] + [
                    n for n in order if n not in used_nodes
                ]
            for nid in pref:
                if fits(nodes[nid], b["resources"]):
                    chosen = nid
                    break
            if chosen is None:
                return None
            take(nodes[chosen], b["resources"])
            placement[i] = chosen
            if chosen not in used_nodes:
                used_nodes.append(chosen)
        return placement

    @staticmethod
    def _fits_take(avail, res):
        if all(avail.get(k, 0) >= v for k, v in res.items()):
            for k, v in res.items():
                avail[k] = avail.get(k, 0) - v
            return True
        return False

    async def _schedule_pending_pgs(self):
        queue, self.pending_pg_queue = self.pending_pg_queue, []
        for pg_id in queue:
            pg = self.placement_groups.get(pg_id)
            if pg is None or pg["state"] in ("CREATED", "REMOVED"):
                continue
            try:
                ok = await self._try_create_pg(pg_id, pg)
            except Exception:
                logger.exception("pg %s creation attempt failed", pg_id.hex())
                ok = False
            if not ok and self.placement_groups.get(pg_id, {}).get("state") in (
                "PENDING",
                "RESCHEDULING",
            ):
                self.pending_pg_queue.append(pg_id)

    async def _try_create_pg(self, pg_id: bytes, pg) -> bool:
        placement = self._select_pg_nodes(pg)
        if placement is None:
            return False
        # Per-node bundle groups: one PrepareBundles + one CommitBundles RPC
        # per raylet instead of one round-trip per bundle (2PC like
        # reference gcs_placement_group_scheduler.h, batched).
        by_node: Dict[bytes, list] = {}
        for b, n in zip(pg["bundles"], placement):
            by_node.setdefault(n, []).append(b)

        # Phase 1: prepare (reserve), all nodes in parallel. A group hosted
        # entirely by one raylet commits in the same RPC (single-participant
        # 2PC degenerates to 1PC) and skips phase 2.
        one_phase = len(by_node) == 1

        async def _prepare_node(node_id, bundles):
            raylet = await self._raylet_client(node_id)
            r = await raylet.call(
                "PrepareBundles",
                {"items": [
                    {"pg_id": pg_id, "bundle_index": b["index"],
                     "resources": b["resources"]} for b in bundles
                ], "commit": one_phase},
                timeout=10,
            )
            return bool(r.get("ok"))

        node_ids = list(by_node.keys())
        results = await asyncio.gather(
            *(_prepare_node(n, by_node[n]) for n in node_ids),
            return_exceptions=True,
        )
        if not all(r is True for r in results):
            # roll back every successfully-prepared node group (a failed
            # PrepareBundles already rolled its own node back)
            async def _cancel_node(node_id, bundles):
                try:
                    raylet = await self._raylet_client(node_id)
                    for b in bundles:
                        await raylet.notify(
                            "CancelBundle",
                            {"pg_id": pg_id, "bundle_index": b["index"]},
                        )
                except Exception:
                    pass

            await asyncio.gather(*(
                _cancel_node(n, by_node[n])
                for n, r in zip(node_ids, results)
                if r is True
            ))
            return False

        if one_phase:
            for n in node_ids:
                for b in by_node[n]:
                    b["node_id"] = n
            pg["state"] = "CREATED"
            self._persist_pg(pg)
            if pg.get("ready_event") is not None:
                pg["ready_event"].set()
            self.pubsub.publish("pg", {"pg_id": pg_id, "state": "CREATED"})
            asyncio.ensure_future(self._schedule_pending_actors())
            return True

        # Phase 2: commit, in parallel. A commit failure (raylet died between
        # prepare and commit) must roll back the committed/prepared bundles
        # and report failure — NOT raise, or the whole pending queue is lost.
        async def _commit_node(node_id, bundles):
            raylet = await self._raylet_client(node_id)
            r = await raylet.call(
                "CommitBundles",
                {"items": [
                    {"pg_id": pg_id, "bundle_index": b["index"]}
                    for b in bundles
                ]},
                timeout=10,
            )
            if not r.get("ok"):
                raise RuntimeError(f"commit failed on {node_id.hex()}")
            for b in bundles:
                b["node_id"] = node_id

        commit_results = await asyncio.gather(
            *(_commit_node(n, by_node[n]) for n in node_ids),
            return_exceptions=True,
        )
        if any(isinstance(r, BaseException) for r in commit_results):
            async def _rollback(bundle, node_id):
                try:
                    raylet = await self._raylet_client(node_id)
                    # ReturnBundle releases committed state; CancelBundle
                    # covers still-only-prepared bundles. Send both —
                    # raylets treat unknown bundles as no-ops.
                    await raylet.notify(
                        "ReturnBundle",
                        {"pg_id": pg_id, "bundle_index": bundle["index"]},
                    )
                    await raylet.notify(
                        "CancelBundle",
                        {"pg_id": pg_id, "bundle_index": bundle["index"]},
                    )
                except Exception:
                    pass

            await asyncio.gather(*(
                _rollback(b, n) for b, n in zip(pg["bundles"], placement)
            ))
            for bundle in pg["bundles"]:
                bundle["node_id"] = None
            return False
        pg["state"] = "CREATED"
        self._persist_pg(pg)
        if pg.get("ready_event") is not None:
            pg["ready_event"].set()
        self.pubsub.publish("pg", {"pg_id": pg_id, "state": "CREATED"})
        # PG capacity consumed: retry pending actors that wait on it.
        asyncio.ensure_future(self._schedule_pending_actors())
        return True

    async def handle_GetPlacementGroup(self, req):
        pg = self.placement_groups.get(req["pg_id"])
        if pg is None:
            return {"found": False}
        return {"found": True, "pg": {k: v for k, v in pg.items() if k != "ready_event"}}

    async def handle_ListPlacementGroups(self, req):
        pgs = [
            {k: v for k, v in pg.items() if k != "ready_event"}
            for pg in self.placement_groups.values()
        ]
        limit = req.get("limit")
        return {"pgs": pgs[:limit] if limit else pgs}

    async def handle_WaitPlacementGroupReady(self, req):
        pg_id = req["pg_id"]
        deadline = time.time() + req.get("timeout", 60.0)
        while True:
            pg = self.placement_groups.get(pg_id)
            if pg is None or pg["state"] == "REMOVED":
                raise ValueError("placement group removed")
            if pg["state"] == "CREATED":
                return {"ready": True}
            # PENDING / RESCHEDULING: wait for the next state transition.
            # A previous creation may have left the event set (e.g. the PG
            # went CREATED -> node died -> RESCHEDULING); arm a fresh one.
            if pg.get("ready_event") is None or pg["ready_event"].is_set():
                pg["ready_event"] = asyncio.Event()
            left = deadline - time.time()
            if left <= 0:
                return {"ready": False}
            try:
                await asyncio.wait_for(pg["ready_event"].wait(), left)
            except asyncio.TimeoutError:
                return {"ready": False}

    async def handle_RemovePlacementGroup(self, req):
        pg_id = req["pg_id"]
        pg = self.placement_groups.get(pg_id)
        if pg is None:
            return {"ok": True}
        for bundle in pg["bundles"]:
            node_id = bundle.get("node_id")
            if node_id and node_id in self.nodes:
                try:
                    raylet = await self._raylet_client(node_id)
                    await raylet.notify(
                        "ReturnBundle", {"pg_id": pg_id, "bundle_index": bundle["index"]}
                    )
                except Exception:
                    pass
        pg["state"] = "REMOVED"
        self._persist_pg(pg)
        if pg.get("ready_event") is not None:
            pg["ready_event"].set()  # wake waiters; they observe REMOVED
        self.pubsub.publish("pg", {"pg_id": pg_id, "state": "REMOVED"})
        return {"ok": True}

    # -------------------------------------------------------------- task events

    async def handle_AddTaskEvents(self, req):
        self.task_events.extend(req["events"])
        overflow = len(self.task_events) - 100_000
        if overflow > 0:
            del self.task_events[:overflow]
        return {"ok": True}

    async def handle_GetTaskEvents(self, req):
        # Filters apply server-side so a large cluster ships N matching
        # events, not the whole 100k-event log sliced client-side. Stored
        # events carry job_id as hex (materialized at flush) — normalize a
        # bytes filter to that form.
        job_id = req.get("job_id")
        if isinstance(job_id, (bytes, bytearray)):
            job_id = job_id.hex()
        trace_id = req.get("trace_id")
        out = [
            e
            for e in self.task_events
            if (job_id is None or e.get("job_id") == job_id)
            and (trace_id is None or e.get("trace_id") == trace_id)
        ]
        limit = req.get("limit", 10_000)
        return {"events": out[-limit:]}

    async def handle_ListTasks(self, req):
        """Server-side fold of the task-event log into latest-state-per-task
        rows (the state API's list_tasks shape), so clients get ``limit``
        tasks over the wire instead of the whole event log.
        ``detail=False`` keeps only the identity/state fields — the fast
        path for dashboards polling task counts."""
        job_id = req.get("job_id")
        latest: Dict[str, dict] = {}
        first_ts: Dict[str, float] = {}
        for ev in self.task_events:
            if ev.get("state") == "SPAN":
                continue  # tracing spans ride the same sink, aren't tasks
            if job_id is not None and ev.get("job_id") != job_id:
                continue
            tid = ev["task_id"]
            first_ts.setdefault(tid, ev["ts"])
            cur = latest.get(tid)
            if cur is None or ev["ts"] >= cur["ts"]:
                latest[tid] = ev
        detail = req.get("detail", True)
        tasks = []
        for ev in latest.values():
            t = {
                "task_id": ev["task_id"],
                "name": ev.get("name", ""),
                "state": ev["state"],
                "job_id": ev.get("job_id", ""),
                "creation_time": first_ts[ev["task_id"]],
                "last_update_time": ev["ts"],
            }
            if detail:
                t["actor_id"] = ev.get("actor_id", "")
                t["node_id"] = ev.get("node_id", "")
                t["worker_id"] = ev.get("worker_id", "")
                t["error_message"] = ev.get("error", "")
            tasks.append(t)
        tasks.sort(key=lambda t: t["creation_time"])
        limit = req.get("limit") or 10_000
        return {"tasks": tasks[:limit], "total": len(tasks)}

    async def handle_GetWorkerFailures(self, req):
        return {"failures": self._worker_failures[-req.get("limit", 1000):]}

    # ------------------------------------------------------------ incidents

    async def handle_ReportIncident(self, req):
        """Stall-watchdog sink: an incident is a structured hang/stall
        report (kind, detail, captured stacks, flight-recorder ring tail)
        published while the problem is still live."""
        inc = dict(req.get("incident") or {})
        inc.setdefault("id", uuid.uuid4().hex[:16])
        inc.setdefault("kind", "unknown")
        inc.setdefault("time", time.time())
        inc.setdefault("status", "open")
        self.incidents.append(inc)
        if len(self.incidents) > 500:
            del self.incidents[: len(self.incidents) - 500]
        _fr.record("incident.open", b"",
                   f"{inc['kind']}: {str(inc.get('detail', ''))[:100]}")
        logger.warning("incident %s [%s] from %s: %s",
                       inc["id"], inc["kind"], inc.get("source", "?"),
                       inc.get("detail", ""))
        self.pubsub.publish("incident", {"id": inc["id"], "kind": inc["kind"]})
        return {"ok": True, "id": inc["id"]}

    async def handle_ListIncidents(self, req):
        """detail=False (default) strips the bulky stacks/ring payloads —
        the shape `ray-tpu status` and dashboards poll; `debug` passes
        detail=True for the full forensics records."""
        limit = req.get("limit") or 100
        out = self.incidents[-limit:]
        if not req.get("detail"):
            out = [
                {k: v for k, v in i.items() if k not in ("stacks", "ring")}
                for i in out
            ]
        return {
            "incidents": out,
            "open": sum(1 for i in self.incidents
                        if i.get("status") == "open"),
        }

    # ------------------------------------------------------------- metrics

    async def handle_ReportUserMetrics(self, req):
        """Workers push ray_tpu.util.metrics records with their task-event
        flush; series are keyed by (name, labels) — the reporter already
        stamped worker/job labels so series never collide across workers."""
        for rec in req.get("records", []):
            key = (rec["name"], tuple(sorted(rec.get("labels", {}).items())))
            cur = self.user_metrics.get(key)
            if cur is None:
                self.user_metrics[key] = cur = {
                    "kind": rec["kind"], "name": rec["name"],
                    "help": rec.get("help", ""), "labels": rec.get("labels", {}),
                    "value": 0.0, "buckets": {}, "count": 0, "sum": 0.0,
                    "boundaries": rec.get("boundaries") or [],
                }
            if rec["kind"] == "gauge":
                cur["value"] = rec["value"]
            elif rec["kind"] == "counter":
                cur["value"] += rec["value"]
            elif rec["kind"] == "histogram":
                for b, c in rec.get("buckets", {}).items():
                    cur["buckets"][b] = cur["buckets"].get(b, 0) + c
                cur["count"] += rec.get("count", 0)
                cur["sum"] += rec.get("sum", 0.0)
        return {"ok": True}

    async def handle_GetUserMetrics(self, req):
        """Structured read of the aggregated user-metric series (the same
        records /metrics renders) so the dashboard's /api/train and
        /api/serve can summarize workload telemetry without scraping and
        re-parsing Prometheus text. Optional name-prefix filter."""
        prefix = req.get("prefix") or ""
        out = []
        for rec in self.user_metrics.values():
            if prefix and not rec["name"].startswith(prefix):
                continue
            out.append({
                "kind": rec["kind"], "name": rec["name"],
                "labels": dict(rec["labels"]), "value": rec["value"],
                "buckets": dict(rec["buckets"]), "count": rec["count"],
                "sum": rec["sum"],
                "boundaries": list(rec.get("boundaries") or []),
            })
        return {"records": out}

    def _collect_metrics(self) -> str:
        from ray_tpu._private.metrics import render_prometheus

        samples = []

        def count_by_state(metric: str, rows):
            by_state: Dict[str, int] = {}
            for r in rows:
                by_state[r["state"]] = by_state.get(r["state"], 0) + 1
            for state, count in by_state.items():
                samples.append((metric, {"state": state}, count))

        count_by_state("ray_tpu_gcs_nodes", self.nodes.values())
        count_by_state("ray_tpu_gcs_actors", self.actors.values())
        count_by_state("ray_tpu_gcs_placement_groups", self.placement_groups.values())
        count_by_state("ray_tpu_gcs_jobs", self.jobs.values())
        samples.append(("ray_tpu_gcs_task_events_buffered", {}, len(self.task_events)))
        samples.append((
            "ray_tpu_gcs_incidents_open", {},
            sum(1 for i in self.incidents if i.get("status") == "open"),
        ))
        samples.append(("ray_tpu_gcs_uptime_seconds", {}, time.time() - self.start_time))
        # user metrics (util/metrics.py)
        for rec in self.user_metrics.values():
            if rec["kind"] == "histogram":
                cumulative = 0
                for b in rec.get("boundaries", []):
                    cumulative += rec["buckets"].get(str(b), 0)
                    samples.append(
                        (f"{rec['name']}_bucket", {**rec["labels"], "le": str(b)}, cumulative)
                    )
                # Prometheus requires le="+Inf" == count.
                samples.append(
                    (f"{rec['name']}_bucket", {**rec["labels"], "le": "+Inf"}, rec["count"])
                )
                samples.append((f"{rec['name']}_count", rec["labels"], rec["count"]))
                samples.append((f"{rec['name']}_sum", rec["labels"], rec["sum"]))
            else:
                samples.append((rec["name"], rec["labels"], rec["value"]))
        return render_prometheus(samples)

    async def handle_DumpFlightRecorder(self, req):
        """The control plane's own ring — `ray-tpu debug dump` includes it
        so a GCS-side stall (scheduling wedged, pubsub dead) is visible in
        the same archive as the data-plane rings."""
        return {"pid": os.getpid(), "events": _fr.dump(req.get("limit") or 0)}

    async def handle_StartProfile(self, req):
        """Profiling plane: the GCS samples itself alongside the raylets —
        a control-plane bottleneck (actor-creation storm, pubsub fan-out)
        shows up in the same merged timeline as the data plane."""
        from ray_tpu._private import sampling_profiler as _sp

        try:
            _sp.start_profile(
                req.get("duration", 2.0), req.get("hz", 99.0), role="gcs")
        except RuntimeError as e:
            return {"error": str(e), "pid": os.getpid()}
        return {"ok": True, "pid": os.getpid()}

    async def handle_CollectProfile(self, req):
        from ray_tpu._private import sampling_profiler as _sp

        loop = asyncio.get_running_loop()
        profile = await loop.run_in_executor(None, _sp.collect_profile)
        if profile is None:
            return {"error": "no profile capture in progress",
                    "pid": os.getpid()}
        return {"profile": profile, "pid": os.getpid()}

    async def handle_Ping(self, req):
        return {
            "ok": True,
            "uptime": time.time() - self.start_time,
            "metrics_port": getattr(self, "metrics_port", 0),
        }


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--session-dir", default="")
    parser.add_argument("--port-file", default="")
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO, stream=sys.stderr)
    from ray_tpu._private.proc_profile import maybe_enable_process_profile
    maybe_enable_process_profile("gcs")

    async def run():
        server = GcsServer(args.host, args.session_dir)
        port = await server.start(args.port)
        if args.port_file:
            tmp = args.port_file + ".tmp"
            with open(tmp, "w") as f:
                f.write(str(port))
            os.replace(tmp, args.port_file)
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":
    main()
