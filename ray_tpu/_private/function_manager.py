"""Export/import of remote functions and actor classes through the GCS KV.

Same shape as the reference's function table
(reference: python/ray/_private/function_manager.py): the driver exports
cloudpickled callables under a content-hash key; executing workers fetch once
and cache. Export happens lazily on first `.remote()` call.
"""

from __future__ import annotations

import hashlib
import threading
import weakref
from typing import Any, Dict

import cloudpickle

FN_NS = "fn"


class FunctionManager:
    def __init__(self, kv_put, kv_get):
        # kv_put(ns, key, value, overwrite) / kv_get(ns, key) are sync callables
        # wired to the GCS client by the worker.
        self._kv_put = kv_put
        self._kv_get = kv_get
        self._exported: set = set()
        self._cache: Dict[bytes, Any] = {}
        self._by_obj: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
        self._lock = threading.Lock()

    def export(self, obj: Any) -> bytes:
        """Pickle obj, store under its hash, return the key.

        Memoized per object (weak-keyed, so a driver minting fresh closures
        per submission doesn't leak memory): re-pickling the same function
        for every .remote() costs ~0.2 ms/call."""
        try:
            memo = self._by_obj.get(obj)
        except TypeError:
            memo = None  # unhashable / not weakrefable
        if memo is not None:
            return memo
        data = cloudpickle.dumps(obj)
        key = hashlib.sha1(data).digest()
        with self._lock:
            exported = key in self._exported
        if not exported:
            self._kv_put(FN_NS, key, data, False)
            with self._lock:
                self._exported.add(key)
                self._cache[key] = obj
        try:
            self._by_obj[obj] = key
        except TypeError:
            pass
        return key

    def seed(self, key: bytes, data: bytes):
        """Pre-populate the cache from a blob fetched by someone else (the
        raylet ships the actor class in the spawn message so freshly-forked
        actor workers skip the per-process KV round-trip)."""
        with self._lock:
            if key in self._cache:
                return
        obj = cloudpickle.loads(data)
        with self._lock:
            self._cache[key] = obj

    def fetch_cached(self, key: bytes) -> Any:
        """Non-blocking cache probe; None on miss (callers then fetch() off
        the io loop — the KV round-trip blocks)."""
        with self._lock:
            return self._cache.get(key)

    def fetch(self, key: bytes) -> Any:
        with self._lock:
            if key in self._cache:
                return self._cache[key]
        data = self._kv_get(FN_NS, key)
        if data is None:
            raise RuntimeError(f"function {key.hex()} not found in GCS function table")
        obj = cloudpickle.loads(data)
        with self._lock:
            self._cache[key] = obj
        return obj
