"""Task timeline: Chrome-trace dump of the GCS task-event log.

Counterpart of ``ray timeline`` (reference: python/ray/_private/state.py:944
chrome_tracing_dump :434 — task state transitions buffered by every core
worker, flushed to the GCS task-event sink, rendered as Chrome's trace-event
JSON). Open the output in chrome://tracing or https://ui.perfetto.dev.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

_TERMINAL = ("FINISHED", "FAILED")


def chrome_trace_events(events: List[dict]) -> List[dict]:
    """Fold raw task events into Chrome 'X' (complete) + 'i' (instant) events."""
    by_task: Dict[str, List[dict]] = {}
    for ev in events:
        by_task.setdefault(ev["task_id"], []).append(ev)
    out: List[dict] = []
    for task_id, evs in by_task.items():
        evs.sort(key=lambda e: e["ts"])
        running_ev = None
        for ev in evs:
            if ev["state"] == "SPAN":
                # User/tracing span (ray_tpu.util.tracing) — duration baked in.
                out.append(
                    {
                        "cat": "span",
                        "name": ev.get("name") or "span",
                        "ph": "X",
                        "ts": ev["ts"] * 1e6,
                        "dur": max(0.0, ev.get("dur", 0.0) * 1e6),
                        "pid": f"node:{(ev.get('node_id') or '?')[:8]}",
                        "tid": f"worker:{(ev.get('worker_id') or '?')[:8]}",
                        "args": {
                            "trace_id": ev.get("trace_id", ""),
                            "span_id": ev.get("task_id", ""),
                            "parent_span_id": ev.get("parent_span_id", ""),
                            **(ev.get("attributes") or {}),
                            "error": ev.get("error", ""),
                        },
                    }
                )
                continue
            if ev["state"] == "RUNNING":
                running_ev = ev
            elif ev["state"] in _TERMINAL and running_ev is None:
                # Terminal event whose RUNNING was dropped (task-event ring
                # overflow / flush loss, or a path that never emits RUNNING,
                # e.g. async-actor tasks): without a start there is no 'X'
                # duration to draw — emit an instant so the task is still
                # visible in the trace instead of silently vanishing.
                out.append(
                    {
                        "cat": "task",
                        "name": f"{ev.get('name') or task_id[:8]}:{ev['state']}",
                        "ph": "i",
                        "s": "t",
                        "ts": ev["ts"] * 1e6,
                        "pid": f"node:{(ev.get('node_id') or '?')[:8]}",
                        "tid": f"worker:{(ev.get('worker_id') or '?')[:8]}",
                        "args": {
                            "task_id": task_id,
                            "job_id": ev.get("job_id", ""),
                            "state": ev["state"],
                            "error": ev.get("error", ""),
                            "note": "RUNNING event missing (dropped or never emitted)",
                        },
                    }
                )
            elif ev["state"] in _TERMINAL and running_ev is not None:
                out.append(
                    {
                        "cat": "task",
                        "name": ev.get("name") or task_id[:8],
                        "ph": "X",
                        "ts": running_ev["ts"] * 1e6,
                        "dur": max(0.0, (ev["ts"] - running_ev["ts"]) * 1e6),
                        "pid": f"node:{(ev.get('node_id') or '?')[:8]}",
                        "tid": f"worker:{(ev.get('worker_id') or '?')[:8]}",
                        "args": {
                            "task_id": task_id,
                            "job_id": ev.get("job_id", ""),
                            "state": ev["state"],
                            "error": ev.get("error", ""),
                        },
                        "cname": (
                            "thread_state_runnable"
                            if ev["state"] == "FINISHED"
                            else "terrible"
                        ),
                    }
                )
                running_ev = None
            elif ev["state"] in ("SUBMITTED", "RETRY"):
                out.append(
                    {
                        "cat": "task",
                        "name": f"{ev.get('name') or task_id[:8]}:{ev['state']}",
                        "ph": "i",
                        "s": "t",
                        "ts": ev["ts"] * 1e6,
                        "pid": f"node:{(ev.get('node_id') or '?')[:8]}",
                        "tid": f"worker:{(ev.get('worker_id') or '?')[:8]}",
                    }
                )
    out.sort(key=lambda e: e["ts"])
    return out


def timeline(filename: Optional[str] = None):
    """Dump the cluster's task timeline; returns the event list (and writes
    Chrome-trace JSON to ``filename`` if given)."""
    from ray_tpu._private import worker as worker_mod

    if worker_mod.global_worker is None:
        raise RuntimeError("ray_tpu is not initialized")
    raw = worker_mod.global_worker.gcs.call("GetTaskEvents", {"limit": 100_000})[
        "events"
    ]
    events = chrome_trace_events(raw)
    if filename:
        with open(filename, "w") as f:
            json.dump(events, f)
    return events
