"""Task timeline + merged profiling view: Chrome-trace dumps of the GCS
task-event log, optionally folded with cluster CPU-sample captures.

Counterpart of ``ray timeline`` (reference: python/ray/_private/state.py:944
chrome_tracing_dump :434 — task state transitions buffered by every core
worker, flushed to the GCS task-event sink, rendered as Chrome's trace-event
JSON). Open the output in chrome://tracing or https://ui.perfetto.dev.

This module is also the merge point of the profiling plane
(``merged_profile_trace``): CPU samples from every process
(_private/sampling_profiler.py via the StartProfile/CollectProfile fan-out),
task state transitions, tracing spans, and registered JAX device-trace
directories all land in ONE time-aligned Chrome trace — every timestamp in
every lane is wall-clock ``time.time()`` microseconds, so "the input
pipeline stalled while the collective waited" is visible as adjacent lanes
of the same Perfetto view.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

_TERMINAL = ("FINISHED", "FAILED")


def chrome_trace_events(events: List[dict]) -> List[dict]:
    """Fold raw task events into Chrome 'X' (complete) + 'i' (instant) events."""
    by_task: Dict[str, List[dict]] = {}
    for ev in events:
        by_task.setdefault(ev["task_id"], []).append(ev)
    out: List[dict] = []
    for task_id, evs in by_task.items():
        evs.sort(key=lambda e: e["ts"])
        running_ev = None
        submitted_ev = None
        for ev in evs:
            if ev["state"] == "SPAN":
                # User/tracing span (ray_tpu.util.tracing) — duration baked in.
                out.append(
                    {
                        "cat": "span",
                        "name": ev.get("name") or "span",
                        "ph": "X",
                        "ts": ev["ts"] * 1e6,
                        "dur": max(0.0, ev.get("dur", 0.0) * 1e6),
                        "pid": f"node:{(ev.get('node_id') or '?')[:8]}",
                        "tid": f"worker:{(ev.get('worker_id') or '?')[:8]}",
                        "args": {
                            "trace_id": ev.get("trace_id", ""),
                            "span_id": ev.get("task_id", ""),
                            "parent_span_id": ev.get("parent_span_id", ""),
                            **(ev.get("attributes") or {}),
                            "error": ev.get("error", ""),
                        },
                    }
                )
                continue
            if ev["state"] == "RUNNING":
                running_ev = ev
            elif ev["state"] in _TERMINAL and running_ev is None:
                # Terminal event whose RUNNING was dropped (task-event ring
                # overflow / flush loss, or a path that never emits RUNNING,
                # e.g. async-actor tasks): without a start there is no 'X'
                # duration to draw — emit an instant so the task is still
                # visible in the trace instead of silently vanishing.
                out.append(
                    {
                        "cat": "task",
                        "name": f"{ev.get('name') or task_id[:8]}:{ev['state']}",
                        "ph": "i",
                        "s": "t",
                        "ts": ev["ts"] * 1e6,
                        "pid": f"node:{(ev.get('node_id') or '?')[:8]}",
                        "tid": f"worker:{(ev.get('worker_id') or '?')[:8]}",
                        "args": {
                            "task_id": task_id,
                            "job_id": ev.get("job_id", ""),
                            "state": ev["state"],
                            "error": ev.get("error", ""),
                            "note": "RUNNING event missing (dropped or never emitted)",
                        },
                    }
                )
            elif ev["state"] in _TERMINAL and running_ev is not None:
                out.append(
                    {
                        "cat": "task",
                        "name": ev.get("name") or task_id[:8],
                        "ph": "X",
                        "ts": running_ev["ts"] * 1e6,
                        "dur": max(0.0, (ev["ts"] - running_ev["ts"]) * 1e6),
                        "pid": f"node:{(ev.get('node_id') or '?')[:8]}",
                        "tid": f"worker:{(ev.get('worker_id') or '?')[:8]}",
                        "args": {
                            "task_id": task_id,
                            "job_id": ev.get("job_id", ""),
                            "state": ev["state"],
                            "error": ev.get("error", ""),
                        },
                        "cname": (
                            "thread_state_runnable"
                            if ev["state"] == "FINISHED"
                            else "terrible"
                        ),
                    }
                )
                if submitted_ev is not None:
                    # Causality arrow: Chrome flow events connect the
                    # SUBMITTED instant (submitter's lane) to the start of
                    # the RUNNING slice (executing worker's lane) — in
                    # Perfetto the scheduling delay is a drawn edge instead
                    # of two unconnected marks.
                    flow = {
                        "cat": "task_flow",
                        "name": "submit",
                        "id": task_id,
                    }
                    out.append({
                        **flow,
                        "ph": "s",
                        "ts": submitted_ev["ts"] * 1e6,
                        "pid": f"node:{(submitted_ev.get('node_id') or '?')[:8]}",
                        "tid": f"worker:{(submitted_ev.get('worker_id') or '?')[:8]}",
                    })
                    out.append({
                        **flow,
                        "ph": "f",
                        "bp": "e",  # bind to the enclosing RUNNING slice
                        "ts": running_ev["ts"] * 1e6,
                        "pid": f"node:{(ev.get('node_id') or '?')[:8]}",
                        "tid": f"worker:{(ev.get('worker_id') or '?')[:8]}",
                    })
                    submitted_ev = None
                running_ev = None
            elif ev["state"] in ("SUBMITTED", "RETRY"):
                if ev["state"] == "SUBMITTED":
                    submitted_ev = ev
                out.append(
                    {
                        "cat": "task",
                        "name": f"{ev.get('name') or task_id[:8]}:{ev['state']}",
                        "ph": "i",
                        "s": "t",
                        "ts": ev["ts"] * 1e6,
                        "pid": f"node:{(ev.get('node_id') or '?')[:8]}",
                        "tid": f"worker:{(ev.get('worker_id') or '?')[:8]}",
                    }
                )
    out.sort(key=lambda e: e["ts"])
    return out


_FLIGHT_INSTANTS = {
    "obj.spill": "spill",
    "obj.restore": "restore",
    "obj.leak": "leak",
}


def flight_instant_events(node_hex: str, events: List[dict]) -> List[dict]:
    """Render a raylet flight-recorder ring's object-plane events
    (``obj.spill`` / ``obj.restore`` / ``obj.leak``) as Chrome instants on
    the owning node's lane — recorded since PR 3 but invisible in
    ``ray-tpu timeline`` until now. ``events`` is the formatted dump
    (flight_recorder.dump / DumpFlightRecorder reply)."""
    out: List[dict] = []
    for ev in events:
        name = _FLIGHT_INSTANTS.get(ev.get("event", ""))
        if name is None:
            continue
        oid = ev.get("a", "")
        out.append({
            "cat": "object_store",
            "name": f"obj.{name}",
            "ph": "i",
            "s": "t",
            "ts": float(ev.get("ts", 0.0)) * 1e6,
            "pid": f"node:{(node_hex or '?')[:8]}",
            "tid": "object_store",
            "args": {
                "object_id": oid if isinstance(oid, str) else str(oid),
                "bytes": ev.get("b", ""),
                "event": ev.get("event", ""),
            },
        })
    return out


# ------------------------------------------------ profiling-plane merging


def profile_trace_events(bundle: dict, *, max_events: int = 300_000) -> List[dict]:
    """Render a cluster profile bundle (profiling.capture_cluster_profile)
    as Chrome slices: one ``cpu:`` lane per sampled thread, consecutive
    samples of the same stack collapsed into one slice. Lane pids reuse the
    task timeline's ``node:<id8>`` grouping so CPU time and task execution
    for a node sit under one Perfetto process group."""
    out: List[dict] = []

    def _one_profile(profile: dict, node_hex: str):
        period = 1.0 / max(1.0, float(profile.get("hz") or 99.0))
        t0 = float(profile.get("t0") or 0.0)
        threads = profile.get("threads", [])
        stacks = profile.get("stacks", [])
        role = profile.get("role") or "proc"
        pid_lane = f"node:{node_hex[:8]}" if node_hex else "node:?"
        proc = f"{role}:{profile.get('pid', 0)}"
        # group samples per thread, preserving time order
        by_thread: Dict[int, List[list]] = {}
        for s in profile.get("samples", []):
            by_thread.setdefault(s[1], []).append(s)
        for ti, samples in by_thread.items():
            tname = threads[ti] if 0 <= ti < len(threads) else str(ti)
            tid_lane = f"cpu:{proc}:{tname}"
            samples.sort(key=lambda s: s[0])
            run_start = run_end = None
            run_stack = -1
            run_n = 0

            def _emit():
                if run_stack < 0 or run_n == 0:
                    return
                stack = (stacks[run_stack]
                         if 0 <= run_stack < len(stacks) else "?")
                leaf = stack.rsplit(";", 1)[-1]
                out.append({
                    "cat": "cpu_sample",
                    "name": leaf,
                    "ph": "X",
                    "ts": (t0 + run_start) * 1e6,
                    "dur": max(period, run_end - run_start + period) * 1e6,
                    "pid": pid_lane,
                    "tid": tid_lane,
                    "args": {"stack": stack, "samples": run_n,
                             "process": proc},
                })

            for dt, _ti, si in samples:
                if si == run_stack and dt - run_end <= 2.5 * period:
                    run_end = dt
                    run_n += 1
                    continue
                _emit()
                run_start = run_end = dt
                run_stack = si
                run_n = 1
            _emit()

    for node in bundle.get("nodes", []):
        for p in node.get("profiles", []):
            _one_profile(p, node.get("node_id", ""))
    for p in bundle.get("drivers", []):
        _one_profile(p, "driver")
    if bundle.get("gcs"):
        _one_profile(bundle["gcs"], "gcs")
    if len(out) > max_events:
        del out[max_events:]
    return out


def merged_profile_trace(bundle: dict, task_events: Optional[List[dict]] = None,
                         device_traces: Optional[List[dict]] = None) -> dict:
    """ONE Perfetto-loadable object: cluster CPU samples + task/span events
    + links to registered JAX device-trace directories, all on the shared
    wall-clock microsecond axis. The return shape is the Chrome trace
    "object format" ({"traceEvents": [...]}), which both chrome://tracing
    and ui.perfetto.dev accept."""
    events = chrome_trace_events(task_events or [])
    events += profile_trace_events(bundle)
    for dt in device_traces or []:
        # The device trace itself is a TensorBoard/XPlane directory — too
        # alien to inline, so mark WHEN it was captured and WHERE it lives;
        # open it with `tensorboard --logdir` / xprof for the device view.
        events.append({
            "cat": "device_trace",
            "name": "jax_device_trace",
            "ph": "i",
            "s": "g",
            "ts": float(dt.get("time", 0.0)) * 1e6,
            "pid": "device_traces",
            "tid": dt.get("host", "") or "host",
            "args": {"path": dt.get("path", ""),
                     "steps": dt.get("steps", 0)},
        })
    events.sort(key=lambda e: e["ts"])
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {
            "clock": "wall (time.time), microseconds",
            "capture_t0": bundle.get("t0"),
            "capture_duration_s": bundle.get("duration"),
            "capture_hz": bundle.get("hz"),
            "errors": bundle.get("errors", []),
            "device_traces": [
                {"path": d.get("path", ""), "steps": d.get("steps", 0)}
                for d in device_traces or []
            ],
        },
    }


def timeline(filename: Optional[str] = None, *,
             job_id: Optional[str] = None, trace_id: Optional[str] = None):
    """Dump the cluster's task timeline; returns the event list (and writes
    Chrome-trace JSON to ``filename`` if given). ``job_id`` (hex) and
    ``trace_id`` filter server-side — a large cluster ships one job's
    events, not the whole 100k-event log."""
    from ray_tpu._private import worker as worker_mod

    if worker_mod.global_worker is None:
        raise RuntimeError("ray_tpu is not initialized")
    req: dict = {"limit": 100_000}
    if job_id is not None:
        req["job_id"] = job_id
    if trace_id is not None:
        req["trace_id"] = trace_id
    raw = worker_mod.global_worker.gcs.call("GetTaskEvents", req)["events"]
    events = chrome_trace_events(raw)
    if filename:
        with open(filename, "w") as f:
            json.dump(events, f)
    return events
