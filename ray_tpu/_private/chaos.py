"""Deterministic fault injection: the chaos plane.

The observability stack (incidents, leak sweeps, perf gates) explains
failures after the fact; this module CAUSES them on purpose so the whole
robustness story — failover, admission control, autoscaler reconvergence,
KV/plasma leak freedom — can be asserted end-to-end in a repeatable test
(reference analogues: the reference's nightly chaos suites +
test_utils.py RayletKiller; Jepsen-style fault schedules, but seeded and
replayable).

Named **injection sites** are threaded through the hot seams of the
runtime; each is a plain ``hit(site, **attrs)`` call guarded by the
module-level ``ARMED`` flag, so with no plan loaded the per-call cost is
one module attribute read (the tier-1 perf gate keeps this honest: with
``RTPU_chaos_plan`` unset the microbench rows must stay in-band).

SITE-NAME STABILITY CONTRACT
----------------------------
Like the flight-recorder event names, the site names are a public
debugging/testing surface — chaos plans in CI and operator runbooks key
on them. Renaming one is a breaking change; add new sites instead.

  rpc.send          client side, before a request frame is written
                    (attrs: method). drop = never send (caller times
                    out), delay, dup = send the frame twice
  rpc.recv          server side, before dispatch (attrs: method).
                    drop = swallow the request, delay, dup = dispatch
                    twice (exercises receiver idempotence)
  raylet.spawn      worker-pool spawn path (attrs: job). fail = the
                    spawn raises, delay
  raylet.heartbeat  the raylet's GCS heartbeat loop (attrs: node).
                    drop = skip one beat, delay
  plasma.write      worker plasma put path. error = the put raises,
                    delay
  replica.step      after each PRODUCTIVE serve.llm engine step
                    (attrs: deployment, replica). kill = SIGKILL the
                    replica process, hang = stall the step loop for
                    delay_s, error = raise in the step loop

THE PLAN
--------
A plan is JSON — ``{"seed": s, "rules": [...]}`` or a bare rule list —
set via the ``RTPU_chaos_plan`` env var or published to GCS KV
(namespace ``chaos``, key ``plan``). Drivers publish their env plan at
``init`` and raylets/workers load it when they join, so the whole
cluster replays ONE schedule. Each rule:

    {"site": "replica.step",    # required: a site name above
     "action": "kill",          # required: see the site's actions
     "after_n": 50,             # skip the first N matching hits
                                # (alias: after_steps)
     "every_n": 0,              # 0 = fire once; k = fire on every k-th
                                # eligible hit
     "count": 1,                # max fires per process (0 = unlimited)
     "prob": 1.0,               # fire probability per eligible hit,
                                # drawn from the rule's seeded RNG
     "delay_s": 0.05,           # duration for delay / hang actions
     <attr>: "value"}           # any other key must match the site's
                                # attrs: exact string, fnmatch pattern,
                                # or a list of either

Determinism: rule state (hit counters, RNG) lives per process and every
random draw comes from ``random.Random(seed * 1000003 + rule_index)``,
so the same plan against the same workload replays the same injection
schedule. Every fired injection emits a ``chaos.inject`` flight event
and bumps ``ray_tpu_chaos_injections_total`` (labels: site, action) —
tests assert *exactly-one attributed incident per induced fault* by
joining those against the GCS incident table.
"""

from __future__ import annotations

import json
import random
import threading
from fnmatch import fnmatchcase
from typing import Any, Dict, List, Optional

__all__ = ["ARMED", "hit", "load_plan", "clear", "sync_with_gcs",
           "injections_total"]

# The hot-seam guard: seams check `chaos.ARMED` before calling hit(), so a
# disarmed process pays one module attribute read per site.
ARMED = False

_KV_NS = b"chaos"
_KV_KEY = b"plan"

_lock = threading.Lock()
_sites: Dict[str, List["_Rule"]] = {}
_injections = 0

_CONTROL_KEYS = {"site", "action", "after_n", "after_steps", "every_n",
                 "count", "prob", "delay_s", "seed"}


class _Rule:
    __slots__ = ("site", "action", "match", "after_n", "every_n", "count",
                 "prob", "delay_s", "rng", "hits", "fired", "index")

    def __init__(self, spec: dict, index: int, seed: int):
        self.site = str(spec["site"])
        self.action = str(spec["action"])
        self.after_n = int(spec.get("after_n", spec.get("after_steps", 0)))
        self.every_n = int(spec.get("every_n", 0))
        self.count = int(spec.get("count", 1))
        self.prob = float(spec.get("prob", 1.0))
        self.delay_s = float(spec.get("delay_s", 0.05))
        self.match = {k: v for k, v in spec.items()
                      if k not in _CONTROL_KEYS}
        # per-rule seeded RNG: the prob draws replay identically run to run
        self.rng = random.Random(int(spec.get("seed", seed)) * 1000003
                                 + index)
        self.index = index
        self.hits = 0
        self.fired = 0

    def _matches(self, attrs: dict) -> bool:
        for key, want in self.match.items():
            got = attrs.get(key)
            if got is None:
                return False
            got = str(got)
            opts = want if isinstance(want, (list, tuple)) else [want]
            if not any(fnmatchcase(got, str(o)) for o in opts):
                return False
        return True

    def check(self, attrs: dict) -> Optional[dict]:
        """One site hit against this rule; returns the action dict when
        the rule fires. Counters/RNG advance under the module lock so the
        schedule is deterministic even with concurrent hitters."""
        if not self._matches(attrs):
            return None
        self.hits += 1
        if self.hits <= self.after_n:
            return None
        if self.count and self.fired >= self.count:
            return None
        eligible = self.hits - self.after_n
        if self.every_n > 0:
            if eligible % self.every_n != 0:
                return None
        elif self.fired:
            # every_n == 0: a one-shot trigger point (still capped by
            # count, so count>1 re-fires on consecutive hits)
            pass
        if self.prob < 1.0 and self.rng.random() >= self.prob:
            return None
        self.fired += 1
        return {"action": self.action, "delay_s": self.delay_s,
                "rule": self.index}


def hit(site: str, **attrs) -> Optional[dict]:
    """One pass of an injection site. Returns ``None`` (no fault) or the
    fired rule's action dict ``{"action", "delay_s", "rule"}``. The SEAM
    interprets the action — this function only decides, records the
    ``chaos.inject`` flight event, and bumps the counter."""
    rules = _sites.get(site)
    if not rules:
        return None
    with _lock:
        act = None
        for r in rules:
            act = r.check(attrs)
            if act is not None:
                break
    if act is None:
        return None
    _emit(site, act, attrs)
    return act


def _emit(site: str, act: dict, attrs: dict):
    global _injections
    _injections += 1
    detail = " ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
    try:
        from ray_tpu._private import flight_recorder as _fr

        _fr.record("chaos.inject", b"",
                   f"{site} {act['action']} rule={act['rule']} {detail}")
    except Exception:
        pass
    try:
        _metric().inc(1, tags={"site": site, "action": act["action"]})
    except Exception:
        pass


_counter = None


def _metric():
    global _counter
    if _counter is None:
        from ray_tpu.util.metrics import Counter

        _counter = Counter(
            "ray_tpu_chaos_injections_total",
            "faults fired by the chaos plane", tag_keys=("site", "action"))
    return _counter


def injections_total() -> int:
    """Faults fired by THIS process since the plan loaded."""
    return _injections


def load_plan(plan: Any) -> int:
    """Arm this process with ``plan`` (dict, rule list, JSON str/bytes).
    Replaces any previous plan and resets all rule state; returns the
    number of rules loaded. An empty/falsy plan disarms."""
    global ARMED, _sites, _injections
    if isinstance(plan, (bytes, bytearray)):
        plan = bytes(plan).decode("utf-8")
    if isinstance(plan, str):
        plan = json.loads(plan) if plan.strip() else None
    if isinstance(plan, dict):
        seed = int(plan.get("seed", 0))
        specs = plan.get("rules") or []
    else:
        seed = 0
        specs = plan or []
    sites: Dict[str, List[_Rule]] = {}
    for i, spec in enumerate(specs):
        rule = _Rule(spec, i, seed)
        sites.setdefault(rule.site, []).append(rule)
    with _lock:
        _sites = sites
        _injections = 0
        ARMED = bool(sites)
    return sum(len(v) for v in sites.values())


def clear():
    """Disarm: all sites become no-ops again."""
    load_plan(None)


def sync_with_gcs(gcs, publish: bool = False) -> bool:
    """Arm from ``RTPU_chaos_plan`` or, failing that, from the plan
    published in GCS KV. With ``publish`` (drivers at init), an env plan
    is ALSO written to the KV so every process that joins later — raylet,
    fork-server worker, another driver — replays the same schedule.
    Returns True when a plan was armed."""
    from ray_tpu._private.config import RTPU_CONFIG

    env_plan = RTPU_CONFIG.chaos_plan
    if env_plan:
        load_plan(env_plan)
        if publish:
            try:
                gcs.kv_put(_KV_NS, _KV_KEY, env_plan.encode("utf-8"))
            except Exception:
                pass
        return ARMED
    try:
        value = gcs.kv_get(_KV_NS, _KV_KEY)
    except Exception:
        return False
    if value:
        load_plan(value)
    return ARMED
