"""In-process memory store for inline objects owned by this worker.

Counterpart of the reference's CoreWorkerMemoryStore
(reference: src/ray/core_worker/store_provider/memory_store/memory_store.h):
small task returns and pending-object placeholders live here; `get` waiters
block on per-object asyncio events on the worker's IO loop.
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, Optional

from ray_tpu._private.ids import ObjectID


class _Pending:
    __slots__ = ("event",)

    def __init__(self):
        self.event = asyncio.Event()


class InPlasma:
    """Placeholder value: the object's data lives in plasma, not in memory."""

    __slots__ = ("size", "locations")

    def __init__(self, size: int, locations=None):
        self.size = size
        # set of node_id bytes where a copy exists (owner-maintained directory)
        self.locations = set(locations or [])


class MemoryStore:
    """Must only be touched from the IO loop thread."""

    def __init__(self):
        self._store: Dict[ObjectID, Any] = {}
        self._pending: Dict[ObjectID, _Pending] = {}

    def put_pending(self, object_id: ObjectID):
        if object_id not in self._store and object_id not in self._pending:
            self._pending[object_id] = _Pending()

    def put(self, object_id: ObjectID, value: Any):
        self._store[object_id] = value
        p = self._pending.pop(object_id, None)
        if p is not None:
            p.event.set()

    def contains(self, object_id: ObjectID) -> bool:
        return object_id in self._store

    def get_if_exists(self, object_id: ObjectID):
        return self._store.get(object_id)

    def is_pending(self, object_id: ObjectID) -> bool:
        return object_id in self._pending

    async def wait_ready(self, object_id: ObjectID, timeout: Optional[float] = None):
        """Wait until a value (or plasma placeholder) is set. Returns True if ready."""
        if object_id in self._store:
            return True
        p = self._pending.get(object_id)
        if p is None:
            # Not pending and not present: either never created here or already freed.
            return object_id in self._store
        try:
            await asyncio.wait_for(p.event.wait(), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    def free(self, object_id: ObjectID):
        self._store.pop(object_id, None)
        p = self._pending.pop(object_id, None)
        if p is not None:
            p.event.set()

    def fail_pending(self, object_id: ObjectID, error: Exception):
        """Resolve a pending object to an error value (task failure, etc.)."""
        self.put(object_id, error)

    def size(self) -> int:
        return len(self._store)
