"""In-process memory store for inline objects owned by this worker.

Counterpart of the reference's CoreWorkerMemoryStore
(reference: src/ray/core_worker/store_provider/memory_store/memory_store.h):
small task returns and pending-object placeholders live here; `get` waiters
block on per-object asyncio events on the worker's IO loop.
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, Optional

from ray_tpu._private.ids import ObjectID


class _Pending:
    """Placeholder for an expected value. The event is lazy (most objects
    are put before anyone waits) and batch waiters let a 1000-ref get()
    block on ONE event instead of 1000 (each wait_for costs a Task + timer
    on the loop)."""

    __slots__ = ("event", "waiters")

    def __init__(self):
        self.event = None
        self.waiters = None

    def resolve(self):
        if self.event is not None:
            self.event.set()
        if self.waiters:
            for w in self.waiters:
                w.remaining -= 1
                if w.remaining <= 0:
                    w.event.set()
            self.waiters = None


class _BatchWaiter:
    __slots__ = ("remaining", "event")

    def __init__(self):
        self.remaining = 0
        self.event = asyncio.Event()


class InPlasma:
    """Placeholder value: the object's data lives in plasma, not in memory."""

    __slots__ = ("size", "locations")

    def __init__(self, size: int, locations=None):
        self.size = size
        # set of node_id bytes where a copy exists (owner-maintained directory)
        self.locations = set(locations or [])


class MemoryStore:
    """Must only be touched from the IO loop thread."""

    def __init__(self):
        self._store: Dict[ObjectID, Any] = {}
        self._pending: Dict[ObjectID, _Pending] = {}
        # io-loop callback fired when an object becomes available — the
        # core worker's dependency-gated task dispatch hangs off it
        # (reference: task_dependency_manager notifying the scheduler)
        self.on_ready = None

    def put_pending(self, object_id: ObjectID):
        if object_id not in self._store and object_id not in self._pending:
            self._pending[object_id] = _Pending()

    def put(self, object_id: ObjectID, value: Any):
        self._store[object_id] = value
        p = self._pending.pop(object_id, None)
        if p is not None:
            p.resolve()
        if self.on_ready is not None:
            self.on_ready(object_id)

    def contains(self, object_id: ObjectID) -> bool:
        return object_id in self._store

    def get_if_exists(self, object_id: ObjectID):
        return self._store.get(object_id)

    def is_pending(self, object_id: ObjectID) -> bool:
        return object_id in self._pending

    async def wait_ready(self, object_id: ObjectID, timeout: Optional[float] = None):
        """Wait until a value (or plasma placeholder) is set. Returns True if ready."""
        if object_id in self._store:
            return True
        p = self._pending.get(object_id)
        if p is None:
            # Not pending and not present: either never created here or already freed.
            return object_id in self._store
        if p.event is None:
            p.event = asyncio.Event()
        if timeout is None:
            await p.event.wait()
            return True
        try:
            await asyncio.wait_for(p.event.wait(), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def wait_ready_many(self, object_ids, timeout: Optional[float] = None) -> bool:
        """Wait until ALL given objects resolve (value, placeholder, or
        free). One event for the whole batch. False on timeout."""
        w = _BatchWaiter()
        registered = []
        for oid in object_ids:
            if oid in self._store:
                continue
            p = self._pending.get(oid)
            if p is None:
                continue
            if p.waiters is None:
                p.waiters = []
            p.waiters.append(w)
            registered.append(p)
            w.remaining += 1
        if w.remaining <= 0:
            return True
        if timeout is None:
            await w.event.wait()
            return True
        try:
            await asyncio.wait_for(w.event.wait(), timeout)
            return True
        except asyncio.TimeoutError:
            # Deregister, or a get()-with-timeout polling loop accumulates
            # a stale waiter per call on every still-pending object.
            for p in registered:
                if p.waiters is not None:
                    try:
                        p.waiters.remove(w)
                    except ValueError:
                        pass
            return False

    def free(self, object_id: ObjectID):
        self._store.pop(object_id, None)
        p = self._pending.pop(object_id, None)
        if p is not None:
            p.resolve()

    def fail_pending(self, object_id: ObjectID, error: Exception):
        """Resolve a pending object to an error value (task failure, etc.)."""
        self.put(object_id, error)

    def size(self) -> int:
        return len(self._store)
