"""Asyncio msgpack-framed RPC used by every control-plane connection.

The reference uses gRPC for all inter-process control traffic
(reference: src/ray/rpc/grpc_server.h, grpc_client.h). We use a leaner
length-prefixed msgpack protocol over asyncio TCP: one persistent duplex
connection per (client, server) pair, request/response multiplexed by sequence
number, plus fire-and-forget notifications. This keeps per-call overhead low
(single syscall write of one small frame) which matters for the task
throughput benchmarks, and avoids protoc codegen for every service.

Frame layout: 4-byte little-endian length, then msgpack array:
    [MSG_REQUEST,  seq, method: str, payload]
    [MSG_RESPONSE, seq, None,        payload]
    [MSG_ERROR,    seq, traceback: str, exc: bytes(cloudpickle)]
    [MSG_NOTIFY,   0,   method: str, payload]

Out-of-band frames (the bulk-data plane): a 5th element carries the byte
length of a RAW binary payload appended immediately AFTER the msgpack body
— the length prefix covers only the msgpack header, writev-style:
    [u32 len(header)][msgpack [MSG_REQUEST_OOB,  seq, method, payload, n]][n raw bytes]
    [u32 len(header)][msgpack [MSG_RESPONSE_OOB, seq, None,   payload, n]][n raw bytes]
The bulk bytes are never msgpack-encoded: the sender writes the caller's
buffer view (e.g. a plasma slice) directly after the header — zero copies
on the send side — and the receiver lands the payload at its final
destination in bounded pieces via a per-method sink (RpcServer.set_oob_sink)
or a caller-provided buffer (RpcClient.call(oob_dest=...)), so a 4 MiB
transfer chunk is never materialized as one Python bytes object. Handlers
see the landed payload as payload["_oob"]: an int byte-count when a sink /
oob_dest absorbed it in place, else a bytearray holding the raw bytes.
Handlers reply out-of-band by returning an OobPayload.

Threading model — the sharded reactor
--------------------------------------
Every process owns a single background IO thread running one asyncio loop
(mirroring the reference's per-process asio io_service,
reference: src/ray/common/asio/). Synchronous front-end code posts coroutines
onto it via run_coroutine_threadsafe. That loop is a server's HOME loop:
``RpcServer.start()`` records it, and all shared handler state belongs to it.

With ``RTPU_rpc_reactor_shards`` > 1 (default ``min(4, cpus)``; a 1-core box
degenerates to exactly the old single-loop behavior), the server accepts on
the home loop but hands each accepted connection to one of N reactor shard
loops, each running in its own thread (process-global pool, shared by every
RpcServer in the process). Per-connection work — frame reads, msgpack
decode/encode, response writes, drain/flow-control, the chaos ``rpc.recv``
seam, OOB payload landing via the per-method sink, and connection-upgrade
hooks — runs on the connection's shard, so independent connections stop
serializing behind one thread.

What is per-shard vs shared:
  per-shard   frame parse/serialize, socket IO, writer locks, OOB sinks,
              upgrade hooks, chaos seams (chaos.py is internally locked)
  shared      registered handlers and the state they close over. By default
              a handler coroutine HOPS to the home loop
              (run_coroutine_threadsafe + wrap_future), so raylet/GCS/worker
              handler state keeps its single-threaded invariants by
              construction rather than by accident. Methods whose handlers
              are thread-safe (pure reads, natively-locked plasma ops) can
              opt into running directly on the shard via
              ``set_shard_safe({...})`` — the raylet marks its bulk
              data-plane methods (ReceiveChunk/FetchChunk/...) this way.
"""

from __future__ import annotations

import asyncio
import os
import socket as _socket_mod
import struct
import threading
import traceback
from typing import Any, Awaitable, Callable, Dict, List, Optional, Tuple

import cloudpickle
import msgpack

from ray_tpu._private import chaos as _chaos

MSG_REQUEST = 0
MSG_RESPONSE = 1
MSG_ERROR = 2
MSG_NOTIFY = 3
MSG_REQUEST_OOB = 4
MSG_RESPONSE_OOB = 5

_LEN = struct.Struct("<I")
# Allow frames up to 2 GiB; large data rides the plasma plane, not RPC, but
# inline task args/returns can reach tens of MiB.
_MAX_FRAME = (1 << 31) - 1
# Out-of-band payloads land at their destination in pieces of this size, so
# receiving a chunk never allocates more than this on the heap.
_OOB_READ_PIECE = 1 << 16


class RpcError(Exception):
    pass


class ConnectionLost(RpcError):
    pass


class RemoteError(RpcError):
    """Server-side handler raised; carries the remote traceback and exception."""

    def __init__(self, tb: str, exc: Exception | None):
        super().__init__(tb)
        self.remote_traceback = tb
        self.exception = exc


def _pack(msg) -> bytes:
    body = msgpack.packb(msg, use_bin_type=True)
    return _LEN.pack(len(body)) + body


def _pack_oob(mtype: int, seq: int, method, payload, data):
    """Build an out-of-band frame header for `data` (any bytes-like).

    Returns (header_bytes, data_view): the caller writes both back-to-back
    (writev-style). data is NEVER copied or msgpack-encoded here — the
    returned view aliases the caller's buffer.
    """
    mv = data if isinstance(data, memoryview) else memoryview(data)
    header = msgpack.packb(
        [mtype, seq, method, payload, mv.nbytes], use_bin_type=True
    )
    return _LEN.pack(len(header)) + header, mv


async def _read_frame(reader: asyncio.StreamReader):
    """Read one msgpack frame header. For OOB frame types the raw payload
    (msg[4] bytes) follows on the stream and the caller MUST consume it
    (via _read_oob_into) before reading the next frame."""
    header = await reader.readexactly(_LEN.size)
    (length,) = _LEN.unpack(header)
    if length > _MAX_FRAME:
        raise RpcError(f"frame too large: {length}")
    body = await reader.readexactly(length)
    return msgpack.unpackb(body, raw=False, strict_map_key=False)


async def _read_oob_into(reader: asyncio.StreamReader, dest, nbytes: int):
    """Land an out-of-band payload straight into `dest` (a writable
    memoryview, e.g. a plasma buffer slice) in bounded pieces — the full
    payload is never materialized as one heap object. dest=None drains and
    discards (receiver had nowhere to put it but the stream must stay
    framed)."""
    off = 0
    while off < nbytes:
        piece = await reader.read(min(nbytes - off, _OOB_READ_PIECE))
        if not piece:
            raise asyncio.IncompleteReadError(b"", nbytes - off)
        if dest is not None:
            dest[off : off + len(piece)] = piece
        off += len(piece)


class OobPayload:
    """Handler return marker: respond with an out-of-band frame.

    `header` is the msgpack-able response payload; `data` is any bytes-like
    (typically a plasma memoryview slice) appended raw after the header.
    `release`, if given, is called exactly once after the frame has been
    handed to the transport — use it to drop plasma pins.
    """

    __slots__ = ("header", "data", "_release")

    def __init__(self, header, data, release=None):
        self.header = header
        self.data = data
        self._release = release

    def release(self):
        cb, self._release = self._release, None
        if cb is not None:
            try:
                cb()
            except Exception:
                pass


# ------------------------------------------------------- reactor shard pool
# Process-global pool of extra event-loop threads serving accepted
# connections (shard 0 is always the server's home loop, so the pool holds
# shards 1..N-1). Grown lazily, shared by every RpcServer in the process.

_shard_lock = threading.Lock()
_shard_loops: List[asyncio.AbstractEventLoop] = []


def _shard_loop(index: int) -> asyncio.AbstractEventLoop:
    with _shard_lock:
        while len(_shard_loops) <= index:
            loop = asyncio.new_event_loop()
            t = threading.Thread(
                target=_run_shard, args=(loop,),
                name=f"rtpu-rpc-shard-{len(_shard_loops) + 1}", daemon=True)
            t.start()
            _shard_loops.append(loop)
        return _shard_loops[index]


def _run_shard(loop: asyncio.AbstractEventLoop):
    asyncio.set_event_loop(loop)
    loop.run_forever()


def resolve_reactor_shards(requested: Optional[int] = None) -> int:
    """Shard count: explicit arg > RTPU_rpc_reactor_shards > min(4, cpus).
    1 (any 1-core box) means the classic single-loop reactor."""
    n = requested
    if n is None:
        from ray_tpu._private.config import RTPU_CONFIG

        n = RTPU_CONFIG.rpc_reactor_shards
    n = int(n or 0)
    if n <= 0:
        n = min(4, os.cpu_count() or 1)
    return max(1, n)


Handler = Callable[[Any], Awaitable[Any]]

# Per-method receive sink: sink(payload, nbytes) -> None | (dest_view, done).
# Returning a (writable memoryview, done_callback|None) lands the raw
# payload directly at its final destination (done(ok) fires after the read
# completes); returning None makes the server buffer it into a bytearray.
OobSink = Callable[[Any, int], Optional[Tuple[memoryview, Optional[Callable]]]]


class RpcServer:
    """Serves registered async handlers; one instance per process role."""

    def __init__(self, host: str = "127.0.0.1", shards: Optional[int] = None):
        self._host = host
        self._handlers: Dict[str, Handler] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self.port: Optional[int] = None
        # writer -> owning event loop (close must happen on that loop)
        self._conns: Dict[Any, asyncio.AbstractEventLoop] = {}
        self._validator = None
        self._upgrades: Dict[str, Any] = {}
        self._oob_sinks: Dict[str, OobSink] = {}
        # sharded reactor state (module docstring "Threading model")
        self._shards_requested = shards
        self.num_shards = 1
        self._home_loop: Optional[asyncio.AbstractEventLoop] = None
        self._lsock = None
        self._accept_task = None
        self._next_shard = 0
        self._shard_safe: set = set()

    def set_shard_safe(self, methods):
        """Mark methods whose handlers may run directly on a connection's
        shard loop (thread-safe by construction: pure reads or
        natively-locked state). Everything else hops to the home loop.

        Raises at registration on a name with no registered handler: a
        typo here is otherwise invisible — the real method silently keeps
        hopping home, which is correct but quietly defeats the
        optimization. Register handlers (register/register_all) first.
        """
        methods = set(methods)
        unknown = sorted(m for m in methods if m not in self._handlers)
        if unknown:
            raise ValueError(
                f"set_shard_safe: no registered handler for {unknown} "
                f"(known: {sorted(self._handlers)[:20]}...); register "
                "handlers before marking them shard-safe"
            )
        self._shard_safe.update(methods)

    def set_oob_sink(self, method: str, sink: OobSink):
        """Register a landing sink for MSG_REQUEST_OOB frames of `method`:
        the raw payload streams straight into the memoryview the sink
        returns (e.g. a pre-created plasma buffer at the chunk's offset)
        instead of being buffered on the heap first."""
        self._oob_sinks[method] = sink

    def set_upgrade_hook(self, method: str, hook):
        """Register a connection-upgrade method: ``hook(payload) ->
        (response_payload, adopt_cb | None)``. When adopt_cb is returned the
        socket is detached from asyncio after the response is flushed and
        handed to ``adopt_cb(raw_blocking_socket)`` — the basis of the
        direct call channel (direct_channel.py). The client must not send
        anything after the upgrade request until it reads the response, or
        those bytes would be lost in the asyncio transport buffer."""
        self._upgrades[method] = hook

    def set_validator(self, fn):
        """Optional (method, payload) -> None hook run before dispatch;
        raise to reject (see _private/schema.py typed wire contracts)."""
        self._validator = fn

    def register(self, method: str, handler: Handler):
        self._handlers[method] = handler

    def register_all(self, obj, prefix: str = ""):
        """Register every ``handle_<name>`` coroutine method of obj as <name>."""
        for attr in dir(obj):
            if attr.startswith("handle_"):
                self.register(prefix + attr[len("handle_") :], getattr(obj, attr))

    async def start(self, port: int = 0) -> int:
        self._home_loop = asyncio.get_running_loop()
        self.num_shards = resolve_reactor_shards(self._shards_requested)
        if self.num_shards <= 1:
            self._server = await asyncio.start_server(
                self._on_connection, self._host, port, limit=_MAX_FRAME
            )
            self.port = self._server.sockets[0].getsockname()[1]
            return self.port
        sock = _socket_mod.socket(_socket_mod.AF_INET, _socket_mod.SOCK_STREAM)
        sock.setsockopt(_socket_mod.SOL_SOCKET, _socket_mod.SO_REUSEADDR, 1)
        sock.bind((self._host, port))
        sock.listen(256)
        sock.setblocking(False)
        self._lsock = sock
        self.port = sock.getsockname()[1]
        self._accept_task = asyncio.ensure_future(self._accept_loop())
        return self.port

    async def _accept_loop(self):
        """Accept on the home loop, serve each connection on a shard loop
        picked round-robin (shard 0 IS the home loop, so a 1-shard server
        never crosses threads)."""
        loop = self._home_loop
        while True:
            try:
                conn, _addr = await loop.sock_accept(self._lsock)
            except (asyncio.CancelledError, OSError):
                return
            conn.setblocking(False)
            shard = self._next_shard % self.num_shards
            self._next_shard += 1
            if shard == 0:
                asyncio.ensure_future(self._serve_conn(conn))
            else:
                asyncio.run_coroutine_threadsafe(
                    self._serve_conn(conn), _shard_loop(shard - 1))

    async def _serve_conn(self, sock):
        try:
            reader, writer = await asyncio.open_connection(
                sock=sock, limit=_MAX_FRAME)
        except Exception:
            try:
                sock.close()
            except Exception:
                pass
            return
        await self._on_connection(reader, writer)

    async def stop(self):
        if self._accept_task is not None:
            self._accept_task.cancel()
            self._accept_task = None
        if self._lsock is not None:
            try:
                self._lsock.close()
            except Exception:
                pass
            self._lsock = None
        if self._server is not None:
            self._server.close()
            try:
                await self._server.wait_closed()
            except Exception:
                pass
        here = asyncio.get_running_loop()
        for w, loop in list(self._conns.items()):
            try:
                if loop is here:
                    w.close()
                else:
                    loop.call_soon_threadsafe(w.close)
            except Exception:
                pass

    async def _on_connection(self, reader, writer):
        self._conns[writer] = asyncio.get_running_loop()
        lock = asyncio.Lock()
        try:
            while True:
                try:
                    msg = await _read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    return
                mtype, seq, method, payload = msg[0], msg[1], msg[2], msg[3]
                if mtype == MSG_REQUEST_OOB:
                    try:
                        payload = await self._land_oob(reader, method, payload, msg[4])
                    except (asyncio.IncompleteReadError, ConnectionResetError):
                        return
                    asyncio.ensure_future(
                        self._dispatch(writer, lock, seq, method, payload)
                    )
                    continue
                if mtype == MSG_REQUEST and method in self._upgrades:
                    try:
                        resp, adopt = self._upgrades[method](payload)
                    except Exception as e:
                        resp, adopt = {"ok": False, "reason": str(e)}, None
                    writer.write(_pack([MSG_RESPONSE, seq, None, resp]))
                    await writer.drain()
                    if adopt is not None:
                        sock = writer.get_extra_info("socket")
                        dup = sock.dup()
                        dup.setblocking(True)
                        self._conns.pop(writer, None)
                        writer.transport.pause_reading()
                        # drain() only waits for the buffer to fall below
                        # the high-water mark; abort() discards whatever is
                        # still buffered. Under a full socket buffer that
                        # loses the upgrade response and costs the client a
                        # timeout + backoff — wait for a true flush first
                        # via the transport's own flow-control signal.
                        await self._flush_transport(writer)
                        # Closes the transport's fd only; the dup keeps the
                        # TCP connection alive for the adopting thread.
                        writer.transport.abort()
                        adopt(dup)
                        return
                    continue
                if mtype == MSG_REQUEST:
                    if _chaos.ARMED:
                        act = _chaos.hit("rpc.recv", method=method)
                        if act is not None:
                            if act["action"] == "drop":
                                continue  # swallowed: the caller times out
                            if act["action"] == "dup":
                                # receiver idempotence under at-least-once
                                # delivery: dispatch the same request twice
                                asyncio.ensure_future(self._dispatch(
                                    writer, lock, seq, method, payload))
                            elif act["action"] == "delay":
                                asyncio.ensure_future(self._dispatch_later(
                                    act["delay_s"], writer, lock, seq,
                                    method, payload))
                                continue
                    asyncio.ensure_future(
                        self._dispatch(writer, lock, seq, method, payload)
                    )
                elif mtype == MSG_NOTIFY:
                    handler = self._handlers.get(method)
                    if handler is not None:
                        asyncio.ensure_future(
                            self._run_notify(method, handler, payload))
        finally:
            self._conns.pop(writer, None)
            try:
                writer.close()
            except Exception:
                pass

    @staticmethod
    async def _flush_transport(writer, timeout: float = 5.0):
        """Wait for a TRUE transport flush (buffer empty, not merely below
        the high-water mark): shrink the flow-control window to zero so
        ``drain()`` returns only once the kernel accepted every buffered
        byte. This is the transport's own resume_writing signal — no
        polling loop."""
        t = writer.transport
        try:
            if t.get_write_buffer_size() == 0:
                return
            t.set_write_buffer_limits(high=0, low=0)
            await asyncio.wait_for(writer.drain(), timeout)
        except Exception:
            pass

    async def _land_oob(self, reader, method, payload, nbytes: int):
        """Consume an OOB request's raw payload. The method's sink, when
        registered, hands back the final destination buffer (a plasma slice)
        so the bytes never exist as one heap object; otherwise the payload
        buffers into a bytearray. Returns the payload dict annotated with
        "_oob" (int = landed in place via sink; bytearray = buffered)."""
        if nbytes > _MAX_FRAME:
            raise RpcError(f"oob payload too large: {nbytes}")
        payload = dict(payload) if isinstance(payload, dict) else {}
        sink = self._oob_sinks.get(method)
        dest = done = None
        if sink is not None:
            try:
                hooked = sink(payload, nbytes)
            except Exception:
                traceback.print_exc()
                hooked = None
            if hooked is not None:
                dest, done = hooked
        if dest is not None:
            ok = False
            try:
                await _read_oob_into(reader, dest, nbytes)
                ok = True
            finally:
                if done is not None:
                    try:
                        done(ok)
                    except Exception:
                        traceback.print_exc()
            payload["_oob"] = nbytes
        else:
            scratch = bytearray(nbytes)
            await _read_oob_into(reader, memoryview(scratch), nbytes)
            payload["_oob"] = scratch
        return payload

    async def _dispatch_later(self, delay_s, writer, lock, seq, method,
                              payload):
        """Chaos-delayed dispatch (rpc.recv delay action)."""
        await asyncio.sleep(delay_s)
        await self._dispatch(writer, lock, seq, method, payload)

    async def _run_handler(self, method: str, handler, payload):
        """Run a handler with the home-loop dispatch contract: on the home
        loop (or for shard-safe methods) call it in place; from a shard
        loop, hop — the coroutine executes on the home loop and the shard
        awaits its result, so shared handler state never sees two threads.
        The response is packed and written back on the shard."""
        loop = asyncio.get_running_loop()
        if loop is self._home_loop or method in self._shard_safe \
                or self._home_loop is None:
            return await handler(payload)
        cf = asyncio.run_coroutine_threadsafe(handler(payload),
                                              self._home_loop)
        try:
            return await asyncio.wrap_future(cf)
        except asyncio.CancelledError:
            cf.cancel()
            raise

    async def _run_notify(self, method, handler, payload):
        try:
            await self._run_handler(method, handler, payload)
        except Exception:
            traceback.print_exc()

    async def _dispatch(self, writer, lock, seq, method, payload):
        try:
            handler = self._handlers.get(method)
            if handler is None:
                raise RpcError(f"no such method: {method}")
            if self._validator is not None:
                self._validator(method, payload)
            result = await self._run_handler(method, handler, payload)
            if isinstance(result, OobPayload):
                await self._reply_oob(writer, lock, seq, result)
                return
            out = _pack([MSG_RESPONSE, seq, None, result])
        except Exception as e:
            tb = traceback.format_exc()
            try:
                exc_bytes = cloudpickle.dumps(e)
            except Exception:
                exc_bytes = cloudpickle.dumps(RpcError(str(e)))
            out = _pack([MSG_ERROR, seq, tb, exc_bytes])
        async with lock:
            try:
                writer.write(out)
                await writer.drain()
            except (ConnectionResetError, BrokenPipeError):
                pass

    async def _reply_oob(self, writer, lock, seq, result: OobPayload):
        """Send a response whose bulk payload rides raw after the header —
        the handler's buffer view (e.g. a plasma slice) goes straight to the
        transport, no bytes() and no msgpack encode of the data."""
        hdr, mv = _pack_oob(
            MSG_RESPONSE_OOB, seq, None, result.header, result.data
        )
        async with lock:
            try:
                writer.write(hdr)
                writer.write(mv)
                await writer.drain()
            except (ConnectionResetError, BrokenPipeError):
                pass
            finally:
                # the transport owns (a copy of) any unsent tail after
                # write(); the handler's pin can drop now
                result.release()


class RpcClient:
    """Single persistent connection with multiplexed in-flight requests."""

    def __init__(self, host: str, port: int):
        self._host, self._port = host, port
        self._reader = None
        self._writer = None
        self._seq = 0
        self._pending: Dict[int, asyncio.Future] = {}
        # seq -> writable memoryview an OOB response lands into directly
        self._pending_oob_dest: Dict[int, memoryview] = {}
        self._lock: Optional[asyncio.Lock] = None
        self._connected = False
        self._read_task = None

    @property
    def address(self) -> Tuple[str, int]:
        return (self._host, self._port)

    async def connect(self):
        self._reader, self._writer = await asyncio.open_connection(
            self._host, self._port, limit=_MAX_FRAME
        )
        self._lock = asyncio.Lock()
        self._connected = True
        self._read_task = asyncio.ensure_future(self._read_loop())

    async def _read_loop(self):
        try:
            while True:
                msg = await _read_frame(self._reader)
                mtype, seq, extra, payload = msg[0], msg[1], msg[2], msg[3]
                if mtype == MSG_RESPONSE_OOB:
                    payload = await self._land_oob_response(seq, payload, msg[4])
                fut = self._pending.pop(seq, None)
                self._pending_oob_dest.pop(seq, None)
                if fut is None or fut.done():
                    continue
                if mtype in (MSG_RESPONSE, MSG_RESPONSE_OOB):
                    fut.set_result(payload)
                elif mtype == MSG_ERROR:
                    try:
                        exc = cloudpickle.loads(payload)
                    except Exception:
                        exc = None
                    fut.set_exception(RemoteError(extra, exc))
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError):
            pass
        finally:
            self._connected = False
            err = ConnectionLost(f"connection to {self._host}:{self._port} lost")
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(err)
            self._pending.clear()
            self._pending_oob_dest.clear()

    async def _land_oob_response(self, seq: int, payload, nbytes: int):
        """Consume an OOB response's raw payload: straight into the buffer
        the caller registered via call(oob_dest=...) when sizes agree (the
        zero-copy pull path), else into a bytearray."""
        payload = dict(payload) if isinstance(payload, dict) else {}
        dest = self._pending_oob_dest.pop(seq, None)
        if dest is not None and dest.nbytes == nbytes:
            await _read_oob_into(self._reader, dest, nbytes)
            payload["_oob"] = nbytes
        else:
            scratch = bytearray(nbytes)
            await _read_oob_into(self._reader, memoryview(scratch), nbytes)
            payload["_oob"] = scratch
        return payload

    async def call(self, method: str, payload: Any = None, timeout: float = None,
                   oob=None, oob_dest: Optional[memoryview] = None):
        """One request/response round-trip.

        oob: bytes-like sent RAW after the request header (MSG_REQUEST_OOB)
        — the view goes straight to the transport, never copied into a
        packed frame. The caller must keep the underlying buffer valid
        until call() returns (the transport copies any back-pressured tail).
        oob_dest: writable memoryview an out-of-band RESPONSE payload lands
        into directly; on success the response dict carries "_oob" == nbytes.
        """
        if not self._connected:
            raise ConnectionLost(f"not connected to {self._host}:{self._port}")
        self._seq += 1
        seq = self._seq
        fut = asyncio.get_running_loop().create_future()
        self._pending[seq] = fut
        if oob_dest is not None:
            self._pending_oob_dest[seq] = oob_dest
        chaos_act = _chaos.hit("rpc.send", method=method) if _chaos.ARMED \
            else None
        try:
            if chaos_act is not None and chaos_act["action"] == "delay":
                await asyncio.sleep(chaos_act["delay_s"])
            if chaos_act is not None and chaos_act["action"] == "drop":
                pass  # never sent: the caller's timeout is the symptom
            elif oob is not None:
                hdr, mv = _pack_oob(MSG_REQUEST_OOB, seq, method, payload, oob)
                async with self._lock:
                    self._writer.write(hdr)
                    self._writer.write(mv)
                    await self._writer.drain()
            else:
                frame = _pack([MSG_REQUEST, seq, method, payload])
                async with self._lock:
                    self._writer.write(frame)
                    if chaos_act is not None and chaos_act["action"] == "dup":
                        self._writer.write(frame)
                    await self._writer.drain()
            if timeout is None:
                return await fut
            return await asyncio.wait_for(fut, timeout)
        finally:
            if fut.cancelled() or not fut.done():
                # timeout/cancel: a late OOB response must not land into
                # the caller's buffer after it may have been reused
                self._pending_oob_dest.pop(seq, None)
                self._pending.pop(seq, None)

    async def notify(self, method: str, payload: Any = None):
        if not self._connected:
            raise ConnectionLost(f"not connected to {self._host}:{self._port}")
        frame = _pack([MSG_NOTIFY, 0, method, payload])
        async with self._lock:
            self._writer.write(frame)
            await self._writer.drain()

    def is_connected(self) -> bool:
        return self._connected

    async def close(self):
        self._connected = False
        if self._read_task is not None:
            self._read_task.cancel()
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass


class IoThread:
    """The per-process background asyncio loop (the 'io_service').

    Debug mode (the asyncio runtime's sanitizer analogue — the reference
    ships tsan/asan build configs, .bazelrc :104; a single-threaded asyncio
    control plane's failure mode is instead a BLOCKED loop): set
    ``RTPU_DEBUG_LOOP_MS=<n>`` to (a) log callbacks that hold the loop
    longer than n ms via asyncio's slow-callback detector and (b) run a
    watchdog thread that dumps all stacks if the loop stops ticking for
    10×n ms — catching accidental sync work (ray_tpu.get etc.) posted onto
    the io loop, the class of deadlock the client-server had."""

    _singleton = None
    _singleton_lock = threading.Lock()

    def __init__(self, name="rtpu-io"):
        import os as _os

        self.loop = asyncio.new_event_loop()
        self._debug_ms = float(_os.environ.get("RTPU_DEBUG_LOOP_MS", "0") or 0)
        if self._debug_ms > 0:
            self.loop.slow_callback_duration = self._debug_ms / 1000.0
            self.loop.set_debug(True)
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._thread.start()
        if self._debug_ms > 0:
            self._last_tick = 0.0
            threading.Thread(
                target=self._watchdog, name=name + "-watchdog", daemon=True
            ).start()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def _watchdog(self):
        import faulthandler
        import sys
        import time as _time

        stall = self._debug_ms * 10 / 1000.0
        self._last_tick = _time.monotonic()

        async def _tick():
            self._last_tick = _time.monotonic()

        warned = 0.0
        while True:
            _time.sleep(stall / 2)
            try:
                asyncio.run_coroutine_threadsafe(_tick(), self.loop)
            except RuntimeError:
                return  # loop closed
            _time.sleep(stall / 2)
            now = _time.monotonic()
            if now - self._last_tick > stall and now - warned > 5.0:
                warned = now
                print(
                    f"[rtpu-io watchdog] io loop blocked > {stall:.2f}s — "
                    "sync work is running on the io thread; stacks follow",
                    file=sys.stderr, flush=True,
                )
                faulthandler.dump_traceback(file=sys.stderr)

    @classmethod
    def current(cls) -> "IoThread":
        with cls._singleton_lock:
            if cls._singleton is None or not cls._singleton._thread.is_alive():
                cls._singleton = cls()
            return cls._singleton

    def run(self, coro, timeout=None):
        """Run a coroutine on the io loop from a foreign (sync) thread."""
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def post(self, coro):
        """Fire-and-forget a coroutine on the io loop."""
        return asyncio.run_coroutine_threadsafe(coro, self.loop)


class ClientPool:
    """Cache of RpcClients keyed by address, created lazily on the io loop."""

    def __init__(self):
        self._clients: Dict[Tuple[str, int], RpcClient] = {}
        self._locks: Dict[Tuple[str, int], asyncio.Lock] = {}

    async def get(self, host: str, port: int) -> RpcClient:
        key = (host, port)
        client = self._clients.get(key)
        if client is not None and client.is_connected():
            return client
        lock = self._locks.setdefault(key, asyncio.Lock())
        async with lock:
            client = self._clients.get(key)
            if client is not None and client.is_connected():
                return client
            client = RpcClient(host, port)
            await client.connect()
            self._clients[key] = client
            return client

    async def close_all(self):
        for c in self._clients.values():
            await c.close()
        self._clients.clear()
