"""Unique identifiers for jobs, tasks, actors, objects, nodes and workers.

Design notes
------------
The reference runtime derives task-scoped object ids from the parent task id plus a
return-index suffix (reference: src/ray/common/id.h). We keep that property — an
ObjectID embeds the TaskID that produced it — because the owner of a task can then
pre-compute the ids of its returns before the task runs, which is what makes
owner-side bookkeeping (pending returns, lineage) possible without a round trip.

Sizes (bytes): JobID=4, ActorID=12, TaskID=16, ObjectID=20 (TaskID + 4-byte index),
NodeID/WorkerID/PlacementGroupID=14.
"""

from __future__ import annotations

import os
import random
import threading

# ID generation is on the task-submission hot path; os.urandom costs ~80 µs
# per call (syscall), a seeded Mersenne ~1 µs. Seed from the OS and reseed
# after fork so fork-server worker children never repeat the parent's stream.
_rng = random.Random(os.urandom(16))
if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=lambda: _rng.seed(os.urandom(16)))


def _rand_bytes(n: int) -> bytes:
    return _rng.randbytes(n)


_JOB_ID_SIZE = 4
_ACTOR_ID_SIZE = 12
_TASK_ID_SIZE = 16
_OBJECT_ID_SIZE = 20
_UNIQUE_ID_SIZE = 14


class BaseID:
    # _hash caches hash(_bytes): IDs key dicts all over the submit path
    # (leases, pending tasks, refcounts) — hashing the bytes each lookup
    # was a measurable share of driver io-thread time.
    __slots__ = ("_bytes", "_hash")
    SIZE = _UNIQUE_ID_SIZE

    def __init__(self, id_bytes: bytes):
        if len(id_bytes) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(id_bytes)}"
            )
        self._bytes = bytes(id_bytes)
        self._hash = hash(self._bytes)

    @classmethod
    def from_random(cls):
        return cls(_rand_bytes(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\xff" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._bytes == b"\xff" * self.SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __lt__(self, other):
        return self._bytes < other._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    SIZE = _JOB_ID_SIZE

    @classmethod
    def from_int(cls, value: int):
        return cls(value.to_bytes(cls.SIZE, "little"))

    def int_value(self) -> int:
        return int.from_bytes(self._bytes, "little")


class NodeID(BaseID):
    SIZE = _UNIQUE_ID_SIZE


class WorkerID(BaseID):
    SIZE = _UNIQUE_ID_SIZE


class PlacementGroupID(BaseID):
    SIZE = _UNIQUE_ID_SIZE


class ActorID(BaseID):
    SIZE = _ACTOR_ID_SIZE

    @classmethod
    def of(cls, job_id: JobID):
        return cls(_rand_bytes(cls.SIZE - JobID.SIZE) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bytes[-JobID.SIZE :])


class TaskID(BaseID):
    SIZE = _TASK_ID_SIZE

    @classmethod
    def for_task(cls, job_id: JobID):
        return cls(_rand_bytes(cls.SIZE - JobID.SIZE) + job_id.binary())

    @classmethod
    def for_actor_creation(cls, actor_id: ActorID):
        pad = cls.SIZE - ActorID.SIZE
        return cls(b"\x00" * pad + actor_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bytes[-JobID.SIZE :])


class ObjectID(BaseID):
    SIZE = _OBJECT_ID_SIZE

    @classmethod
    def from_task(cls, task_id: TaskID, index: int):
        """The i-th return of a task; index starts at 1 (0 = the put-counter space)."""
        return cls(task_id.binary() + index.to_bytes(4, "little"))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int):
        # Puts live in the same id-space, distinguished by the high bit of the suffix.
        return cls(task_id.binary() + (put_index | 0x80000000).to_bytes(4, "little"))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[: TaskID.SIZE])

    def return_index(self) -> int:
        return int.from_bytes(self._bytes[TaskID.SIZE :], "little")


_local = threading.local()


def _hex_to_id(kind, hex_str):
    return kind.from_hex(hex_str)
