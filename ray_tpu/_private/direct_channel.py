"""Direct call channel: a GIL-lean blocking-socket fast path for actor tasks.

Why this exists: the default actor-call path routes every submit and every
reply through two asyncio event loops (driver io loop + worker io loop). A
profile of the 1:1 sync pattern (`get(a.m.remote())` in a loop) shows ~10
io-loop iterations, 2+ self-pipe wakeups and ~6 cross-thread handoffs per
call — on a single-core box that caps sync calls at ~700/s while the
reference's C++ core worker does 2,050/s (BASELINE.md). The reference gets
its speed from a dedicated gRPC completion-queue thread ping-ponging with
the caller (reference: src/ray/core_worker/transport/direct_actor_transport.cc,
normal_task_submitter.cc) — this module is the Python-shaped analogue:

- One extra *blocking* socket per (caller worker, actor worker) pair,
  established by upgrading a fresh RPC connection (`__direct_channel__`
  handshake) off the worker's existing advertised port.
- The caller's USER thread serializes the task spec and sends it straight
  from `.remote()` — the driver io loop never sees the task.
- The actor worker reads frames on a dedicated reader thread which runs the
  serial-actor pump INLINE (executor claims the pump in the reader thread):
  recv -> execute -> reply happens on one thread with zero loop hops.
- Replies land on the caller's reader thread, which resolves blocked
  `get()`s via a threading.Condition (the "staging store") and posts the
  authoritative ownership bookkeeping to the io loop in coalesced batches
  (the loop's memory store stays the single source of truth; staging is a
  read-through cache in front of it).

Ordering: a channel only ACTIVATES when the io loop confirms the actor
submitter is quiescent (no in-flight pushes, empty queues); from then on
EVERY task for that actor rides the channel, so per-caller order is just
socket FIFO — there is no cross-channel interleave to re-order. The
`posted_unrouted` counter closes the activation race: a user thread only
direct-sends once every spec it previously posted to the loop has been
routed (and loop-forwarded onto the channel under the same order lock).

Failure semantics mirror the in-flight push path (worker.py
_push_actor_batch ConnectionLost): tasks sent on a channel that breaks MAY
have executed, so they fail with ActorDiedError; tasks still in the unsent
out-queue provably did not execute and are re-routed through the loop path.
"""

from __future__ import annotations

import socket
import struct
import threading
from collections import deque
from typing import Any, Dict, Optional

import msgpack

_LEN = struct.Struct("<I")

# Frame types on an upgraded channel (disjoint from rpc.py's MSG_* range).
MSG_DIRECT_TASK = 4  # [MSG_DIRECT_TASK, spec]
MSG_DIRECT_REPLY = 5  # [MSG_DIRECT_REPLY, task_id, reply]

HANDSHAKE_METHOD = "__direct_channel__"

_INLINE = "inline"
_ERR = "err"


def pack_frame(msg) -> bytes:
    body = msgpack.packb(msg, use_bin_type=True)
    return _LEN.pack(len(body)) + body


class FrameReader:
    """Incremental length-prefixed msgpack frame parser over a blocking
    socket. recv() is called with the GIL released, so a blocked reader
    thread costs nothing."""

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._buf = bytearray()

    def read_frames(self):
        """Blocks for at least one frame; returns every complete frame
        buffered so far (natural batch under load)."""
        while True:
            frames = []
            while True:
                if len(self._buf) < _LEN.size:
                    break
                (length,) = _LEN.unpack_from(self._buf, 0)
                if len(self._buf) < _LEN.size + length:
                    break
                body = bytes(self._buf[_LEN.size:_LEN.size + length])
                del self._buf[:_LEN.size + length]
                frames.append(msgpack.unpackb(body, raw=False,
                                              strict_map_key=False))
            if frames:
                return frames
            chunk = self._sock.recv(1 << 20)
            if not chunk:
                raise ConnectionError("direct channel closed")
            self._buf.extend(chunk)


class SendPipe:
    """Serialized, coalescing writer shared by user threads, the io loop and
    reader threads. append+try-flush: whoever holds flush_lock drains the
    out-deque with one sendall per accumulated batch; appenders that lose
    the race are guaranteed their frame is flushed by the current holder
    (the holder re-checks after every drain). The io loop uses try_flush
    nonblocking so it can never park on a full socket buffer."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.order_lock = threading.Lock()  # also guards channel counters
        self._flush_lock = threading.Lock()
        self._out: deque = deque()
        self.dead = False

    def append_locked(self, frame: bytes):
        """Caller must hold order_lock."""
        self._out.append(frame)

    def send(self, frame: bytes, blocking=True):
        with self.order_lock:
            if self.dead:
                raise ConnectionError("direct channel closed")
            self._out.append(frame)
        self.flush(blocking=blocking)

    def flush(self, blocking=True):
        """Drain the out-deque. Safe against the append-between-last-check-
        and-release race: the holder re-checks after releasing and retries.
        Socket errors mark the pipe dead (the reader thread's EOF runs the
        authoritative death path) and are not raised here."""
        while True:
            if not self._flush_lock.acquire(blocking=blocking):
                return
            try:
                while True:
                    with self.order_lock:
                        if not self._out or self.dead:
                            break
                        batch = b"".join(self._out)
                        self._out.clear()
                    try:
                        self.sock.sendall(batch)
                    except OSError:
                        with self.order_lock:
                            self.dead = True
                        return
            finally:
                self._flush_lock.release()
            with self.order_lock:
                if not self._out or self.dead:
                    return
            # Raced: frames were appended by a thread that saw the flush
            # lock held just as we were exiting — go around again.

    def pending_unsent(self) -> list:
        """Drain the unsent out-queue (on channel death). Frames return as
        raw bytes; the caller re-decodes what it needs."""
        with self.order_lock:
            self.dead = True
            out = list(self._out)
            self._out.clear()
        return out

    def close(self):
        with self.order_lock:
            self.dead = True
        try:
            self.sock.close()
        except Exception:
            pass


def _unpack_frame_bytes(frame: bytes):
    return msgpack.unpackb(frame[_LEN.size:], raw=False, strict_map_key=False)


# --------------------------------------------------------------- caller side


class DirectChannel:
    """Caller-side state for one actor's direct channel."""

    __slots__ = (
        "actor_id", "pipe", "active", "posted_unrouted", "reader", "addr",
        "closed",
    )

    def __init__(self, actor_id: bytes, sock: socket.socket, addr):
        self.actor_id = actor_id
        self.pipe = SendPipe(sock)
        self.addr = addr
        # Both guarded by pipe.order_lock:
        self.active = False  # loop confirmed quiescence; all tasks ride here
        self.posted_unrouted = 0  # specs posted to the loop, not yet routed
        self.reader: Optional[threading.Thread] = None
        self.closed = False


class DirectManager:
    """Caller-side registry: channels, the reply staging store, and the
    fast blocking-get path. One per CoreWorker."""

    _FALLBACK = object()

    def __init__(self, core):
        self.core = core
        self.cond = threading.Condition()
        # oid bytes -> memory-store-shaped entry, kept until the io loop's
        # deferred bookkeeping lands the value in the authoritative store.
        self.staged: Dict[bytes, tuple] = {}
        # oid bytes -> task_id for replies still in flight on a channel
        self.pending_oids: Dict[bytes, bytes] = {}
        # task_id -> spec for everything sent on a channel
        self.pending_tasks: Dict[bytes, dict] = {}
        self.channels: Dict[bytes, DirectChannel] = {}
        self.unavailable: set = set()  # actor_ids that rejected the handshake
        # actor_id -> monotonic deadline before which connects won't retry;
        # a dead/partitioned node otherwise costs a blocking 5s connect
        # timeout inside EVERY .remote() while the GCS still says ALIVE.
        self._connect_backoff: Dict[bytes, float] = {}
        # actor_id -> submits seen pre-channel (channels open on the 2nd)
        self._call_counts: Dict[bytes, int] = {}
        self.stats = {"direct_sent": 0, "fast_get_hits": 0,
                      "fast_get_fallbacks": 0, "switches": 0,
                      "channel_deaths": 0}

    # ------------------------------------------------------------ submit path

    def try_submit(self, sub, spec: dict) -> bool:
        """Called from .remote() in the user thread, after _register_pending.
        True = the spec rode the channel (or its out-queue); False = caller
        must use the loop path. Also kicks off establishment/switching."""
        actor_id = sub.actor_id
        ch = self.channels.get(actor_id)
        if ch is None:
            # Don't pay connect+handshake+reader-thread for an actor that
            # may only ever see one call (actor-creation storms ping each
            # actor once — 200 channels would cost ~1s of driver CPU for
            # nothing). The SECOND submit reveals a calling pattern.
            calls = self._call_counts.get(actor_id, 0) + 1
            self._call_counts[actor_id] = calls
            if calls < 2:
                return False
            import time as _time

            if (actor_id not in self.unavailable and sub.state == "ALIVE"
                    and sub.addr
                    and _time.monotonic()
                    >= self._connect_backoff.get(actor_id, 0.0)):
                ch = self._establish(sub)
            if ch is None:
                return False
        with ch.pipe.order_lock:
            if ch.closed or ch.pipe.dead:
                return False
            if not ch.active or ch.posted_unrouted > 0:
                # Not switched yet (or earlier specs still queued loop-side):
                # keep loop order, count it so activation waits for it.
                ch.posted_unrouted += 1
                return False
            self._track_locked(spec)
            ch.pipe.append_locked(pack_frame([MSG_DIRECT_TASK, spec]))
            self.stats["direct_sent"] += 1
        ch.pipe.flush()
        return True

    def loop_routed(self, sub, spec: dict) -> bool:
        """Called on the io loop when routing a posted spec. Returns True if
        the spec was forwarded onto the (active) channel — the loop path
        must then skip its own push. Runs under the order lock so forwarded
        frames keep their posted order relative to direct sends."""
        ch = self.channels.get(sub.actor_id)
        if ch is None:
            return False
        with ch.pipe.order_lock:
            if ch.posted_unrouted > 0:
                ch.posted_unrouted -= 1
            if not ch.active or ch.closed or ch.pipe.dead:
                return False
            self._track_locked(spec)
            ch.pipe.append_locked(pack_frame([MSG_DIRECT_TASK, spec]))
            self.stats["direct_sent"] += 1
        # Never touch the socket from the io loop — even a "nonblocking"
        # flush can park in sendall on a full buffer. A pool thread pays.
        import asyncio

        asyncio.get_running_loop().run_in_executor(None, ch.pipe.flush)
        return True

    def _track_locked(self, spec: dict):
        from ray_tpu._private import task_spec as ts

        with self.cond:
            self.pending_tasks[spec["task_id"]] = spec
            for oid in ts.return_object_ids(spec):
                self.pending_oids[oid.binary()] = spec["task_id"]

    def _establish(self, sub) -> Optional[DirectChannel]:
        """Blocking connect + handshake from the user thread (once per
        actor incarnation). On success, posts the switch request to the
        loop; the channel activates when the loop confirms quiescence."""
        actor_id = sub.actor_id
        addr = sub.addr
        try:
            sock = socket.create_connection((addr[0], addr[1]), timeout=5.0)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.sendall(pack_frame(
                [0, 0, HANDSHAKE_METHOD,  # MSG_REQUEST
                 {"caller_id": self.core.worker_id.binary(),
                  "actor_id": actor_id}]))
            reader = FrameReader(sock)
            sock.settimeout(5.0)
            frames = reader.read_frames()
            mtype, _seq, _x, payload = frames[0]
            if mtype != 1 or not payload.get("ok"):  # MSG_RESPONSE
                sock.close()
                self.unavailable.add(actor_id)
                return None
            sock.settimeout(None)
        except Exception:
            # Connection refused/timeout: back off (not a permanent
            # blacklist — the worker may be mid-boot or mid-restart, but a
            # partitioned node must not cost 5s inside every .remote()).
            import time as _time

            self._connect_backoff[actor_id] = _time.monotonic() + 10.0
            try:
                sock.close()
            except Exception:
                pass
            return None
        self._connect_backoff.pop(actor_id, None)
        ch = DirectChannel(actor_id, sock, addr)
        existing = self.channels.setdefault(actor_id, ch)
        if existing is not ch:  # lost a racing establish
            ch.pipe.close()
            return existing
        from ray_tpu._private import flight_recorder as _fr

        _fr.record("chan.up", actor_id, f"{addr[0]}:{addr[1]}")
        t = threading.Thread(
            target=self._reader_loop, args=(ch, reader),
            name=f"rtpu-direct-{actor_id.hex()[:8]}", daemon=True)
        ch.reader = t
        t.start()
        # Ask the loop to flip `active` once the submitter is quiescent.
        self.core._post_batched("direct_switch", actor_id)
        return ch

    # --------------------------------------------------------- io-loop hooks

    def on_switch_request(self, actor_id: bytes):
        """io loop: arm the pending switch and try to flip immediately."""
        sub = self.core._actor_submitters.get(actor_id)
        ch = self.channels.get(actor_id)
        if sub is None or ch is None:
            return
        sub.direct_pending_switch = True
        self.maybe_activate(sub)

    def maybe_activate(self, sub):
        """io loop: flip the channel active when nothing is in flight on the
        loop path. Called at switch request and whenever loop-path work for
        this submitter drains to zero."""
        if not getattr(sub, "direct_pending_switch", False):
            return
        ch = self.channels.get(sub.actor_id)
        if ch is None:
            return
        if (sub.state == "ALIVE" and not sub.inflight and not sub.push_queue
                and not sub.buffer and sub.pushing == 0):
            with ch.pipe.order_lock:
                if (not ch.closed and not ch.pipe.dead
                        and ch.posted_unrouted == 0):
                    ch.active = True
                    sub.direct_pending_switch = False
                    self.stats["switches"] += 1

    def on_channel_down(self, actor_id: bytes, unsent_frames: list):
        """io loop: the reader died. Fail every sent-but-unreplied task with
        the in-flight semantics; re-route unsent frames through the loop
        path (they provably never reached the worker)."""
        from ray_tpu.exceptions import ActorDiedError

        from ray_tpu._private import flight_recorder as _fr

        _fr.record("chan.down", actor_id, f"{len(unsent_frames)} unsent")
        ch = self.channels.pop(actor_id, None)
        sub = self.core._actor_submitters.get(actor_id)
        if sub is not None:
            sub.direct_pending_switch = False
        self.stats["channel_deaths"] += 1
        unsent_task_ids = set()
        respecs = []
        for raw in unsent_frames:
            try:
                msg = _unpack_frame_bytes(raw)
            except Exception:
                continue
            if msg and msg[0] == MSG_DIRECT_TASK:
                unsent_task_ids.add(msg[1]["task_id"])
                respecs.append(msg[1])
        with self.cond:
            pending = [
                (tid, spec) for tid, spec in self.pending_tasks.items()
                if spec.get("actor_id") == actor_id  # other channels live on
            ]
        for task_id, spec in pending:
            if task_id in unsent_task_ids:
                continue
            self._discard_task(spec)
            self.core._fail_task(
                spec,
                ActorDiedError(
                    actor_id, "actor died while this task was in flight"),
            )
        if sub is not None and respecs:
            kick = None
            for spec in respecs:
                self._discard_task(spec)
                kick = self.core._route_actor_spec(sub.actor_id, spec) or kick
            if kick is not None:
                self.core._pump_actor(kick)
        # Wake blocked fast-gets only after every task is either staged as
        # an error (sent) or discarded+re-routed (unsent): a waiter that
        # wakes mid-cleanup would still see the unsent oid as
        # direct-pending and go back to sleep with no further notify.
        with self.cond:
            self.cond.notify_all()
        if sub is not None:
            import asyncio

            asyncio.ensure_future(self.core._refresh_actor_state(sub))

    def process_replies(self, items: list):
        """io loop: authoritative bookkeeping for a batch of direct replies,
        then retire the staging entries (the memory store now serves
        reads). The common ok-inline case runs synchronously right here —
        no coroutine per reply batch."""
        import asyncio

        slow = []
        retire = []
        for spec, reply in items:
            if self.core._process_task_reply_sync(spec, reply, notify=False):
                retire.extend(_return_oid_bytes(spec))
            else:
                slow.append((spec, reply))
        if retire:
            with self.cond:
                for oid in retire:
                    self.staged.pop(oid, None)
                self.cond.notify_all()
        if not slow:
            return

        async def _run():
            for spec, reply in slow:
                try:
                    await self.core._process_task_reply(spec, reply)
                finally:
                    with self.cond:
                        for oid in _return_oid_bytes(spec):
                            self.staged.pop(oid, None)

        asyncio.ensure_future(_run())

    def _discard_task(self, spec: dict):
        with self.cond:
            self.pending_tasks.pop(spec["task_id"], None)
            for oid in _return_oid_bytes(spec):
                self.pending_oids.pop(oid, None)

    # ------------------------------------------------------------ reader side

    def _reader_loop(self, ch: DirectChannel, reader: FrameReader):
        core = self.core
        try:
            while True:
                frames = reader.read_frames()
                batch = []
                with self.cond:
                    for msg in frames:
                        if msg[0] != MSG_DIRECT_REPLY:
                            continue
                        task_id, reply = msg[1], msg[2]
                        spec = self.pending_tasks.pop(task_id, None)
                        if spec is None:
                            continue
                        self._stage_locked(spec, reply)
                        batch.append((spec, reply))
                    if batch:
                        self.cond.notify_all()
                if batch:
                    core._post_batched("direct_replies", batch)
        except Exception:
            if ch.closed or core.is_shutdown:
                return
            ch.closed = True
            unsent = ch.pipe.pending_unsent()
            unsent_ids = set()
            for raw in unsent:
                try:
                    msg = _unpack_frame_bytes(raw)
                    if msg and msg[0] == MSG_DIRECT_TASK:
                        unsent_ids.add(msg[1]["task_id"])
                except Exception:
                    pass
            # Stage errors under the cond so blocked fast-gets wake with a
            # resolution instead of timing out — but NOT for unsent tasks:
            # those provably never reached the worker and will be re-routed
            # through the loop path by on_channel_down; a staged
            # ActorDiedError would shadow their successful re-execution.
            self._stage_channel_error(ch, skip_task_ids=unsent_ids)
            core._post_batched("direct_down", (ch.actor_id, unsent))

    def _stage_locked(self, spec: dict, reply: dict):
        """Reader thread, under self.cond: make the reply's results readable
        by the fast-get path. Anything not ok-inline falls back to the loop
        (the deferred bookkeeping resolves it there)."""
        from ray_tpu._private import serialization

        oids = _return_oid_bytes(spec)
        if reply.get("status") == "ok":
            results = reply.get("results", [])
            for oid, result in zip(oids, results):
                self.pending_oids.pop(oid, None)
                if "inline" in result:
                    self.staged[oid] = (_INLINE, result["inline"], None)
                # plasma results: leave unstaged; fast-get falls back and the
                # loop-side _process_task_reply lands the InPlasma entry.
        else:
            if reply.get("cancelled"):
                from ray_tpu.exceptions import TaskCancelledError

                payload, _ = serialization.serialize_inline(
                    TaskCancelledError())
            elif "exception" in reply:
                payload = reply["exception"]
            else:
                payload, _ = serialization.serialize_inline(
                    RuntimeError(reply.get("error", "task failed")))
            for oid in oids:
                self.pending_oids.pop(oid, None)
                self.staged[oid] = (_ERR, payload, None)

    def _stage_channel_error(self, ch: DirectChannel, skip_task_ids=()):
        from ray_tpu._private import serialization
        from ray_tpu.exceptions import ActorDiedError

        err = ActorDiedError(
            ch.actor_id, "actor died while this task was in flight")
        payload, _ = serialization.serialize_inline(err)
        with self.cond:
            for task_id, spec in list(self.pending_tasks.items()):
                if spec.get("actor_id") != ch.actor_id:
                    continue  # a different actor's channel — untouched
                if task_id in skip_task_ids:
                    continue  # unsent: will be re-routed, not failed
                for oid in _return_oid_bytes(spec):
                    if oid in self.pending_oids:
                        self.pending_oids.pop(oid, None)
                        self.staged[oid] = (_ERR, payload, None)
            self.cond.notify_all()

    # --------------------------------------------------------------- get path

    def fast_get(self, refs, timeout: Optional[float]):
        """User thread. Returns a value list, raises like get(), or returns
        _FALLBACK when any ref can't be served from staging/pending/store.
        Never touches the io loop."""
        import time as _time

        core = self.core
        store = core.memory_store
        deadline = None if timeout is None else _time.monotonic() + timeout
        oids = [r.object_id() for r in refs]
        keys = [o.binary() for o in oids]
        pending_tasks = core._pending_tasks
        with self.cond:
            # Incremental wait: only re-check still-missing refs per wake —
            # a 1000-ref get otherwise rescans all 1000 keys on every
            # condition wake (O(N^2) across the batch).
            unresolved = list(zip(oids, keys))
            first_pass = True
            while True:
                still = []
                for oid, k in unresolved:
                    if k in self.staged:
                        continue
                    if k in self.pending_oids:
                        still.append((oid, k))
                        continue
                    entry = store.get_if_exists(oid)
                    if (isinstance(entry, tuple)
                            and entry[0] in (_INLINE, _ERR)):
                        continue
                    if entry is None and oid.task_id().binary() in pending_tasks:
                        # Loop-path task still awaiting its reply: the loop
                        # notifies this condition when it lands the result.
                        still.append((oid, k))
                        continue
                    self.stats["fast_get_fallbacks"] += 1
                    return self._FALLBACK
                if not still:
                    break
                if first_pass and len(still) > 1024:
                    # Huge pending batch: the loop's wait_ready_many blocks
                    # on ONE event for the whole set, while this condition
                    # wakes per reply batch and re-scans the remainder —
                    # O(sum of remaining) work that measurably regressed a
                    # 50k-ref drain. Let the io.run path handle bulk gets.
                    self.stats["fast_get_fallbacks"] += 1
                    return self._FALLBACK
                first_pass = False
                unresolved = still
                if deadline is None:
                    self.cond.wait()
                else:
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0 or not self.cond.wait(remaining):
                        from ray_tpu.exceptions import GetTimeoutError

                        raise GetTimeoutError(
                            "get() timed out on direct-pending objects")
            entries = []
            for oid, k in zip(oids, keys):
                e = self.staged.get(k)
                if e is None:
                    e = store.get_if_exists(oid)
                entries.append(e)
        out = []
        for oid, entry in zip(oids, entries):
            if not (isinstance(entry, tuple) and entry[0] in (_INLINE, _ERR)):
                self.stats["fast_get_fallbacks"] += 1
                return self._FALLBACK  # migrated/freed mid-read: rare
            value = core._materialize(oid, entry[:2])
            if isinstance(value, Exception):
                raise value
            out.append(value)
        self.stats["fast_get_hits"] += 1
        return out

    def can_serve(self, refs) -> bool:
        """Cheap pre-check without taking the condition (racy-negative ok).
        Also true when every ref is already an inline/err entry in the
        memory store — those gets skip the io-loop round trip entirely even
        when the value arrived via the loop path."""
        store = self.core.memory_store
        pending_tasks = self.core._pending_tasks
        for r in refs:
            oid = r.object_id()
            k = oid.binary()
            if k in self.staged or k in self.pending_oids:
                continue
            entry = store.get_if_exists(oid)
            if isinstance(entry, tuple) and entry[0] in (_INLINE, _ERR):
                continue
            if entry is None and oid.task_id().binary() in pending_tasks:
                continue
            return False
        return True

    def forget_actor(self, actor_id: bytes):
        """io loop, on terminal actor death: drop per-actor bookkeeping so
        a driver churning short-lived actors doesn't grow these maps
        forever, and run the FULL channel-down path for any live channel.

        A silent close here would strand every in-flight direct task: the
        reader thread's exception handler early-returns once ch.closed is
        set (it assumes the closer staged the errors), so gets would hang
        into GetTimeoutError instead of raising ActorDiedError, and the
        dead channel would stay in self.channels blocking a restarted
        actor's fast path."""
        self._call_counts.pop(actor_id, None)
        self._connect_backoff.pop(actor_id, None)
        self.unavailable.discard(actor_id)
        ch = self.channels.get(actor_id)
        if ch is None:
            return
        already_closed = ch.closed
        ch.closed = True
        # Drain BEFORE closing the socket: pending_unsent marks the pipe
        # dead, so no new frame can slip in between drain and close.
        unsent = ch.pipe.pending_unsent()
        ch.pipe.close()
        if already_closed:
            # reader (or a prior call) already ran the death path
            self.channels.pop(actor_id, None)
            return
        unsent_ids = set()
        for raw in unsent:
            try:
                msg = _unpack_frame_bytes(raw)
                if msg and msg[0] == MSG_DIRECT_TASK:
                    unsent_ids.add(msg[1]["task_id"])
            except Exception:
                pass
        # Same sequence as the reader-death path: stage ActorDiedError for
        # sent tasks so blocked fast-gets wake with a resolution (unsent
        # tasks are re-routed, not failed), then the authoritative loop
        # cleanup pops the channel, fails sent tasks in the memory store
        # and re-routes the unsent specs.
        self._stage_channel_error(ch, skip_task_ids=unsent_ids)
        self.on_channel_down(actor_id, unsent)

    def notify_store(self):
        """io loop, after landing a task reply (any path) in the memory
        store: wake blocked fast-gets. This is what lets fast_get serve
        LOOP-delivered results too — get() on a plain task blocks on this
        condition instead of paying an io.run round trip per call."""
        with self.cond:
            self.cond.notify_all()

    def discard_object(self, oid_bytes: bytes):
        """io loop (ref count hit zero): drop any staged copy."""
        with self.cond:
            self.staged.pop(oid_bytes, None)

    def close_all(self):
        for ch in list(self.channels.values()):
            ch.closed = True
            ch.pipe.close()
        self.channels.clear()


def _return_oid_bytes(spec: dict):
    from ray_tpu._private import task_spec as ts

    return [o.binary() for o in ts.return_object_ids(spec)]


# --------------------------------------------------------------- worker side


class WorkerDirectServer:
    """Actor-worker side: owns upgraded sockets. One reader thread per
    channel feeds the executor's serial pump directly (claiming the pump
    into the reader thread when it is idle); replies are written back on the
    same socket by whichever thread finished the task."""

    def __init__(self, core):
        self.core = core
        self.pipes: list = []

    def eligible(self) -> bool:
        ex = self.core.executor
        return (ex.actor_instance is not None and not ex.actor_is_async
                and ex._serial)

    def adopt(self, sock: socket.socket, caller_id: bytes):
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        pipe = SendPipe(sock)
        self.pipes.append(pipe)
        t = threading.Thread(
            target=self._reader_loop, args=(sock, pipe),
            name=f"rtpu-direct-srv-{caller_id.hex()[:8]}", daemon=True)
        t.start()

    def _reader_loop(self, sock: socket.socket, pipe: SendPipe):
        reader = FrameReader(sock)
        executor = self.core.executor
        # Keep the typed wire contracts honest on this path too: direct
        # frames carry the same spec shape as PushActorTask.
        validator = self.core.server._validator

        def reply_cb(spec, reply):
            try:
                pipe.send(pack_frame(
                    [MSG_DIRECT_REPLY, spec["task_id"], reply]))
            except Exception:
                pass  # caller gone; its side fails the task

        try:
            while True:
                frames = reader.read_frames()
                specs = [m[1] for m in frames if m[0] == MSG_DIRECT_TASK]
                if specs:
                    if validator is not None:
                        for spec in specs:
                            validator("PushActorTask", {"spec": spec})
                    executor.intake_direct(specs, reply_cb)
        except Exception:
            pipe.close()
            try:
                self.pipes.remove(pipe)
            except ValueError:
                pass

    def close_all(self):
        for pipe in list(self.pipes):
            pipe.close()
        self.pipes.clear()
