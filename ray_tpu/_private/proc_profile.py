"""Env-gated whole-process cProfile for the runtime daemons.

Set ``RTPU_PROFILE_PROC=<dir>`` before starting a cluster and every daemon
(GCS, raylet) dumps ``<dir>/<name>-<pid>.prof`` when it receives SIGTERM or
exits cleanly. Complements the profiling plane's on-demand sampler
(`_private/sampling_profiler.py` behind StartProfile/CollectProfile,
`ray-tpu profile`, `/api/profile`): the sampler is timed windows while the
cluster runs; this one is cProfile whole-life coverage with zero blind
spots at process start, which is where burst bottlenecks (actor-creation
storms) live. Inspect with ``python -m pstats`` or snakeviz.
"""

from __future__ import annotations

import atexit
import os
import signal


def maybe_enable_process_profile(name: str) -> None:
    profile_dir = os.environ.get("RTPU_PROFILE_PROC")
    if not profile_dir:
        return
    import cProfile

    prof = cProfile.Profile()
    prof.enable()
    done = {"dumped": False}

    def _dump():
        if done["dumped"]:
            return
        done["dumped"] = True
        prof.disable()
        try:
            os.makedirs(profile_dir, exist_ok=True)
            prof.dump_stats(
                os.path.join(profile_dir, f"{name}-{os.getpid()}.prof")
            )
        except Exception:
            pass

    atexit.register(_dump)
    prev = signal.getsignal(signal.SIGTERM)

    def _on_term(signum, frame):
        _dump()
        if callable(prev):
            prev(signum, frame)
        else:
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except ValueError:
        pass  # not the main thread
