"""Task execution inside a worker: normal tasks, actor creation, actor tasks.

Counterpart of the reference's TaskReceiver + scheduling queues
(reference: src/ray/core_worker/transport/task_receiver.cc:36,
actor_scheduling_queue.h, out_of_order_actor_scheduling_queue.h, fiber.h):

- Normal tasks run one-at-a-time on a dedicated thread (the raylet leases this
  worker exclusively, so there is never more than one in flight).
- Actor tasks are totally ordered *per caller* via sequence numbers with a
  reorder buffer, then dispatched to either a thread pool of size
  ``max_concurrency`` (sync actors) or a private asyncio loop (async actors —
  the reference uses fibers; an event loop is the Python-native equivalent).
"""

from __future__ import annotations

import asyncio
import inspect
import threading
import traceback
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Optional

from ray_tpu._private import serialization
from ray_tpu._private.ids import ObjectID, TaskID
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.task_spec import TASK_ACTOR, return_object_ids
from ray_tpu.exceptions import TaskCancelledError, format_exception


class _AsyncActorLoop:
    """Private event loop thread for async actors."""

    def __init__(self):
        self.loop = asyncio.new_event_loop()
        t = threading.Thread(target=self._run, name="rtpu-async-actor", daemon=True)
        t.start()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()


class Executor:
    def __init__(self, core):
        self.core = core  # CoreWorker
        self._normal_pool = ThreadPoolExecutor(max_workers=1, thread_name_prefix="rtpu-exec")
        # actor state
        self.actor_instance = None
        self.actor_id: Optional[bytes] = None
        self.actor_is_async = False
        self._actor_pool: Optional[ThreadPoolExecutor] = None
        self._actor_loop: Optional[_AsyncActorLoop] = None
        self._actor_sem: Optional[asyncio.Semaphore] = None
        # per-caller ordering: caller_id -> {"expected": int|None, "buffer": {seq: (spec, fut)}}
        self._callers: Dict[bytes, dict] = {}
        self._cancelled: set = set()
        self._current_task_name = ""

    # ----------------------------------------------------------- normal path

    async def execute_normal(self, spec: dict) -> dict:
        return await self._execute(spec, self._normal_pool)

    # ------------------------------------------------------------ actor path

    async def create_actor(self, spec: dict, actor_id: bytes) -> dict:
        loop = asyncio.get_running_loop()
        # functions.fetch may hit the GCS KV through the blocking client — keep
        # it off the IO loop.
        cls = await loop.run_in_executor(None, self.core.functions.fetch, spec["fn_key"])
        args, kwargs, pins = await self._resolve_args(spec)

        def make():
            return cls(*args, **kwargs)

        try:
            self.actor_instance = await loop.run_in_executor(self._normal_pool, make)
        except Exception as e:
            return {"ok": False, "error": format_exception(e)}
        finally:
            del args, kwargs, pins
        self.actor_id = actor_id
        self.core.on_became_actor(actor_id, spec)
        self.actor_is_async = any(
            inspect.iscoroutinefunction(getattr(type(self.actor_instance), m, None))
            for m in dir(type(self.actor_instance))
            if not m.startswith("__")
        )
        max_conc = spec.get("max_concurrency", 1)
        if self.actor_is_async:
            self._actor_loop = _AsyncActorLoop()
            self._actor_sem = None  # created lazily on the actor loop
            self._actor_max_conc = max_conc if max_conc > 1 else 1000
        else:
            self._actor_pool = ThreadPoolExecutor(
                max_workers=max(1, max_conc), thread_name_prefix="rtpu-actor"
            )
        return {"ok": True}

    async def push_actor_task(self, spec: dict) -> dict:
        """Order by (caller_id, seq_no), then execute."""
        caller = spec.get("caller_id", b"")
        seq = spec.get("seq_no", 0)
        state = self._callers.setdefault(caller, {"expected": None, "buffer": {}})
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        state["buffer"][seq] = (spec, fut)
        if state["expected"] is None:
            state["expected"] = seq
        # drain in order
        while state["expected"] in state["buffer"]:
            s, f = state["buffer"].pop(state["expected"])
            state["expected"] += 1
            asyncio.ensure_future(self._run_actor_task(s, f))
        return await fut

    async def _run_actor_task(self, spec: dict, fut: asyncio.Future):
        try:
            if self.actor_is_async:
                reply = await self._execute_async_actor(spec)
            else:
                reply = await self._execute(spec, self._actor_pool)
        except Exception as e:
            reply = {"status": "error", "error": format_exception(e), "app_error": False}
        if not fut.done():
            fut.set_result(reply)


    def _actor_method(self, method_name):
        """Resolve an actor method; `__ray_call__` runs an arbitrary function
        against the instance (reference: actor.__ray_call__.remote(fn))."""
        if method_name == "__ray_call__":
            inst = self.actor_instance
            return lambda fn, *a, **kw: fn(inst, *a, **kw)
        return getattr(self.actor_instance, method_name)

    async def _execute_async_actor(self, spec: dict) -> dict:
        method_name = spec["method_name"]
        args, kwargs, pins = await self._resolve_args(spec)
        method = self._actor_method(method_name)
        outer = asyncio.get_running_loop()
        result_fut = outer.create_future()

        sem_holder = self

        async def run_on_actor_loop():
            tctx = spec.get("trace_ctx")
            if tctx:
                from ray_tpu.util import tracing

                tracing._mark_enabled()
                tracing.set_context(dict(tctx))  # task-local contextvar copy
            if sem_holder._actor_sem is None:
                sem_holder._actor_sem = asyncio.Semaphore(sem_holder._actor_max_conc)
            async with sem_holder._actor_sem:
                if inspect.iscoroutinefunction(method):
                    return await method(*args, **kwargs)
                return method(*args, **kwargs)

        def done_cb(f):
            def transfer():
                if result_fut.done():
                    return
                if f.cancelled():
                    result_fut.set_exception(TaskCancelledError())
                elif f.exception() is not None:
                    result_fut.set_exception(f.exception())
                else:
                    result_fut.set_result(f.result())

            outer.call_soon_threadsafe(transfer)

        inner = asyncio.run_coroutine_threadsafe(run_on_actor_loop(), self._actor_loop.loop)
        inner.add_done_callback(done_cb)
        self.core.register_running_task(spec["task_id"], inner)
        try:
            result = await result_fut
            return await self._package_results(spec, result)
        except Exception as e:
            return self._error_reply(spec, e)
        finally:
            self.core.unregister_running_task(spec["task_id"])
            del args, kwargs, pins

    # --------------------------------------------------------------- shared

    async def _resolve_args(self, spec: dict):
        """Deserialize wire args; top-level refs are fetched (zero-copy)."""
        args: list = []
        kwargs: dict = {}
        pins = []  # keep plasma pin handles alive for the call duration

        for kind, key, wire in spec["args"]:
            if "v" in wire:
                val, _refs = serialization.deserialize_inline(wire["v"])
            elif "ref" in wire:
                id_bytes, owner = wire["ref"]
                ref = ObjectRef(ObjectID(id_bytes), tuple(owner) if owner else None)
                val = await self.core.async_get_one(ref)
                pins.append(val)
            else:
                raise ValueError(f"bad wire arg {wire}")
            if kind == "p":
                args.append(val)
            else:
                kwargs[key] = val
        return args, kwargs, pins

    async def _execute(self, spec: dict, pool: ThreadPoolExecutor) -> dict:
        task_id = spec["task_id"]
        if task_id in self._cancelled:
            self._cancelled.discard(task_id)
            return self._error_reply(spec, TaskCancelledError(), cancelled=True)
        loop = asyncio.get_running_loop()
        try:
            if spec["type"] == TASK_ACTOR:
                fn = self._actor_method(spec["method_name"])
            else:
                fn = await loop.run_in_executor(
                    None, self.core.functions.fetch, spec["fn_key"]
                )
            args, kwargs, pins = await self._resolve_args(spec)
        except Exception as e:
            return {"status": "error", "error": format_exception(e), "app_error": False}

        self.core.task_events.record(spec, "RUNNING")
        old_ctx = self.core.push_task_context(spec)

        def call():
            tctx = spec.get("trace_ctx")
            if tctx:
                # Restore the caller's trace context in the execution thread
                # so user spans + nested submits stay on the same trace
                # (reference: _ray_trace_ctx kwarg propagation).
                from ray_tpu.util import tracing

                tracing._mark_enabled()
                tracing.set_context(dict(tctx))
            try:
                return fn(*args, **kwargs)
            finally:
                if tctx:
                    tracing.set_context(None)

        try:
            result = await loop.run_in_executor(pool, call)
        except Exception as e:
            return self._error_reply(spec, e)
        finally:
            self.core.pop_task_context(old_ctx)
            del args, kwargs, pins
        return await self._package_results(spec, result)

    def _error_reply(self, spec, e: Exception, cancelled=False):
        self.core.task_events.record(spec, "FAILED", error=str(e)[:500])
        return {
            "status": "error",
            "error": format_exception(e),
            "exception": serialization.serialize_inline(e)[0],
            "app_error": True,
            "cancelled": cancelled,
        }

    async def _package_results(self, spec: dict, result: Any) -> dict:
        num_returns = spec["num_returns"]
        if num_returns == 1:
            values = [result]
        elif num_returns == 0:
            values = []
        else:
            values = list(result)
            if len(values) != num_returns:
                return self._error_reply(
                    spec,
                    ValueError(
                        f"task declared num_returns={num_returns} but returned "
                        f"{len(values)} values"
                    ),
                )
        return_ids = return_object_ids(spec)
        results = []
        loop = asyncio.get_running_loop()
        for oid, value in zip(return_ids, values):
            payload, _refs = await loop.run_in_executor(
                None, serialization.serialize_inline, value
            )
            size = len(payload["p"]) + sum(len(b) for b in payload["b"])
            if size <= self.core.inline_threshold:
                results.append({"inline": payload})
            else:
                meta = await self.core.put_return_to_plasma(oid, payload, spec)
                results.append({"plasma": meta})
        self.core.task_events.record(spec, "FINISHED")
        return {"status": "ok", "results": results}

    def cancel(self, task_id: bytes):
        self._cancelled.add(task_id)
        self.core.try_cancel_running(task_id)

    def shutdown(self):
        self._normal_pool.shutdown(wait=False)
        if self._actor_pool:
            self._actor_pool.shutdown(wait=False)
