"""Task execution inside a worker: normal tasks, actor creation, actor tasks.

Counterpart of the reference's TaskReceiver + scheduling queues
(reference: src/ray/core_worker/transport/task_receiver.cc:36,
actor_scheduling_queue.h, out_of_order_actor_scheduling_queue.h, fiber.h):

- Normal tasks run one-at-a-time on a dedicated thread (the raylet leases this
  worker exclusively, so there is never more than one in flight).
- Actor tasks are totally ordered *per caller* via sequence numbers with a
  reorder buffer, then dispatched to either a thread pool of size
  ``max_concurrency`` (sync actors) or a private asyncio loop (async actors —
  the reference uses fibers; an event loop is the Python-native equivalent).
"""

from __future__ import annotations

import asyncio
import inspect
import threading
import traceback
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Optional

from ray_tpu._private import serialization
from ray_tpu._private.ids import ObjectID, TaskID
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.task_spec import TASK_ACTOR, return_object_ids
from ray_tpu.exceptions import TaskCancelledError, format_exception


class _AsyncActorLoop:
    """Private event loop thread for async actors."""

    def __init__(self):
        self.loop = asyncio.new_event_loop()
        t = threading.Thread(target=self._run, name="rtpu-async-actor", daemon=True)
        t.start()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()


class Executor:
    def __init__(self, core):
        self.core = core  # CoreWorker
        self._normal_pool = ThreadPoolExecutor(max_workers=1, thread_name_prefix="rtpu-exec")
        # Persistent elastic pool for batched pushes: ThreadPoolExecutor
        # only spawns a new thread when no idle one exists, so this reuses
        # threads across batches instead of paying thread creation per RPC,
        # while still giving each in-flight task its own thread (tasks in a
        # batch may synchronize with each other).
        from ray_tpu._private.config import RTPU_CONFIG

        self._batch_pool = ThreadPoolExecutor(
            max_workers=RTPU_CONFIG.batch_exec_max_threads,
            thread_name_prefix="rtpu-batch",
        )
        self._batch_inflight = 0  # grows the pool cap, see handle_PushTasks
        # actor state
        self.actor_instance = None
        self.actor_id: Optional[bytes] = None
        self.actor_is_async = False
        self._actor_pool: Optional[ThreadPoolExecutor] = None
        self._actor_loop: Optional[_AsyncActorLoop] = None
        self._actor_sem: Optional[asyncio.Semaphore] = None
        # per-caller ordering: caller_id -> {"expected": int|None, "buffer": {seq: (spec, fut)}}
        self._callers: Dict[bytes, dict] = {}
        self._cancelled: set = set()
        self._current_task_name = ""
        # serial-actor pump (max_concurrency == 1, the default): one
        # long-lived consumer in the actor thread executes queued tasks
        # back-to-back and delivers replies in batches, instead of paying a
        # threadpool submit + future chain + loop wakeup per call
        self._serial = False
        self._run_q: deque = deque()
        self._pump_lock = threading.Lock()
        self._pump_running = False
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        # reply delivery: pump appends here and schedules ONE loop drain
        # per burst — delivery is immediate when the loop is idle and
        # batches naturally when it is busy, so a completed task's reply is
        # never held behind a slow successor
        self._done_q: deque = deque()
        self._done_scheduled = False

    # ----------------------------------------------------------- normal path

    async def execute_normal(self, spec: dict) -> dict:
        return await self._execute(spec, self._normal_pool)

    # ------------------------------------------------------------ actor path

    async def create_actor(self, spec: dict, actor_id: bytes) -> dict:
        loop = asyncio.get_running_loop()
        self._loop = loop  # the pump + direct intake need it before any task
        # functions.fetch may hit the GCS KV through the blocking client — keep
        # it off the IO loop.
        cls = await loop.run_in_executor(None, self.core.functions.fetch, spec["fn_key"])
        args, kwargs, pins = await self._resolve_args(spec)

        def make():
            return cls(*args, **kwargs)

        try:
            self.actor_instance = await loop.run_in_executor(self._normal_pool, make)
        except Exception as e:
            return {"ok": False, "error": format_exception(e)}
        finally:
            del args, kwargs, pins
        self.actor_id = actor_id
        self.core.on_became_actor(actor_id, spec)
        self.actor_is_async = any(
            inspect.iscoroutinefunction(getattr(type(self.actor_instance), m, None))
            for m in dir(type(self.actor_instance))
            if not m.startswith("__")
        )
        max_conc = spec.get("max_concurrency", 1)
        if self.actor_is_async:
            self._actor_loop = _AsyncActorLoop()
            self._actor_sem = None  # created lazily on the actor loop
            self._actor_max_conc = max_conc if max_conc > 1 else 1000
        else:
            self._actor_pool = ThreadPoolExecutor(
                max_workers=max(1, max_conc), thread_name_prefix="rtpu-actor"
            )
            self._serial = max_conc <= 1
        return {"ok": True}

    def _enqueue_actor_task(self, spec: dict) -> "asyncio.Future":
        """Order by (caller_id, seq_no); returns a future for the reply."""
        caller = spec.get("caller_id", b"")
        seq = spec.get("seq_no", 0)
        state = self._callers.setdefault(caller, {"expected": None, "buffer": {}})
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        state["buffer"][seq] = (spec, fut)
        if state["expected"] is None:
            state["expected"] = seq
        # drain in order
        while state["expected"] in state["buffer"]:
            s, f = state["buffer"].pop(state["expected"])
            state["expected"] += 1
            asyncio.ensure_future(self._run_actor_task(s, f))
        return fut

    async def push_actor_task(self, spec: dict) -> dict:
        return await self._enqueue_actor_task(spec)

    def enqueue_actor_tasks(self, specs: list) -> list:
        """Batched ordered push: register every spec (the reorder buffer
        sees the whole batch) and return the per-task reply futures —
        the caller streams replies back as they resolve, so one slow task
        never holds a finished peer's reply."""
        return [self._enqueue_actor_task(s) for s in specs]

    async def _run_actor_task(self, spec: dict, fut: asyncio.Future):
        if self._serial and spec.get("type") == TASK_ACTOR:
            with self._pump_lock:
                self._run_q.append((spec, fut))
                start = not self._pump_running
                if start:
                    self._pump_running = True
            if start:
                self._actor_pool.submit(self._serial_pump)
            return
        try:
            if self.actor_is_async:
                reply = await self._execute_async_actor(spec)
            else:
                reply = await self._execute(spec, self._actor_pool)
        except Exception as e:
            reply = {"status": "error", "error": format_exception(e), "app_error": False}
        if not fut.done():
            fut.set_result(reply)

    # ------------------------------------------------- serial-actor pump

    def intake_direct(self, specs: list, reply_cb):
        """Direct-channel intake (runs on the channel's reader thread).
        Queues the batch for the serial pump — one enqueue+wake for the
        whole recv batch, no io-loop hop (direct_channel.py's reason for
        being). The pump itself always runs on the actor's single
        _actor_pool thread: executing inline here would be one wake
        cheaper, but a serial actor's tasks must stay on ONE thread for
        its whole lifetime (thread-bound user state — sqlite handles,
        threading.local caches; the reference runs all actor tasks on the
        actor's main thread). Ordering: the channel is FIFO and, once
        active, carries every task for this caller, so arrival order is
        submission order."""
        with self._pump_lock:
            for spec in specs:
                self._run_q.append((spec, reply_cb))
            start = not self._pump_running
            if start:
                self._pump_running = True
        if start:
            self._actor_pool.submit(self._serial_pump)

    def _serial_pump(self):
        """Consumer loop in the (single) actor thread — or, for
        direct-channel tasks, in the channel's reader thread that claimed
        the pump. Executes queued tasks back-to-back. Replies sink either
        to the io loop (loop-path tasks: batched _done_q + one pending
        wakeup) or straight onto the direct channel (callable sink) from
        this thread."""
        while True:
            with self._pump_lock:
                if not self._run_q:
                    self._pump_running = False
                    return
                spec, sink = self._run_q.popleft()
            reply = self._run_one_serial(spec)
            if callable(sink):
                if isinstance(reply, tuple) and reply[0] == "plasma":
                    # Large return: the plasma put needs the io loop; the
                    # channel write then happens on a pool thread — the io
                    # loop must never block in sendall.
                    asyncio.run_coroutine_threadsafe(
                        self._finish_direct(spec, sink, reply[1]), self._loop)
                else:
                    sink(spec, reply)
                continue
            self._done_q.append((spec, sink, reply))
            with self._pump_lock:
                schedule = not self._done_scheduled
                if schedule:
                    self._done_scheduled = True
            if schedule:
                self._loop.call_soon_threadsafe(self._drain_done)

    async def _finish_direct(self, spec: dict, sink, payloads):
        try:
            reply = await self._finish_results(spec, payloads)
        except Exception as e:
            reply = self._error_reply(spec, e)
        # sink -> pipe.send -> blocking sendall: keep it off the io loop.
        asyncio.get_running_loop().run_in_executor(None, sink, spec, reply)

    def _drain_done(self):
        """On the io loop: resolve queued reply futures."""
        with self._pump_lock:
            self._done_scheduled = False
        while True:
            try:
                spec, fut, reply = self._done_q.popleft()
            except IndexError:
                return
            if isinstance(reply, tuple) and reply[0] == "plasma":
                asyncio.ensure_future(
                    self._finish_deferred(spec, fut, reply[1])
                )
            elif not fut.done():
                fut.set_result(reply)

    def _run_one_serial(self, spec: dict):
        """Execute one actor task entirely in the actor thread: resolve
        args, run, serialize. Only plasma-bound returns defer to the loop."""
        task_id = spec["task_id"]
        if task_id in self._cancelled:
            self._cancelled.discard(task_id)
            return self._error_reply(spec, TaskCancelledError(), cancelled=True)
        try:
            fn = self._actor_method(spec["method_name"])
            args, kwargs, pins = self._decode_args(
                spec,
                lambda ref: asyncio.run_coroutine_threadsafe(
                    self.core.async_get_one(ref), self._loop
                ).result(),
            )
        except Exception as e:
            return {"status": "error", "error": format_exception(e),
                    "app_error": False}
        self.core.task_events.record(spec, "RUNNING")
        old_ctx = self.core.push_task_context(spec)
        try:
            result = self._call_with_trace(spec, fn, args, kwargs)
            payloads = self._serialize_returns(spec, result)
        except Exception as e:
            return self._error_reply(spec, e)
        finally:
            self.core.pop_task_context(old_ctx)
            del args, kwargs, pins
        if all(size <= self.core.inline_threshold for _, size in payloads):
            self.core.task_events.record(spec, "FINISHED")
            return {"status": "ok",
                    "results": [
                        {"inline": serialization.inline_payload(p, bufs)}
                        for (p, bufs), _ in payloads
                    ]}
        return ("plasma", payloads)

    async def _finish_deferred(self, spec: dict, fut: asyncio.Future, payloads):
        try:
            reply = await self._finish_results(spec, payloads)
        except Exception as e:
            reply = self._error_reply(spec, e)
        if not fut.done():
            fut.set_result(reply)


    def _actor_method(self, method_name):
        """Resolve an actor method; `__ray_call__` runs an arbitrary function
        against the instance (reference: actor.__ray_call__.remote(fn))."""
        if method_name == "__ray_call__":
            inst = self.actor_instance
            return lambda fn, *a, **kw: fn(inst, *a, **kw)
        return getattr(self.actor_instance, method_name)

    async def _execute_async_actor(self, spec: dict) -> dict:
        method_name = spec["method_name"]
        args, kwargs, pins = await self._resolve_args(spec)
        method = self._actor_method(method_name)
        outer = asyncio.get_running_loop()
        result_fut = outer.create_future()

        sem_holder = self

        async def run_on_actor_loop():
            tctx = spec.get("trace_ctx")
            if tctx:
                from ray_tpu.util import tracing

                # the spec-borne enabled bit short-circuits the KV TTL:
                # spans in this task (and its immediate children) record
                # even in a worker whose cached flag is stale/cold
                tracing._mark_enabled()
                tracing.set_context({
                    k: v for k, v in tctx.items() if k != "enabled"
                })  # task-local contextvar copy
            if sem_holder._actor_sem is None:
                sem_holder._actor_sem = asyncio.Semaphore(sem_holder._actor_max_conc)
            async with sem_holder._actor_sem:
                if inspect.iscoroutinefunction(method):
                    return await method(*args, **kwargs)
                return method(*args, **kwargs)

        def done_cb(f):
            def transfer():
                if result_fut.done():
                    return
                if f.cancelled():
                    result_fut.set_exception(TaskCancelledError())
                elif f.exception() is not None:
                    result_fut.set_exception(f.exception())
                else:
                    result_fut.set_result(f.result())

            outer.call_soon_threadsafe(transfer)

        inner = asyncio.run_coroutine_threadsafe(run_on_actor_loop(), self._actor_loop.loop)
        inner.add_done_callback(done_cb)
        self.core.register_running_task(spec["task_id"], inner)
        try:
            result = await result_fut
            return await self._package_results(spec, result)
        except Exception as e:
            return self._error_reply(spec, e)
        finally:
            self.core.unregister_running_task(spec["task_id"])
            del args, kwargs, pins

    # --------------------------------------------------------------- shared

    def _decode_args(self, spec: dict, resolve_ref):
        """Deserialize wire args. resolve_ref(ObjectRef) -> value supplies
        top-level ref args (style — await-bridged, blocking — is the
        caller's choice); None is fine when the spec has no ref args."""
        args: list = []
        kwargs: dict = {}
        pins = []  # keep plasma pin handles alive for the call duration
        for kind, key, wire in spec["args"]:
            if "v" in wire:
                val, _refs = serialization.deserialize_inline(wire["v"])
            elif "ref" in wire:
                id_bytes, owner = wire["ref"]
                ref = ObjectRef(ObjectID(id_bytes), tuple(owner) if owner else None)
                val = resolve_ref(ref)
                pins.append(val)
            else:
                raise ValueError(f"bad wire arg {wire}")
            if kind == "p":
                args.append(val)
            else:
                kwargs[key] = val
        return args, kwargs, pins

    async def _resolve_args(self, spec: dict):
        """IO-loop arg resolution: refs fetch asynchronously first, then the
        shared decode runs with them pre-resolved."""
        resolved: Dict[bytes, Any] = {}
        for _kind, _key, wire in spec["args"]:
            if "ref" in wire:
                id_bytes, owner = wire["ref"]
                ref = ObjectRef(ObjectID(id_bytes), tuple(owner) if owner else None)
                resolved[id_bytes] = await self.core.async_get_one(ref)
        return self._decode_args(
            spec, lambda r: resolved[r.object_id().binary()]
        )

    def _call_with_trace(self, spec: dict, fn, args, kwargs):
        """Run fn under the caller's propagated trace context (reference:
        _ray_trace_ctx kwarg propagation) in the current thread."""
        tctx = spec.get("trace_ctx")
        if tctx:
            from ray_tpu.util import tracing

            tracing._mark_enabled()  # spec-borne enabled bit beats KV TTL
            tracing.set_context(
                {k: v for k, v in tctx.items() if k != "enabled"})
        try:
            return fn(*args, **kwargs)
        finally:
            if tctx:
                tracing.set_context(None)

    async def execute_batch(self, specs) -> list:
        """Execute a PushTasks batch: ONE pooled thread runs the tasks
        back-to-back (run_in_executor per task cost ~40 µs of submit +
        wakeup — the dominant worker-side cost for tiny tasks), with a
        spill-on-block escape hatch: if the serial runner makes no progress
        for 15 ms (a task is blocking, likely synchronizing with a
        batch-mate), every remaining task gets its own thread — restoring
        the tasks-own-a-thread semantics separate leases would have given
        them. Claims make serial/spilled execution race-free."""
        loop = asyncio.get_running_loop()
        n = len(specs)
        prepared: list = [None] * n
        replies: list = [None] * n

        async def _prep(i, spec):
            task_id = spec["task_id"]
            if task_id in self._cancelled:
                self._cancelled.discard(task_id)
                replies[i] = self._error_reply(
                    spec, TaskCancelledError(), cancelled=True
                )
                return
            try:
                fn = self.core.functions.fetch_cached(spec["fn_key"])
                if fn is None:
                    fn = await loop.run_in_executor(
                        None, self.core.functions.fetch, spec["fn_key"]
                    )
                args, kwargs, pins = await self._resolve_args(spec)
            except Exception as e:
                replies[i] = {"status": "error",
                              "error": format_exception(e),
                              "app_error": False}
                return
            prepared[i] = (fn, args, kwargs, pins)

        # Resolve all tasks' ref args concurrently (a batch of plasma/borrow
        # fetches must overlap, not serialize).
        await asyncio.gather(*(_prep(i, s) for i, s in enumerate(specs)))

        todo = [i for i in range(n) if prepared[i] is not None]
        outcomes: list = [None] * n
        if todo:
            claim_lock = threading.Lock()
            claimed: set = set()

            def run_one(i):
                with claim_lock:
                    if i in claimed:
                        return
                    claimed.add(i)
                spec = specs[i]
                fn, args, kwargs, pins = prepared[i]
                self.core.task_events.record(spec, "RUNNING")
                old_ctx = self.core.push_task_context(spec)
                try:
                    result = self._call_with_trace(spec, fn, args, kwargs)
                    outcomes[i] = ("ok", self._serialize_returns(spec, result))
                except Exception as e:
                    outcomes[i] = ("err", e)
                finally:
                    self.core.pop_task_context(old_ctx)
                    prepared[i] = None  # drop args/pins promptly

            def run_serial():
                for i in todo:
                    run_one(i)

            pool = self._batch_pool
            # The serial runner occupies a pool thread for the whole batch —
            # account for it (and grow the cap) so many concurrently-blocked
            # batches can't starve each other's spills of threads.
            self._batch_inflight += 1
            if self._batch_inflight > pool._max_workers:
                pool._max_workers = self._batch_inflight + 16
            serial_fut = loop.run_in_executor(pool, run_serial)
            try:
                last_progress = -1
                while True:
                    try:
                        await asyncio.wait_for(asyncio.shield(serial_fut), 0.015)
                        break
                    except asyncio.TimeoutError:
                        pass
                    with claim_lock:
                        progress = len(claimed)
                    if progress > last_progress:
                        # still advancing — a batch of short tasks merely
                        # totals >15 ms; keep it serial and re-arm
                        last_progress = progress
                        continue
                    # stalled: the claimed task is blocking (likely on a
                    # batch-mate) — give the unclaimed remainder their own
                    # threads; claims keep serial/spilled execution disjoint
                    with claim_lock:
                        unclaimed = [i for i in todo if i not in claimed]
                    if not unclaimed:
                        await serial_fut  # last task just runs long
                        break
                    self._batch_inflight += len(unclaimed)
                    if self._batch_inflight > pool._max_workers:
                        pool._max_workers = self._batch_inflight + 16
                    try:
                        spills = [
                            loop.run_in_executor(pool, run_one, i)
                            for i in unclaimed
                        ]
                        await asyncio.gather(serial_fut, *spills)
                    finally:
                        self._batch_inflight -= len(unclaimed)
                    break
            finally:
                self._batch_inflight -= 1

        for i in todo:
            status, val = outcomes[i]
            if status == "err":
                replies[i] = self._error_reply(specs[i], val)
            else:
                try:
                    replies[i] = await self._finish_results(specs[i], val)
                except Exception as e:
                    replies[i] = self._error_reply(specs[i], e)
        return replies

    async def _execute(self, spec: dict, pool: ThreadPoolExecutor) -> dict:
        task_id = spec["task_id"]
        if task_id in self._cancelled:
            self._cancelled.discard(task_id)
            return self._error_reply(spec, TaskCancelledError(), cancelled=True)
        loop = asyncio.get_running_loop()
        try:
            if spec["type"] == TASK_ACTOR:
                fn = self._actor_method(spec["method_name"])
            else:
                # cache hit is the common case after the first execution —
                # skip the threadpool hop the blocking KV fetch needs
                fn = self.core.functions.fetch_cached(spec["fn_key"])
                if fn is None:
                    fn = await loop.run_in_executor(
                        None, self.core.functions.fetch, spec["fn_key"]
                    )
            args, kwargs, pins = await self._resolve_args(spec)
        except Exception as e:
            return {"status": "error", "error": format_exception(e), "app_error": False}

        self.core.task_events.record(spec, "RUNNING")
        old_ctx = self.core.push_task_context(spec)

        def call():
            # Serialize the returns in the execution thread too: pushing
            # them back through run_in_executor costs a loop round-trip per
            # task (the reference serializes in the executing C++ thread,
            # core_worker.cc HandlePushTask).
            result = self._call_with_trace(spec, fn, args, kwargs)
            return self._serialize_returns(spec, result)

        try:
            payloads = await loop.run_in_executor(pool, call)
        except Exception as e:
            return self._error_reply(spec, e)
        finally:
            self.core.pop_task_context(old_ctx)
            del args, kwargs, pins
        return await self._finish_results(spec, payloads)

    def _error_reply(self, spec, e: Exception, cancelled=False):
        self.core.task_events.record(spec, "FAILED", error=str(e)[:500])
        return {
            "status": "error",
            "error": format_exception(e),
            "exception": serialization.serialize_inline(e)[0],
            "app_error": True,
            "cancelled": cancelled,
        }

    def _serialize_returns(self, spec: dict, result: Any) -> list:
        """Serialize return values (runs in the execution thread)."""
        num_returns = spec["num_returns"]
        if num_returns == 1:
            values = [result]
        elif num_returns == 0:
            values = []
        else:
            values = list(result)
            if len(values) != num_returns:
                raise ValueError(
                    f"task declared num_returns={num_returns} but returned "
                    f"{len(values)} values"
                )
        out = []
        for value in values:
            # Keep the raw protocol-5 buffer views: plasma-bound returns
            # stream them straight into shm (put_return_to_plasma) and only
            # inline returns materialize bytes (_finish_results).
            p, bufs, _refs = serialization.serialize(value)
            size = len(p) + serialization.buffers_nbytes(bufs)
            out.append(((p, bufs), size))
        return out

    async def _finish_results(self, spec: dict, payloads: list) -> dict:
        """Build the reply from pre-serialized returns (runs on the loop —
        the plasma path needs it)."""
        return_ids = return_object_ids(spec)
        results = []
        for oid, ((p, bufs), size) in zip(return_ids, payloads):
            if size <= self.core.inline_threshold:
                results.append({"inline": serialization.inline_payload(p, bufs)})
            else:
                meta = await self.core.put_return_to_plasma(oid, (p, bufs), spec)
                results.append({"plasma": meta})
        self.core.task_events.record(spec, "FINISHED")
        return {"status": "ok", "results": results}

    async def _package_results(self, spec: dict, result: Any) -> dict:
        """Serialize-and-reply for results produced on the loop (async
        actors); sync paths serialize in the execution thread instead."""
        loop = asyncio.get_running_loop()
        try:
            payloads = await loop.run_in_executor(
                None, self._serialize_returns, spec, result
            )
        except Exception as e:
            return self._error_reply(spec, e)
        return await self._finish_results(spec, payloads)

    def cancel(self, task_id: bytes):
        self._cancelled.add(task_id)
        self.core.try_cancel_running(task_id)

    def shutdown(self):
        self._normal_pool.shutdown(wait=False)
        self._batch_pool.shutdown(wait=False)
        if self._actor_pool:
            self._actor_pool.shutdown(wait=False)
