"""Memory observability plane: shared helpers for building, persisting and
rendering per-process memory reports.

The ledger itself lives in ``reference_counter.ReferenceCounter`` (per owned
ref: size, owner task, creation callsite, pin state, age); this module holds
everything around it that more than one process role needs:

  - ``callsite()``       cheap creation-callsite capture for ``ray.put``-
                         shaped paths (first frame outside ray_tpu);
  - ``process_rss()``    this process's resident set size, no psutil needed;
  - ``build_worker_report()``  one worker/driver's full memory report — the
                         payload of the worker-side ``GetMemoryReport`` RPC
                         and of the periodic on-disk snapshot that survives
                         SIGKILL (OOM forensics);
  - ``write_snapshot()`` / ``read_snapshot()``  the snapshot file protocol
                         (``<session>/logs/memory_worker-<pid>.json``),
                         mirroring the PR 3 flight-recorder tail files;
  - ``format_top_holders()``  compact text rendering attached to a dead
                         worker's death report → ``ActorDiedError``.

Everything here is pull-only: nothing is computed until a report is asked
for, and the hot-path cost of the plane is limited to the fields
``reference_counter`` already writes plus one frame-walk per ``ray.put``
(disable with ``RTPU_memory_ledger_callsite=0``).
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import List, Optional

_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def callsite(depth: int = 12) -> str:
    """``file.py:lineno`` of the first stack frame outside the ray_tpu
    package — the user line that created the object. Bounded frame walk,
    no traceback objects, ~1 µs; returns "" when everything is internal
    (framework-internal puts) or capture is disabled."""
    from ray_tpu._private.config import RTPU_CONFIG

    if not RTPU_CONFIG.memory_ledger_callsite:
        return ""
    try:
        f = sys._getframe(2)
    except ValueError:
        return ""
    for _ in range(depth):
        if f is None:
            return ""
        fname = f.f_code.co_filename
        if not fname.startswith(_PKG_DIR):
            return f"{os.path.basename(fname)}:{f.f_lineno}"
        f = f.f_back
    return ""


def process_rss(pid: Optional[int] = None) -> int:
    """Resident set size in bytes via /proc (zero-dependency; psutil is the
    raylet's fallback for processes it doesn't own)."""
    path = f"/proc/{pid}/statm" if pid else "/proc/self/statm"
    try:
        with open(path) as f:
            return int(f.read().split()[1]) * _PAGE
    except (OSError, ValueError, IndexError):
        return 0


def build_worker_report(core, limit: int = 0) -> dict:
    """One process's memory report: identity + RSS + ownership ledger.

    ``core`` is a CoreWorker; ``limit`` > 0 keeps the top holders by size
    (the RPC default comes from ``RTPU_memory_report_top_n``).
    """
    total, plasma = core.refs.owned_bytes()
    stats = core.refs.stats()
    return {
        "worker_id": core.worker_id.binary(),
        "pid": os.getpid(),
        "mode": core.mode,
        "actor_id": core.actor_id or b"",
        "job_id": core.job_id.binary(),
        "rss_bytes": process_rss(),
        "owned_refs": stats["owned"],
        "borrowed_refs": stats["borrowed"],
        "owned_bytes": total,
        "owned_plasma_bytes": plasma,
        "memory_store_entries": core.memory_store.size(),
        "time": time.time(),
        "ledger": core.refs.ledger(limit=limit),
    }


# --------------------------------------------------------- snapshot files


def snapshot_path(session_dir: str, pid: int) -> str:
    return os.path.join(session_dir, "logs", f"memory_worker-{pid}.json")


def write_snapshot(core, top_n: int = 10) -> bool:
    """Persist a compact report for this worker so the raylet can attach
    the last-known memory state to an OOM/SIGKILL death report (the same
    no-exit-handler-needed pattern as the flight-recorder tail files).
    Atomic replace: the raylet may read concurrently with a kill."""
    if not core.session_dir:
        return False
    report = build_worker_report(core, limit=top_n)
    path = snapshot_path(core.session_dir, os.getpid())
    tmp = f"{path}.tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(_jsonable(report), f)
        os.replace(tmp, path)
        return True
    except OSError:
        return False


def read_snapshot(session_dir: str, pid: int, max_age_s: float = 0) -> Optional[dict]:
    path = snapshot_path(session_dir, pid)
    try:
        with open(path) as f:
            snap = json.load(f)
    except (OSError, ValueError):
        return None
    if max_age_s and time.time() - float(snap.get("time", 0)) > max_age_s:
        return None
    return snap


def _jsonable(obj):
    if isinstance(obj, (bytes, bytearray)):
        return bytes(obj).hex()
    if isinstance(obj, dict):
        return {k: _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    return obj


# ------------------------------------------------------------- rendering


def _fmt_bytes(n) -> str:
    n = float(n or 0)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}TiB"


def format_top_holders(report: dict, limit: int = 5) -> str:
    """Compact multi-line rendering of a worker report for death reports —
    what an OOM-killed actor's ActorDiedError shows as its final memory
    state."""
    rss = report.get("rss_bytes", 0)
    lines = [
        f"  rss={_fmt_bytes(rss)} owned={report.get('owned_refs', 0)} refs"
        f"/{_fmt_bytes(report.get('owned_bytes', 0))}"
        f" (plasma {_fmt_bytes(report.get('owned_plasma_bytes', 0))})"
    ]
    for row in (report.get("ledger") or [])[:limit]:
        oid = row.get("object_id", "")
        oid_hex = oid if isinstance(oid, str) else bytes(oid).hex()
        where = row.get("callsite") or "?"
        lines.append(
            f"  {oid_hex[:12]} {_fmt_bytes(row.get('size', 0))}"
            f" age={row.get('age_s', 0):.0f}s"
            f"{' plasma' if row.get('plasma') else ''} @ {where}"
        )
    return "\n".join(lines)
