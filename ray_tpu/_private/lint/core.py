"""Shared machinery of the invariant lint plane: findings, file loading,
``# lint: allow(...)`` pragmas, and the committed baseline.

A *finding* is (rule, path, line, message, snippet). The baseline stores a
content fingerprint instead of a line number — (rule, relative path,
normalized source line, occurrence index) hashed — so unrelated edits that
shift line numbers don't invalidate accepted findings, while editing the
offending line itself does (the finding then re-surfaces for re-review,
which is the point).
"""

from __future__ import annotations

import ast
import hashlib
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

# `# lint: allow(rule-a, rule-b) -- optional justification`
_PRAGMA_RE = re.compile(r"#\s*lint:\s*allow\(([^)]*)\)")


@dataclass
class Finding:
    rule: str
    path: str  # repo-relative, '/'-separated
    line: int
    message: str
    snippet: str = ""

    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def render(self) -> str:
        out = f"{self.path}:{self.line}: {self.rule}: {self.message}"
        if self.snippet:
            out += f"\n    {self.snippet}"
        return out

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "snippet": self.snippet,
        }


@dataclass
class SourceFile:
    """One parsed module: source text, AST, and suppression pragmas."""

    path: str  # absolute
    rel: str  # repo-relative, '/'-separated
    text: str
    tree: ast.AST
    lines: List[str] = field(default_factory=list)
    # line number -> set of allowed rule ids on that line
    pragmas: Dict[int, Set[str]] = field(default_factory=dict)

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()[:120]
        return ""

    def allowed(self, line: int, rule: str) -> bool:
        """A pragma suppresses its own line and, when the pragma stands on
        a line of its own, the first following non-comment line."""
        rules = self.pragmas.get(line)
        if rules is not None and (rule in rules or "*" in rules):
            return True
        for pline, rules in self.pragmas.items():
            if rule not in rules and "*" not in rules:
                continue
            if pline >= line:
                continue
            # pragma-only line: walk forward over blank/comment lines
            src = self.lines[pline - 1].strip() if pline <= len(self.lines) else ""
            if not src.startswith("#"):
                continue
            nxt = pline + 1
            while nxt <= len(self.lines) and (
                not self.lines[nxt - 1].strip()
                or self.lines[nxt - 1].strip().startswith("#")
            ):
                nxt += 1
            if nxt == line:
                return True
        return False


def _parse_pragmas(text: str) -> Dict[int, Set[str]]:
    """Extract ``# lint: allow(...)`` pragmas via the tokenizer so strings
    containing the pragma text don't count."""
    out: Dict[int, Set[str]] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type != tokenize.COMMENT:
                continue
            m = _PRAGMA_RE.search(tok.string)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            out.setdefault(tok.start[0], set()).update(rules)
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass
    return out


def load_source(path: str, root: str) -> Optional[SourceFile]:
    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            text = f.read()
        tree = ast.parse(text, filename=path)
    except (OSError, SyntaxError, ValueError):
        return None
    rel = os.path.relpath(path, root).replace(os.sep, "/")
    return SourceFile(
        path=path,
        rel=rel,
        text=text,
        tree=tree,
        lines=text.splitlines(),
        pragmas=_parse_pragmas(text),
    )


def collect_files(paths: Iterable[str], root: str) -> List[SourceFile]:
    """Every .py under ``paths`` (files or directories), parsed. Order is
    deterministic (sorted walk) so finding order and baseline occurrence
    indices are stable run to run."""
    seen: Set[str] = set()
    files: List[SourceFile] = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            cands = [p]
        else:
            cands = []
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d not in ("__pycache__", ".git", "node_modules")
                )
                cands.extend(
                    os.path.join(dirpath, f)
                    for f in sorted(filenames)
                    if f.endswith(".py")
                )
        for c in cands:
            if c in seen:
                continue
            seen.add(c)
            sf = load_source(c, root)
            if sf is not None:
                files.append(sf)
    return files


# --------------------------------------------------------------- baseline


def fingerprint(finding: Finding, occurrence: int) -> str:
    """Stable id of an accepted finding: rule + file + normalized offending
    line + occurrence index among identical triples (so two identical
    lines in one file baseline independently)."""
    norm = " ".join(finding.snippet.split())
    h = hashlib.sha1(
        f"{finding.rule}|{finding.path}|{norm}|{occurrence}".encode()
    ).hexdigest()[:16]
    return h


def fingerprints(findings: List[Finding]) -> List[Tuple[Finding, str]]:
    counts: Dict[Tuple[str, str, str], int] = {}
    out = []
    for f in findings:
        key = (f.rule, f.path, " ".join(f.snippet.split()))
        n = counts.get(key, 0)
        counts[key] = n + 1
        out.append((f, fingerprint(f, n)))
    return out


def load_baseline(path: str) -> Dict[str, dict]:
    """fingerprint -> entry. Tolerates a missing file (empty baseline)."""
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError, ValueError):
        return {}
    out = {}
    for entry in data.get("findings", []):
        fp = entry.get("fingerprint")
        if fp:
            out[fp] = entry
    return out


def save_baseline(path: str, findings: List[Finding]) -> int:
    entries = [
        {
            "fingerprint": fp,
            "rule": f.rule,
            "path": f.path,
            "snippet": " ".join(f.snippet.split()),
        }
        for f, fp in fingerprints(findings)
    ]
    doc = {
        "comment": (
            "Accepted pre-existing lint findings (ray-tpu lint --baseline). "
            "Regenerate with: ray-tpu lint --update-baseline. New findings "
            "not in this file fail CI; editing an offending line re-surfaces "
            "its finding for review."
        ),
        "findings": entries,
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    return len(entries)


def apply_baseline(
    findings: List[Finding], baseline: Dict[str, dict]
) -> Tuple[List[Finding], List[Finding]]:
    """Split findings into (new, accepted) against a loaded baseline."""
    new: List[Finding] = []
    accepted: List[Finding] = []
    for f, fp in fingerprints(findings):
        (accepted if fp in baseline else new).append(f)
    return new, accepted


# ------------------------------------------------------------ AST helpers


def call_name(node: ast.Call) -> str:
    """Dotted name of a call target: 'time.sleep', '.append' (unknown
    receiver), 'open'. Best-effort, literal-attribute chains only."""
    f = node.func
    parts: List[str] = []
    while isinstance(f, ast.Attribute):
        parts.append(f.attr)
        f = f.value
    if isinstance(f, ast.Name):
        parts.append(f.id)
    else:
        parts.append("")
    return ".".join(reversed(parts))


def const_str(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def iter_docstrings(tree: ast.AST):
    """Yield the Constant nodes that are docstrings (module/class/def), so
    scanners can exclude prose from code-literal scans."""
    for node in ast.walk(tree):
        if isinstance(
            node,
            (ast.Module, ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef),
        ):
            body = getattr(node, "body", [])
            if (
                body
                and isinstance(body[0], ast.Expr)
                and isinstance(body[0].value, ast.Constant)
                and isinstance(body[0].value.value, str)
            ):
                yield body[0].value
