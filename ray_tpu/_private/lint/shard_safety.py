"""Shard-safety / thread-ownership analyzer.

The sharded RPC reactor (rpc.py, PR 13) holds one invariant by
construction: handler coroutines hop to the server's HOME loop unless the
method was opted in via ``set_shard_safe({...})``, in which case the
handler runs on whichever shard thread owns the connection — concurrently
with the home loop and with every other shard. That opt-in is a claim of
thread safety that nothing verified until now. Two directions:

``shard-safe-unresolved``
    every name passed to ``set_shard_safe({...})`` must resolve to a
    ``handle_<name>`` method of the enclosing class. A typo'd name is not
    an error at runtime — the method silently keeps hopping home, which is
    *correct but quietly defeats the optimization* (RpcServer also raises
    at registration now; this catches it at lint time, before a cluster
    boots).

``shard-unsafe-mutation``
    the body of a shard-safe handler may mutate ``self`` state only
    lexically inside a ``with self.<lock>:`` block (any attribute/name
    whose final component contains "lock"), or on fields the module
    declares thread-safe in a module-level ``_SHARD_SAFE_FIELDS = {...}``
    set (documented natively-locked state, e.g. the plasma store's
    in-segment mutex). Flagged mutations: ``self.x = / += / del``,
    ``self.x[k] =``, and mutating method calls (append/add/pop/update/
    clear/remove/extend/insert/discard/setdefault/...) on a direct self
    attribute. Aliased mutation (``rec = self._recv[k]; rec[...] = v``)
    is out of scope for a lexical pass — keep shard-safe handlers simple
    enough that this analyzer can read them, that is the discipline.

``shard-home-loop-bypass``
    inside rpc.py itself, a registered handler must only ever be *called*
    from the ``_run_handler`` choke point (which implements the hop).
    Any other call site of a name bound from ``self._handlers`` would
    execute an arbitrary, possibly non-shard-safe handler on the shard
    thread — exactly the bug class the hop exists to prevent.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ray_tpu._private.lint.core import Finding, SourceFile, const_str

_MUTATORS = {
    "append", "appendleft", "add", "pop", "popleft", "popitem", "update",
    "clear", "remove", "extend", "insert", "discard", "setdefault",
    "push", "put_nowait", "sort", "reverse",
}


def _literal_names(node) -> Optional[List[ast.Constant]]:
    """Constant elements of a set/list/tuple/dict-literal argument."""
    if isinstance(node, (ast.Set, ast.List, ast.Tuple)):
        elts = node.elts
    elif isinstance(node, ast.Dict):
        elts = node.keys
    else:
        return None
    out = []
    for e in elts:
        if const_str(e) is None:
            return None  # dynamic registration: out of scope
        out.append(e)
    return out


def _self_attr(node) -> Optional[str]:
    """'x' when node is ``self.x`` (or a subscript/chain rooted there)."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _is_lock_expr(expr) -> bool:
    """``with self._lock:`` / ``with some_lock:`` — the guard we accept."""
    name = None
    if isinstance(expr, ast.Attribute):
        name = expr.attr
    elif isinstance(expr, ast.Name):
        name = expr.id
    elif isinstance(expr, ast.Call):
        return _is_lock_expr(expr.func)  # e.g. self._lock() factories
    return name is not None and "lock" in name.lower()


class _HandlerChecker(ast.NodeVisitor):
    """Walk one handler body tracking lexical lock depth."""

    def __init__(self, sf: SourceFile, handler: str, safe_fields: Set[str]):
        self.sf = sf
        self.handler = handler
        self.safe_fields = safe_fields
        self.lock_depth = 0
        self.findings: List[Finding] = []

    def _flag(self, attr: str, line: int, what: str):
        self.findings.append(Finding(
            "shard-unsafe-mutation", self.sf.rel, line,
            f"shard-safe handler '{self.handler}' {what} 'self.{attr}' "
            "outside a held lock (shard handlers run concurrently with "
            "the home loop; guard with `with self.<lock>:`, add the field "
            "to _SHARD_SAFE_FIELDS, or drop the set_shard_safe opt-in)",
            self.sf.snippet(line)))

    def _check_write(self, target, line: int, what: str):
        if self.lock_depth > 0:
            return
        attr = _self_attr(target)
        if attr is not None and attr not in self.safe_fields:
            self._flag(attr, line, what)

    def visit_With(self, node: ast.With):
        locked = any(_is_lock_expr(i.context_expr) for i in node.items)
        if locked:
            self.lock_depth += 1
        self.generic_visit(node)
        if locked:
            self.lock_depth -= 1

    def visit_Assign(self, node: ast.Assign):
        for t in node.targets:
            self._check_write(t, node.lineno, "assigns")
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        self._check_write(node.target, node.lineno, "mutates")
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            self._check_write(node.target, node.lineno, "assigns")
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete):
        for t in node.targets:
            self._check_write(t, node.lineno, "deletes")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        if self.lock_depth == 0 and isinstance(node.func, ast.Attribute):
            if node.func.attr in _MUTATORS:
                attr = _self_attr(node.func.value)
                if attr is not None and attr not in self.safe_fields:
                    self._flag(attr, node.lineno,
                               f"calls .{node.func.attr}() on")
        self.generic_visit(node)

    # nested defs get their own execution context (executors, callbacks) —
    # don't attribute their writes to the handler's shard thread
    def visit_FunctionDef(self, node):
        pass

    def visit_AsyncFunctionDef(self, node):
        pass

    def visit_Lambda(self, node):
        pass


def _module_safe_fields(sf: SourceFile) -> Set[str]:
    for node in sf.tree.body if isinstance(sf.tree, ast.Module) else []:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "_SHARD_SAFE_FIELDS"
            for t in node.targets
        ):
            names = _literal_names(node.value)
            if names is not None:
                return {n.value for n in names}
    return set()


def _analyze_class(sf: SourceFile, cls: ast.ClassDef,
                   safe_fields: Set[str]) -> List[Finding]:
    findings: List[Finding] = []
    methods: Dict[str, ast.AST] = {
        n.name: n
        for n in cls.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    registrations: List[ast.Call] = []
    for node in ast.walk(cls):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "set_shard_safe"
            and node.args
        ):
            registrations.append(node)
    for call in registrations:
        names = _literal_names(call.args[0])
        if names is None:
            continue
        for name_node in names:
            method = "handle_" + name_node.value
            fn = methods.get(method)
            if fn is None:
                findings.append(Finding(
                    "shard-safe-unresolved", sf.rel, name_node.lineno,
                    f"set_shard_safe('{name_node.value}') does not resolve "
                    f"to a method '{method}' on class {cls.name} — a typo "
                    "here silently keeps the handler hopping home",
                    sf.snippet(name_node.lineno)))
                continue
            checker = _HandlerChecker(sf, method, safe_fields)
            for stmt in fn.body:
                checker.visit(stmt)
            findings.extend(checker.findings)
    return findings


def _analyze_rpc_choke_point(sf: SourceFile) -> List[Finding]:
    """Inside rpc.py: direct calls of self._handlers-bound names anywhere
    but _run_handler."""
    findings: List[Finding] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name == "_run_handler":
            continue
        bound: Set[str] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) and isinstance(
                sub.value, (ast.Call, ast.Subscript)
            ):
                src = sub.value
                target = src.func.value if (
                    isinstance(src, ast.Call)
                    and isinstance(src.func, ast.Attribute)
                    and src.func.attr == "get"
                ) else (src.value if isinstance(src, ast.Subscript) else None)
                if _self_attr(target) == "_handlers":
                    bound.update(
                        t.id for t in sub.targets if isinstance(t, ast.Name)
                    )
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                direct = (
                    isinstance(sub.func, ast.Name) and sub.func.id in bound
                )
                via_subscript = (
                    isinstance(sub.func, ast.Subscript)
                    and _self_attr(sub.func.value) == "_handlers"
                )
                if direct or via_subscript:
                    findings.append(Finding(
                        "shard-home-loop-bypass", sf.rel, sub.lineno,
                        f"registered handler called directly in "
                        f"{node.name}() — only _run_handler may invoke "
                        "handlers (it implements the home-loop hop that "
                        "keeps non-shard-safe state single-threaded)",
                        sf.snippet(sub.lineno)))
    return findings


def analyze(files: List[SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    for sf in files:
        safe_fields = _module_safe_fields(sf)
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(_analyze_class(sf, node, safe_fields))
        if sf.rel.endswith("_private/rpc.py") or sf.rel == "rpc.py":
            findings.extend(_analyze_rpc_choke_point(sf))
    return findings
