"""The invariant lint plane: static analysis over the whole package.

Correctness here increasingly hinges on invariants that live only in
docstrings — the stability contracts (flags, metric names, flight events,
chaos sites), the sharded reactor's thread-ownership rules, and the
never-block-the-loop discipline of the asyncio control plane. The
reference enforces its analogues at build time (``RayConfig`` flags are
generated from ``common/ray_config_def.h``; the RPC surface is
proto-compiled); this package is our equivalent: an AST pass run as
``ray-tpu lint`` and gated in CI.

RULE REFERENCE
--------------
Contract cross-checker (lint/contracts.py):

  flag-undeclared         an ``RTPU_<name>`` read — ``RTPU_CONFIG.<name>``
                          or a ``"RTPU_<name>"`` env literal with
                          lowercase ``<name>`` — names no flag declared in
                          ``_private/config.py`` ``_FLAGS``. (All-caps
                          ``RTPU_FOO`` env vars are infrastructure knobs,
                          exempt.)
  flag-dead               a declared flag nothing in the package reads:
                          dead contract surface — wire it or remove it.
  metric-unregistered     a ``ray_tpu_*`` series is emitted (literal
                          Counter/Gauge/Histogram name, or a raylet/GCS/
                          agent ``(name, labels, value)`` sample tuple)
                          but missing from the metric-name contract
                          docstring in ``util/metrics.py``.
  event-unregistered      a literal ``flight_recorder.record("x.y", ...)``
                          event name is missing from the EVENT-NAME
                          contract docstring in
                          ``_private/flight_recorder.py``.
  chaos-site-unregistered a literal ``chaos.hit("x.y", ...)`` site is
                          missing from the SITE-NAME contract docstring
                          in ``_private/chaos.py``.

Shard-safety / thread-ownership analyzer (lint/shard_safety.py):

  shard-safe-unresolved   a ``set_shard_safe({...})`` name doesn't resolve
                          to a ``handle_<name>`` method on the enclosing
                          class.
  shard-unsafe-mutation   a shard-safe handler mutates ``self`` state
                          outside a ``with self.<lock>:`` block and off
                          the module's ``_SHARD_SAFE_FIELDS`` allowlist.
  shard-home-loop-bypass  rpc.py calls a registered handler anywhere but
                          the ``_run_handler`` choke point that
                          implements the home-loop hop.

Blocking-call detector (lint/blocking.py) — control-plane ``async def``
bodies only (``_private/rpc.py``, ``_private/worker.py``,
``_private/raylet/``, ``_private/gcs/``, ``serve/``):

  blocking-call-in-async  ``time.sleep`` / ``subprocess.run|check_*`` /
                          ``os.system`` / sync DNS/HTTP inside a
                          coroutine.
  blocking-io-in-async    sync ``open()`` / un-awaited socket
                          ``.accept/.connect/.recv/.sendall`` inside a
                          coroutine.
  sync-lock-in-async      un-awaited lock acquisition (``with
                          self._lock:`` or bare ``.acquire()``) inside a
                          coroutine.

SUPPRESSING A FINDING
---------------------
Inline, for accepted-by-design sites (same line or a comment line
immediately above)::

    with self.engine._lock:  # lint: allow(sync-lock-in-async) -- why

or ``# lint: allow(rule-a, rule-b)``; ``allow(*)`` suppresses every rule
on that line. Pre-existing accepted findings live in the committed
baseline instead: ``ray-tpu lint --baseline .lint-baseline.json`` fails
only on findings NOT in the baseline. Regenerate after triaging with
``ray-tpu lint --update-baseline`` — the baseline keys on (rule, file,
source-line content), so editing an offending line re-surfaces its
finding for review while unrelated line drift doesn't.

Run it: ``ray-tpu lint [paths...] [--baseline F] [--json] [--verbose]``.
CI gates on it (.github/workflows/ci.yml); perf/chaos workflows consume
``--json`` output as artifacts.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from ray_tpu._private.lint import blocking, contracts, shard_safety
from ray_tpu._private.lint.core import (
    Finding,
    SourceFile,
    apply_baseline,
    collect_files,
    fingerprints,
    load_baseline,
    load_source,
    save_baseline,
)

__all__ = [
    "Finding", "LintResult", "run_lint", "render_report",
    "load_baseline", "save_baseline", "find_repo_root", "DEFAULT_BASELINE",
]

DEFAULT_BASELINE = ".lint-baseline.json"


def find_repo_root(start: Optional[str] = None) -> str:
    """The directory holding the ray_tpu package (falls back to cwd)."""
    here = os.path.abspath(start or os.getcwd())
    probe = here
    while True:
        if os.path.isdir(os.path.join(probe, "ray_tpu", "_private")):
            return probe
        parent = os.path.dirname(probe)
        if parent == probe:
            break
        probe = parent
    # installed-package fallback: locate the package next to this file
    pkg = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.dirname(pkg)


class LintResult:
    def __init__(self, findings: List[Finding], new: List[Finding],
                 accepted: List[Finding], suppressed: int, files: int):
        self.findings = findings  # all, post-pragma
        self.new = new  # not in baseline -> these fail the run
        self.accepted = accepted  # matched baseline entries
        self.suppressed = suppressed  # killed by inline pragmas
        self.files = files

    @property
    def ok(self) -> bool:
        return not self.new

    def to_json(self) -> dict:
        return {
            "schema": "ray_tpu.lint.v1",
            "ok": self.ok,
            "files_scanned": self.files,
            "suppressed_by_pragma": self.suppressed,
            "accepted_by_baseline": [f.to_json() for f in self.accepted],
            "findings": [f.to_json() for f in self.new],
        }


def _order(f: Finding):
    return (f.path, f.line, f.rule)


def run_lint(
    paths: Optional[List[str]] = None,
    root: Optional[str] = None,
    baseline: Optional[Dict[str, dict]] = None,
) -> LintResult:
    """Run every analyzer. ``paths`` defaults to the whole ray_tpu package
    under ``root``; ``baseline`` is a loaded fingerprint map (see
    core.load_baseline) or None for no baseline."""
    root = os.path.abspath(root or find_repo_root())
    pkg_dir = os.path.join(root, "ray_tpu")
    if paths is None:
        paths = [pkg_dir]
    files = collect_files(paths, root)

    # the flag-dead direction always scans the full package, whatever
    # subset is being linted (see contracts.analyze)
    pkg_files: Optional[List[SourceFile]] = None
    if os.path.isdir(pkg_dir):
        if paths == [pkg_dir]:
            pkg_files = files
        else:
            pkg_files = collect_files([pkg_dir], root)

    cts = contracts.Contracts(root)
    findings: List[Finding] = []
    findings += contracts.analyze(files, cts, package_files=pkg_files)
    findings += shard_safety.analyze(files)
    findings += blocking.analyze(files)

    # inline pragma suppression — a finding may land in a file we didn't
    # lint (flag-dead anchors at config.py), so load lazily by rel path
    by_rel: Dict[str, SourceFile] = {sf.rel: sf for sf in files}

    def _sf_for(rel: str) -> Optional[SourceFile]:
        sf = by_rel.get(rel)
        if sf is None:
            path = os.path.join(root, *rel.split("/"))
            if os.path.isfile(path):
                sf = load_source(path, root)
                if sf is not None:
                    by_rel[rel] = sf
        return sf

    kept: List[Finding] = []
    suppressed = 0
    for f in findings:
        sf = _sf_for(f.path)
        if sf is not None and sf.allowed(f.line, f.rule):
            suppressed += 1
        else:
            kept.append(f)
    kept.sort(key=_order)

    if baseline:
        new, accepted = apply_baseline(kept, baseline)
    else:
        new, accepted = kept, []
    return LintResult(kept, new, accepted, suppressed, len(files))


def render_report(result: LintResult, verbose: bool = False) -> str:
    lines: List[str] = []
    for f in result.new:
        lines.append(f.render())
    if result.new:
        lines.append("")
    summary = (
        f"{len(result.new)} finding(s) "
        f"({result.files} files, {len(result.accepted)} baseline-accepted, "
        f"{result.suppressed} pragma-suppressed)"
    )
    if verbose and result.accepted:
        lines.append("baseline-accepted findings:")
        for f in result.accepted:
            lines.append("  " + f.render().replace("\n", "\n  "))
        lines.append("")
    lines.append(("FAIL: " if result.new else "OK: ") + summary)
    return "\n".join(lines)
