"""Blocking-call-in-coroutine detector for the control-plane event loops.

One synchronous call inside an ``async def`` on the rpc/raylet/GCS/worker/
serve loops silently re-serializes everything behind that loop — on a
1-core CI box the tests still pass, which is why this must be a static
check (Podracer-scale TPU systems live and die by single-threaded-loop
discipline, arXiv 2104.06272; the PR 13 reactor sharding is worthless if a
shard blocks). Three rules, applied only to ``async def`` bodies (nested
sync ``def``s reset the context — they run in executors or callbacks):

``blocking-call-in-async``
    ``time.sleep``, ``subprocess.run/call/check_call/check_output/
    getoutput/getstatusoutput``, ``os.system/os.popen/os.waitpid``,
    ``socket.create_connection/getaddrinfo/gethostbyname``,
    ``requests.*``, ``urllib.request.urlopen``. Use ``asyncio.sleep`` /
    ``run_in_executor`` / the async client instead.

``blocking-io-in-async``
    synchronous file/socket handle work: builtin ``open()`` and un-awaited
    ``.accept()/.connect()/.recv()/.recv_into()/.sendall()`` calls. Small
    local-file opens (markers, snapshots) are routinely accepted via the
    baseline or an inline ``# lint: allow(blocking-io-in-async)`` — the
    rule exists so each one is a *decision*, not an accident.

``sync-lock-in-async``
    un-awaited acquisition of a lock-ish object (final name containing
    lock/mutex/cond/sem): ``with self._lock:`` or a bare ``.acquire()``
    that is not awaited. A threading lock held across an await point — or
    merely contended — stalls the whole loop; use ``asyncio.Lock`` with
    ``async with``, or keep the critical section in sync helper methods
    called from one thread.

Scope: within the ray_tpu package only the control-plane modules are
checked (``_private/rpc.py``, ``_private/worker.py``, ``_private/raylet/``,
``_private/gcs/``, ``serve/``); files linted from OUTSIDE the package
(test fixtures) are always in scope so the rules stay testable.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ray_tpu._private.lint.core import Finding, SourceFile, call_name

_CONTROL_PLANE_PARTS = (
    "ray_tpu/_private/rpc.py",
    "ray_tpu/_private/worker.py",
    "ray_tpu/_private/raylet/",
    "ray_tpu/_private/gcs/",
    "ray_tpu/serve/",
)

_BLOCKING_CALLS = {
    "time.sleep", "_time.sleep",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.getoutput",
    "subprocess.getstatusoutput",
    "os.system", "os.popen", "os.waitpid",
    "socket.create_connection", "socket.getaddrinfo",
    "socket.gethostbyname",
    "requests.get", "requests.post", "requests.put", "requests.delete",
    "requests.head", "requests.request",
    "urlopen", "urllib.request.urlopen",
}

_BLOCKING_SOCKET_METHODS = {"accept", "connect", "recv", "recv_into",
                            "sendall"}

_LOCKISH = ("lock", "mutex", "cond", "sem")


def in_scope(rel: str) -> bool:
    """Control-plane modules inside the package; everything outside it."""
    if rel.startswith("ray_tpu/") or rel.startswith("ray_tpu\\"):
        norm = rel.replace("\\", "/")
        return any(part in norm for part in _CONTROL_PLANE_PARTS)
    return True


def _final_name(expr) -> Optional[str]:
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _lockish(expr) -> bool:
    name = _final_name(expr)
    return name is not None and any(t in name.lower() for t in _LOCKISH)


class _AsyncScanner(ast.NodeVisitor):
    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.findings: List[Finding] = []
        self.async_depth = 0
        self.awaited: set = set()  # id() of Call nodes under an Await

    def _find(self, rule: str, line: int, message: str):
        self.findings.append(
            Finding(rule, self.sf.rel, line, message, self.sf.snippet(line)))

    def visit_AsyncFunctionDef(self, node):
        self.async_depth += 1
        for stmt in node.body:
            self.visit(stmt)
        self.async_depth -= 1

    def visit_FunctionDef(self, node):
        saved = self.async_depth
        self.async_depth = 0
        for stmt in node.body:
            self.visit(stmt)
        self.async_depth = saved

    def visit_Lambda(self, node):
        saved = self.async_depth
        self.async_depth = 0
        self.visit(node.body)
        self.async_depth = saved

    def visit_Await(self, node: ast.Await):
        if isinstance(node.value, ast.Call):
            self.awaited.add(id(node.value))
        self.generic_visit(node)

    def visit_With(self, node: ast.With):
        if self.async_depth:
            for item in node.items:
                ctx = item.context_expr
                # `with open(...)` is handled by the Call visitor; here we
                # catch sync acquisition of lock-ish context managers
                if not isinstance(ctx, ast.Call) and _lockish(ctx):
                    self._find(
                        "sync-lock-in-async", node.lineno,
                        f"sync `with {_final_name(ctx)}:` inside a "
                        "coroutine blocks the event loop while contended — "
                        "use asyncio.Lock with `async with`, or hop the "
                        "work off the loop")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        if self.async_depth:
            name = call_name(node)
            leaf = name.rsplit(".", 1)[-1]
            if name in _BLOCKING_CALLS:
                self._find(
                    "blocking-call-in-async", node.lineno,
                    f"blocking call {name}() inside a coroutine stalls "
                    "this control-plane event loop — use the asyncio "
                    "equivalent or run_in_executor")
            elif name == "open":
                self._find(
                    "blocking-io-in-async", node.lineno,
                    "sync open() inside a coroutine performs filesystem "
                    "IO on the event loop — acceptable only for small "
                    "local files (baseline/allow it) else use "
                    "run_in_executor")
            elif (
                leaf in _BLOCKING_SOCKET_METHODS
                and id(node) not in self.awaited
                and isinstance(node.func, ast.Attribute)
                and not _lockish(node.func.value)
            ):
                recv = _final_name(node.func.value) or ""
                if any(t in recv.lower() for t in ("sock", "conn", "sk")):
                    self._find(
                        "blocking-io-in-async", node.lineno,
                        f"sync socket {recv}.{leaf}() inside a coroutine "
                        "blocks the event loop — use the loop.sock_* "
                        "coroutines or asyncio streams")
            elif (
                leaf == "acquire"
                and id(node) not in self.awaited
                and isinstance(node.func, ast.Attribute)
                and _lockish(node.func.value)
            ):
                self._find(
                    "sync-lock-in-async", node.lineno,
                    f"un-awaited {_final_name(node.func.value)}.acquire() "
                    "inside a coroutine blocks the event loop while "
                    "contended — await an asyncio primitive instead")
        self.generic_visit(node)


def analyze(files: List[SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    for sf in files:
        if not in_scope(sf.rel):
            continue
        scanner = _AsyncScanner(sf)
        # pre-pass: Await marking must happen before Call checks, and
        # ast.walk order doesn't guarantee it — collect awaited calls first
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Await) and isinstance(node.value, ast.Call):
                scanner.awaited.add(id(node.value))
        scanner.visit(sf.tree)
        findings.extend(scanner.findings)
    return findings
