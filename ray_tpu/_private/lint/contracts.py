"""Contract cross-checker: code vs the declared stability contracts.

Four contracts, each declared exactly once in the tree (mirroring the
reference's build-time-generated ``RayConfig`` flag table and its
compiler-enforced proto RPC surface — here the enforcement is this lint):

  flags    ``_FLAGS`` in ``ray_tpu/_private/config.py``. Every flag-style
           read — ``RTPU_CONFIG.<name>`` or a ``"RTPU_<name>"`` env-var
           literal where ``<name>`` starts lowercase — must name a declared
           flag (``flag-undeclared``), and every declared flag must be read
           somewhere in the package (``flag-dead``). All-caps ``RTPU_FOO``
           env vars are process-level infrastructure knobs (RTPU_ADDRESS,
           RTPU_STATE_FILE, ...), not config flags, and are exempt.
  metrics  the metric-name docstring in ``ray_tpu/util/metrics.py``. Every
           ``ray_tpu_*`` series emitted — a literal first argument to
           Counter/Gauge/Histogram, or the ``(name, labels, value)`` sample
           tuples the raylet/GCS/agent collectors build — must be listed
           (``metric-unregistered``).
  events   the EVENT-NAME contract in the
           ``ray_tpu/_private/flight_recorder.py`` docstring vs every
           literal ``record("x.y", ...)`` call (``event-unregistered``).
  sites    the SITE-NAME contract in the ``ray_tpu/_private/chaos.py``
           docstring vs every literal ``chaos.hit("x.y", ...)`` seam
           (``chaos-site-unregistered``).

Dynamic names (f-strings, variables) are invisible to a literal scan and
are deliberately out of scope — the contracts exist precisely so the
stable names stay greppable literals.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu._private.lint.core import (
    Finding,
    SourceFile,
    call_name,
    const_str,
    iter_docstrings,
    load_source,
)

_FLAG_READ_RE = re.compile(r"^RTPU_([a-z][A-Za-z0-9_]*)$")
_METRIC_RE = re.compile(r"ray_tpu_[a-z0-9_]+")
_EVENT_RE = re.compile(r"\b([a-z_]{2,}\.[a-z_]{2,})\b")
# dotted tokens in contract prose that are file names, not event names
_FILE_SUFFIXES = (".py", ".json", ".jsonl", ".md", ".yml", ".yaml", ".txt",
                  ".html", ".sh", ".cc", ".h")

_CONTRACT_FILES = {
    "flags": "ray_tpu/_private/config.py",
    "metrics": "ray_tpu/util/metrics.py",
    "events": "ray_tpu/_private/flight_recorder.py",
    "sites": "ray_tpu/_private/chaos.py",
}


class Contracts:
    """The declared names, parsed once per lint run from the repo root."""

    def __init__(self, root: str):
        self.root = root
        self.flags: Set[str] = set()
        self.flag_lines: Dict[str, int] = {}
        self.metrics: Set[str] = set()
        self.events: Set[str] = set()
        self.sites: Set[str] = set()
        self.config_rel = _CONTRACT_FILES["flags"]
        self._parse()

    def _load(self, key: str) -> Optional[SourceFile]:
        path = os.path.join(self.root, *_CONTRACT_FILES[key].split("/"))
        if not os.path.isfile(path):
            return None
        return load_source(path, self.root)

    def _parse(self):
        cfg = self._load("flags")
        if cfg is not None:
            for node in ast.walk(cfg.tree):
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, ast.AnnAssign):
                    targets = [node.target]
                else:
                    continue
                if not any(
                    isinstance(t, ast.Name) and t.id == "_FLAGS"
                    for t in targets
                ):
                    continue
                if isinstance(node.value, ast.Dict):
                    for k in node.value.keys:
                        name = const_str(k)
                        if name:
                            self.flags.add(name)
                            self.flag_lines[name] = k.lineno
        met = self._load("metrics")
        if met is not None:
            doc = ast.get_docstring(met.tree) or ""
            self.metrics.update(_METRIC_RE.findall(doc))
        fr = self._load("events")
        if fr is not None:
            doc = ast.get_docstring(fr.tree) or ""
            marker = "EVENT-NAME STABILITY CONTRACT"
            section = doc[doc.index(marker):] if marker in doc else doc
            self.events.update(self._dotted_names(section))
        ch = self._load("sites")
        if ch is not None:
            doc = ast.get_docstring(ch.tree) or ""
            start = "SITE-NAME STABILITY CONTRACT"
            end = "THE PLAN"
            if start in doc:
                doc = doc[doc.index(start):]
            if end in doc:
                doc = doc[: doc.index(end)]
            self.sites.update(self._dotted_names(doc))

    @staticmethod
    def _dotted_names(text: str) -> Set[str]:
        out = set()
        for name in _EVENT_RE.findall(text):
            if not name.endswith(_FILE_SUFFIXES):
                out.add(name)
        return out


def _docstring_nodes(sf: SourceFile) -> Set[int]:
    return {id(n) for n in iter_docstrings(sf.tree)}


def _flag_reads(sf: SourceFile) -> List[Tuple[str, int]]:
    """(flag_name, line) for every flag-style read in one module."""
    reads: List[Tuple[str, int]] = []
    docstrings = _docstring_nodes(sf)
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
            if node.value.id == "RTPU_CONFIG" and not node.attr.startswith("_"):
                if node.attr not in ("apply_system_config", "dump"):
                    reads.append((node.attr, node.lineno))
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            if id(node) in docstrings:
                continue
            m = _FLAG_READ_RE.match(node.value)
            if m:
                reads.append((m.group(1), node.lineno))
    return reads


def _metric_emissions(sf: SourceFile) -> List[Tuple[str, int]]:
    out: List[Tuple[str, int]] = []
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call):
            name = call_name(node)
            leaf = name.rsplit(".", 1)[-1]
            if leaf in ("Counter", "Gauge", "Histogram") and node.args:
                s = const_str(node.args[0])
                if s and s.startswith("ray_tpu_"):
                    out.append((s, node.args[0].lineno))
        elif isinstance(node, ast.Tuple) and len(node.elts) == 3:
            # the raylet/GCS/agent collectors build (name, labels, value)
            # sample tuples outside util.metrics
            s = const_str(node.elts[0])
            if (
                s
                and _METRIC_RE.fullmatch(s)
                and isinstance(node.elts[1], ast.Dict)
            ):
                out.append((s, node.elts[0].lineno))
    return out


def _record_modules(sf: SourceFile) -> Set[str]:
    """Local names under which flight_recorder's record() is reachable."""
    names: Set[str] = set()
    direct = False
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ImportFrom) and node.module and \
                node.module.endswith("flight_recorder"):
            for alias in node.names:
                if alias.name == "record":
                    direct = True
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in getattr(node, "names", []):
                if alias.name.split(".")[-1] == "flight_recorder":
                    names.add(alias.asname or "flight_recorder")
    if direct:
        names.add("")  # bare record() calls
    return names


def _event_emissions(sf: SourceFile) -> List[Tuple[str, int]]:
    mods = _record_modules(sf)
    if not mods:
        return []
    out: List[Tuple[str, int]] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        f = node.func
        hit = False
        if isinstance(f, ast.Attribute) and f.attr == "record":
            if isinstance(f.value, ast.Name) and f.value.id in mods:
                hit = True
        elif isinstance(f, ast.Name) and f.id == "record" and "" in mods:
            hit = True
        if hit:
            s = const_str(node.args[0])
            if s and "." in s:
                out.append((s, node.lineno))
    return out


def _site_emissions(sf: SourceFile) -> List[Tuple[str, int]]:
    out: List[Tuple[str, int]] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        f = node.func
        if (
            isinstance(f, ast.Attribute)
            and f.attr == "hit"
            and isinstance(f.value, ast.Name)
            and f.value.id in ("chaos", "_chaos")
        ):
            s = const_str(node.args[0])
            if s:
                out.append((s, node.lineno))
    return out


def analyze(
    files: List[SourceFile],
    contracts: Contracts,
    package_files: Optional[List[SourceFile]] = None,
) -> List[Finding]:
    """Cross-check ``files`` against the contracts. ``package_files``, when
    given, is the full package scan used for the flag-dead direction (a
    flag is dead only if NOTHING in the whole package reads it — a subset
    lint must not mass-report dead flags)."""
    findings: List[Finding] = []

    for sf in files:
        # each contract file is exempt only from its OWN check (config.py
        # builds "RTPU_" strings generically; metrics.py's docstring IS the
        # metric list; ...) — chaos.py reading an undeclared flag must
        # still be a finding
        if sf.rel != _CONTRACT_FILES["flags"]:
            for name, line in _flag_reads(sf):
                if contracts.flags and name not in contracts.flags:
                    findings.append(Finding(
                        "flag-undeclared", sf.rel, line,
                        f"RTPU_{name} read here but not declared in "
                        f"{contracts.config_rel} _FLAGS (stability "
                        "contract: declare the flag or rename the read)",
                        sf.snippet(line)))
        if sf.rel != _CONTRACT_FILES["metrics"]:
            for name, line in _metric_emissions(sf):
                if name not in contracts.metrics:
                    findings.append(Finding(
                        "metric-unregistered", sf.rel, line,
                        f"metric '{name}' emitted here but missing from "
                        "the stability contract docstring in "
                        f"{_CONTRACT_FILES['metrics']}",
                        sf.snippet(line)))
        if sf.rel != _CONTRACT_FILES["events"]:
            for name, line in _event_emissions(sf):
                if name not in contracts.events:
                    findings.append(Finding(
                        "event-unregistered", sf.rel, line,
                        f"flight event '{name}' recorded here but missing "
                        "from the EVENT-NAME contract docstring in "
                        f"{_CONTRACT_FILES['events']}",
                        sf.snippet(line)))
        if sf.rel != _CONTRACT_FILES["sites"]:
            for name, line in _site_emissions(sf):
                if name not in contracts.sites:
                    findings.append(Finding(
                        "chaos-site-unregistered", sf.rel, line,
                        f"chaos site '{name}' fired here but missing from "
                        "the SITE-NAME contract docstring in "
                        f"{_CONTRACT_FILES['sites']}",
                        sf.snippet(line)))

    # flag-dead: the reverse direction, package-wide by construction
    scan = package_files if package_files is not None else files
    if scan and contracts.flags:
        read_anywhere: Set[str] = set()
        for sf in scan:
            if sf.rel == _CONTRACT_FILES["flags"]:
                continue
            read_anywhere.update(name for name, _ in _flag_reads(sf))
        cfg_sf = load_source(
            os.path.join(contracts.root, *contracts.config_rel.split("/")),
            contracts.root)
        for name in sorted(contracts.flags - read_anywhere):
            line = contracts.flag_lines.get(name, 1)
            findings.append(Finding(
                "flag-dead", contracts.config_rel, line,
                f"flag '{name}' declared in _FLAGS but never read "
                "anywhere in the package (dead contract surface: wire it "
                "or remove it)",
                cfg_sf.snippet(line) if cfg_sf else ""))
    return findings
