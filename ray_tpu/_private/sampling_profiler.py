"""Periodic sampling CPU profiler: the capture half of the profiling plane.

Pure-Python py-spy analogue (reference: the dashboard's py-spy integration,
dashboard/modules/reporter/profile_manager.py — here without the binary
dependency): a daemon thread wakes at a fixed rate, walks every thread's
frame via ``sys._current_frames``, and records *timestamped* samples — not
just aggregated counts — so the samples can later be laid onto the cluster
timeline next to task/span events (``_private/timeline.py``
``merged_profile_trace``). Folded flamegraph output is derived from the
same samples (``fold_samples``).

Design constraints:
  - **Idle cost is zero.** Nothing on any hot path consults this module;
    a profiler exists only between StartProfile and CollectProfile RPCs
    (worker/raylet/GCS handlers) or an explicit ``start_profile()`` call.
    The only always-resident state is one module-level ``_active`` slot.
  - **Bounded memory.** Stacks are interned (most samples repeat a few
    distinct stacks); the sample list is capped by ``max_samples``
    (RTPU_profile_max_samples), after which sampling keeps aggregating
    into the folded counters but stops appending timeline samples.
  - **Wire-friendly result.** ``result()`` is a plain msgpack-able dict:
    {"t0", "t1", "hz", "pid", "role", "threads": [name, ...],
     "stacks": ["a;b;c", ...], "samples": [[dt_s, thread_i, stack_i], ...],
     "truncated": bool} — indices into the interned tables.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Dict, List, Optional

from ray_tpu._private.config import RTPU_CONFIG

_MAX_DEPTH = 128
_MAX_DURATION_S = 120.0
_MAX_HZ = 500.0

# Threads that are ~always parked in epoll/wait and would only add noise
# lanes; same skip rule as profiling.sample_stacks.
_IDLE_PREFIXES = ("rtpu-io",)
_IDLE_SUFFIXES = ("-watchdog",)


def frame_label(frame) -> str:
    code = frame.f_code
    fname = code.co_filename.rsplit("/", 1)[-1]
    return f"{code.co_name} ({fname}:{frame.f_lineno})"


def walk_stack(frame) -> str:
    """Root→leaf ';'-joined stack for one thread's current frame."""
    stack: List[str] = []
    f = frame
    depth = 0
    while f is not None and depth < _MAX_DEPTH:
        stack.append(frame_label(f))
        f = f.f_back
        depth += 1
    stack.reverse()
    return ";".join(stack)


def _is_idle_thread(name: str) -> bool:
    return name.startswith(_IDLE_PREFIXES) or name.endswith(_IDLE_SUFFIXES)


class SamplingProfiler:
    """One timed capture of this process's thread stacks.

    ``start(duration_s)`` spawns the sampler thread; ``collect()`` joins it
    (waiting out the remaining window) and returns the result dict. A
    profiler object is single-use.
    """

    def __init__(self, hz: float = 99.0, *, include_idle: bool = False,
                 max_samples: Optional[int] = None, role: str = ""):
        self.hz = min(max(1.0, float(hz)), _MAX_HZ)
        self.include_idle = include_idle
        self.max_samples = (
            int(max_samples) if max_samples is not None
            else RTPU_CONFIG.profile_max_samples
        )
        self.role = role
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._t0 = 0.0
        self._t1 = 0.0
        self._threads: List[str] = []
        self._thread_index: Dict[str, int] = {}
        self._stacks: List[str] = []
        self._stack_index: Dict[str, int] = {}
        self._samples: List[list] = []  # [dt_s, thread_i, stack_i]
        self._truncated = False

    # ------------------------------------------------------------ lifecycle

    def start(self, duration_s: float) -> None:
        if self._thread is not None:
            raise RuntimeError("profiler already started")
        duration_s = min(max(0.05, float(duration_s)), _MAX_DURATION_S)
        self._t0 = time.time()
        self._deadline = time.monotonic() + duration_s
        self._thread = threading.Thread(
            # the sampler skips itself by ident, but keep the -watchdog
            # suffix so the legacy one-shot sampler skips it too when both
            # run at once
            target=self._loop, name="rtpu-sampler-watchdog", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def collect(self, extra_timeout: float = 10.0) -> dict:
        """Wait out the remaining window and return the result dict."""
        t = self._thread
        if t is not None:
            remaining = max(0.0, self._deadline - time.monotonic())
            t.join(remaining + extra_timeout)
            if t.is_alive():  # wedged sampler: cut it loose, return partial
                self._stop.set()
        return self.result()

    # ------------------------------------------------------------- sampling

    def _loop(self):
        period = 1.0 / self.hz
        me = threading.get_ident()
        names = {t.ident: t.name for t in threading.enumerate()}
        refresh = 0
        while not self._stop.is_set() and time.monotonic() < self._deadline:
            now = time.time()
            try:
                frames = sys._current_frames()
            except Exception:
                break
            for tid, frame in frames.items():
                if tid == me:
                    continue
                name = names.get(tid) or str(tid)
                if not self.include_idle and _is_idle_thread(name):
                    continue
                self._record(now, name, walk_stack(frame))
            refresh += 1
            if refresh >= 32:  # new threads appear mid-capture
                refresh = 0
                names = {t.ident: t.name for t in threading.enumerate()}
            self._stop.wait(period)
        self._t1 = time.time()

    def _record(self, now: float, thread_name: str, stack: str):
        ti = self._thread_index.get(thread_name)
        if ti is None:
            ti = self._thread_index[thread_name] = len(self._threads)
            self._threads.append(thread_name)
        si = self._stack_index.get(stack)
        if si is None:
            si = self._stack_index[stack] = len(self._stacks)
            self._stacks.append(stack)
        if len(self._samples) < self.max_samples:
            self._samples.append([round(now - self._t0, 6), ti, si])
        else:
            self._truncated = True

    # -------------------------------------------------------------- results

    def result(self) -> dict:
        return {
            "t0": self._t0,
            "t1": self._t1 or time.time(),
            "hz": self.hz,
            "pid": os.getpid(),
            "role": self.role,
            "threads": list(self._threads),
            "stacks": list(self._stacks),
            "samples": list(self._samples),
            "truncated": self._truncated,
        }


def fold_samples(profile: dict, *, thread_prefix: bool = True) -> Dict[str, int]:
    """Aggregate a profile's samples into {folded_stack: count}
    (flamegraph.pl / speedscope 'folded' input, same shape as
    profiling.sample_stacks)."""
    threads = profile.get("threads", [])
    stacks = profile.get("stacks", [])
    counts: Dict[str, int] = {}
    for _dt, ti, si in profile.get("samples", []):
        try:
            stack = stacks[si]
        except (IndexError, TypeError):
            continue
        if thread_prefix:
            name = threads[ti] if 0 <= ti < len(threads) else str(ti)
            stack = f"{name};{stack}"
        counts[stack] = counts.get(stack, 0) + 1
    return counts


# ------------------------------------------------- per-process active slot
# One capture at a time per process: StartProfile replaces nothing — a
# second start while one runs is an error surfaced to the caller, EXCEPT
# an already-finished capture which is silently discarded (an operator who
# never collected shouldn't wedge the process forever).

_active: Optional[SamplingProfiler] = None
_active_lock = threading.Lock()


def start_profile(duration_s: float, hz: float = 99.0, *,
                  include_idle: bool = False, role: str = "") -> SamplingProfiler:
    global _active
    with _active_lock:
        if _active is not None and _active.running:
            raise RuntimeError("a profile capture is already running")
        prof = SamplingProfiler(hz, include_idle=include_idle, role=role)
        prof.start(duration_s)
        _active = prof
        return prof


def collect_profile() -> Optional[dict]:
    """Collect (blocking until the window closes) and clear the active
    capture; None when nothing was started."""
    global _active
    with _active_lock:
        prof, _active = _active, None
    if prof is None:
        return None
    return prof.collect()


def is_active() -> bool:
    prof = _active
    return prof is not None and prof.running
