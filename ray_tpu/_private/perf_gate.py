"""Perf regression gate: microbench A/B comparator + history ledger.

PRs 1-4 built the observability stack (step telemetry, flight recorder,
merged cluster profiles) but nothing *consumed* it automatically — a
control-plane collapse like multi_client_tasks_async landing at 0.13x the
reference could merge silently because nobody re-ran the table. This module
is the enforcement half: it runs ``microbench.py`` metrics A/B against a
committed baseline, judges each delta against an explicit per-metric noise
band, and keeps an append-only history ledger so the trajectory of every
metric survives across PRs.

Protocol (MICROBENCH.md): each metric runs 3 back-to-back reps and reports
the median; *single* reps swing ±25-30% on the reference box, medians ~±15%.
The noise bands below encode exactly that: a comparison's band is picked by
the LEAST reliable side (min reps of baseline and current), then scaled by
``RTPU_perf_band_scale`` for noisier boxes. A drop beyond the band is a
regression; a rise beyond it is flagged as an improvement (so a suspicious
2x "win" is visible too, not just losses). Latency-style rows
(``_LOWER_IS_BETTER``, e.g. ``serve_llm_stream_p99_ms``) invert that
verdict: the rise is the regression.

Surfaces:
  - ``ray-tpu perf check``     measure now, compare vs the ledger head
  - ``ray-tpu perf compare``   compare two ``microbench.py --json`` files
  - ``ray-tpu perf history``   print the ledger trajectory
  - dashboard ``GET /api/perf``  ledger + latest delta as JSON
  - ``.github/workflows/perf.yml``  base-vs-head A/B on every PR

The ledger (``PERF_HISTORY.jsonl``, overridable via
``RTPU_perf_history_path``) holds one JSON object per line:
``{"time", "iso", "git", "reps", "quick", "host", "note", "metrics"}``.
It is meant to be committed alongside MICROBENCH.md refreshes so the next
session inherits the baseline.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import repo_root
from ray_tpu._private.config import RTPU_CONFIG

# ---------------------------------------------------------------- noise bands
# Fractional deviation from baseline that still counts as noise, keyed by
# the reps of the less-reliable side of the comparison (1 = single run,
# 3 = the committed 3-rep-median protocol). Values come from MICROBENCH.md's
# measured spread on the reference 1-core box; per-metric overrides widen
# rows with a known extra variance source.

_DEFAULT_BANDS = {1: 0.40, 3: 0.25}
_METRIC_BANDS: Dict[str, Dict[int, float]] = {
    # multi-process rows serialize behind one core on small boxes — OS
    # scheduler jitter dominates the measurement
    "multi_client_tasks_async": {1: 0.50, 3: 0.35},
    "n_n_actor_calls_async": {1: 0.50, 3: 0.35},
    # submit-storm A/B pair (ring vs RPC): multi-process like the rows
    # above, plus each side boots its own cluster (cold worker pools)
    "many_drivers_submit_storm": {1: 0.50, 3: 0.35},
    "many_drivers_submit_storm_rpc": {1: 0.50, 3: 0.35},
    # bandwidth depends on store page-fault state (cold first-touch pages
    # vs recycled ones differ ~3x; reps amortize but don't remove it)
    "single_client_put_gigabytes": {1: 0.45, 3: 0.30},
    # wait() at 1k refs batches timers across the whole submit window
    "wait_1k_refs": {1: 0.45, 3: 0.30},
    # serve/llm engine load test: throughput jitters with allocator/GC
    # state across a multi-second numpy run; the p99 row additionally
    # rides the tail of 1k stream completions
    "serve_llm_tokens_per_s": {1: 0.45, 3: 0.30},
    "serve_llm_static_batch_tokens_per_s": {1: 0.45, 3: 0.30},
    "serve_llm_stream_p99_ms": {1: 0.45, 3: 0.30},
    # prefix-caching / speculative-decoding A/B rows (same engine runs)
    "serve_llm_prefix_tokens_per_s": {1: 0.45, 3: 0.30},
    "serve_llm_prefix_cold_tokens_per_s": {1: 0.45, 3: 0.30},
    "serve_llm_spec_tokens_per_s": {1: 0.45, 3: 0.30},
    "serve_llm_spec_baseline_tokens_per_s": {1: 0.45, 3: 0.30},
    # ...but the hit-rate and acceptance rows are 0-1 RATIOS (higher is
    # better, like throughput) over deterministic workloads — a scheduler
    # admission-order wiggle moves them a little, a matcher/acceptance
    # regression moves them a lot, so they get far tighter bands than the
    # wall-clock rows
    "serve_llm_prefix_kv_hit_rate": {1: 0.15, 3: 0.10},
    "serve_llm_spec_acceptance": {1: 0.15, 3: 0.10},
}

# Metrics where LOWER is better (latencies): the gate inverts the verdict —
# a rise beyond the band is the regression, a drop the improvement.
_LOWER_IS_BETTER = {"serve_llm_stream_p99_ms"}


def noise_band(metric: str, reps: int = 1) -> float:
    """Allowed fractional drop (and rise) for ``metric`` measured with
    ``reps`` timing reps per side, scaled by RTPU_perf_band_scale."""
    table = _METRIC_BANDS.get(metric, _DEFAULT_BANDS)
    band = table[3 if reps >= 3 else 1]
    return band * float(RTPU_CONFIG.perf_band_scale)


def is_noisy_runner() -> bool:
    """True when this box cannot produce a meaningful A/B at all: a single
    core means every microbench process (client, server, raylet, GCS)
    timeshares one CPU and the multi-process rows measure the scheduler,
    not the framework. CI uses this as its skip path."""
    try:
        cpus = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        cpus = os.cpu_count() or 1
    return cpus <= 1


# ----------------------------------------------------------------- comparator


def compare(baseline: Dict[str, float], current: Dict[str, float],
            base_reps: int = 1, cur_reps: int = 1) -> Dict[str, Any]:
    """Judge ``current`` against ``baseline`` metric by metric.

    Returns the structured delta report::

        {"status": "pass" | "fail",
         "reps": <min reps of the two sides>,
         "regressions": [metric, ...],
         "improvements": [metric, ...],
         "metrics": {name: {"baseline", "current", "ratio", "band",
                            "status": pass|regression|improved|new|missing}}}

    Missing metrics are informational, never failures: ``new`` (no
    baseline yet) and ``missing`` (baseline row not measured this run,
    e.g. an ``--only`` subset).
    """
    reps = min(int(base_reps or 1), int(cur_reps or 1))
    out: Dict[str, Any] = {"status": "pass", "reps": reps,
                           "regressions": [], "improvements": [],
                           "metrics": {}}
    for name in sorted(set(baseline) | set(current)):
        old = baseline.get(name)
        new = current.get(name)
        band = noise_band(name, reps)
        row: Dict[str, Any] = {"baseline": old, "current": new,
                               "band": round(band, 3)}
        if old is None:
            row["status"] = "new"
        elif new is None:
            row["status"] = "missing"
        elif not old > 0:
            row["status"] = "new"  # unusable baseline value
        else:
            ratio = new / old
            row["ratio"] = round(ratio, 4)
            # latency-style metrics invert: a RISE is the regression
            worse = (ratio > 1.0 + band if name in _LOWER_IS_BETTER
                     else ratio < 1.0 - band)
            better = (ratio < 1.0 - band if name in _LOWER_IS_BETTER
                      else ratio > 1.0 + band)
            if worse:
                row["status"] = "regression"
                out["regressions"].append(name)
            elif better:
                row["status"] = "improved"
                out["improvements"].append(name)
            else:
                row["status"] = "pass"
        out["metrics"][name] = row
    if out["regressions"]:
        out["status"] = "fail"
    return out


def render_report(report: Dict[str, Any]) -> str:
    """Human-readable delta table (the CLI's default output)."""
    lines = [f"{'metric':<34} {'baseline':>12} {'current':>12} "
             f"{'ratio':>7} {'band':>6}  status"]
    for name, row in report["metrics"].items():
        old = row.get("baseline")
        new = row.get("current")
        lines.append(
            f"{name:<34} "
            f"{old if old is not None else '—':>12} "
            f"{new if new is not None else '—':>12} "
            f"{row.get('ratio', '—'):>7} "
            f"±{int(row['band'] * 100):>4}%  "
            f"{row['status'].upper() if row['status'] == 'regression' else row['status']}"
        )
    lines.append(
        f"gate: {report['status']} "
        f"({len(report['regressions'])} regression(s), "
        f"{len(report['improvements'])} improvement(s), "
        f"reps={report['reps']})")
    return "\n".join(lines)


# --------------------------------------------------------------------- ledger


def history_path(path: Optional[str] = None) -> str:
    """Resolve the ledger path; relative paths anchor at the repo root so
    the CLI works from any cwd."""
    p = path or RTPU_CONFIG.perf_history_path
    if not os.path.isabs(p):
        p = os.path.join(repo_root(), p)
    return p


def load_history(path: Optional[str] = None,
                 limit: int = 0) -> List[Dict[str, Any]]:
    """Ledger entries, oldest first (``limit`` keeps the newest N).
    Corrupt lines are skipped, not fatal — the ledger is append-only and a
    torn write must not brick the gate."""
    p = history_path(path)
    if not os.path.isfile(p):
        return []
    entries = []
    with open(p) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                e = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(e, dict) and isinstance(e.get("metrics"), dict):
                entries.append(e)
    return entries[-limit:] if limit else entries


def load_baseline(path: Optional[str] = None) -> Optional[Dict[str, Any]]:
    """The newest ledger entry (what ``perf check`` compares against)."""
    entries = load_history(path, limit=1)
    return entries[-1] if entries else None


def append_history(metrics: Dict[str, float], *, path: Optional[str] = None,
                   reps: int = 1, quick: bool = False, note: str = "",
                   detail: Optional[dict] = None) -> Dict[str, Any]:
    entry = {
        "time": time.time(),
        "iso": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "git": _git_head(),
        "reps": int(reps),
        "quick": bool(quick),
        "host": {"cpus": os.cpu_count()},
        "note": note,
        "metrics": {k: round(float(v), 3) for k, v in metrics.items()},
    }
    if detail:
        entry["detail"] = detail
    p = history_path(path)
    os.makedirs(os.path.dirname(p) or ".", exist_ok=True)
    with open(p, "a") as f:
        f.write(json.dumps(entry) + "\n")
    return entry


def _git_head() -> str:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], capture_output=True,
            text=True, timeout=10, cwd=repo_root())
        return out.stdout.strip() if out.returncode == 0 else ""
    except Exception:
        return ""


# -------------------------------------------------------------- measurement


def load_result_entry(source) -> Dict[str, Any]:
    """Like load_result, plus measurement metadata: returns
    ``{"metrics", "reps", "cpus"}`` where ``cpus`` is the measuring host's
    core count (None for formats that predate ``host.cpus``). Core counts
    matter because the multi-process rows scale with them — comparing a
    1-core measurement against a multi-core one gates the runner, not the
    code (see cmd_perf's annotation / --skip-noisy handling)."""
    meta = None
    if isinstance(source, str):
        with open(source) as f:
            source = json.loads(f.read().strip().splitlines()[-1])
    if isinstance(source, dict):
        host = source.get("host")
        if isinstance(host, dict):
            meta = host.get("cpus")
    metrics, reps = load_result(source)
    return {"metrics": metrics, "reps": reps, "cpus": meta}


def load_result(source) -> Tuple[Dict[str, float], int]:
    """(metrics, reps) from any of the shapes the plane produces:

    - ``microbench.py --json`` output ({"schema": "microbench.v1", ...});
    - a bare ``{metric: value}`` dict (legacy ``--only`` print format,
      still what old base commits emit in the CI A/B) — assumed single-rep;
    - a ledger entry ({"metrics": ..., "reps": ...});
    - a path to a JSON file holding any of the above.
    """
    if isinstance(source, str):
        with open(source) as f:
            source = json.loads(f.read().strip().splitlines()[-1])
    if not isinstance(source, dict):
        raise ValueError(f"unrecognized perf result: {type(source)}")
    if isinstance(source.get("metrics"), dict):
        metrics = source["metrics"]
        # microbench.v1 carries per-metric {"value", min/median/max} rows
        flat = {
            k: (v["value"] if isinstance(v, dict) else v)
            for k, v in metrics.items()
        }
        return ({k: float(v) for k, v in flat.items() if v is not None},
                int(source.get("reps") or 1))
    flat = {k: v for k, v in source.items() if isinstance(v, (int, float))}
    if not flat:
        raise ValueError("no metrics found in perf result")
    return {k: float(v) for k, v in flat.items()}, 1


def run_microbench(only: Optional[str] = None, quick: bool = True,
                   timeout: float = 1200.0) -> Dict[str, Any]:
    """Run ``microbench.py --json`` in a fresh subprocess (the bench boots
    and tears down its own cluster; process state must not leak into the
    caller) and return the parsed microbench.v1 payload."""
    cmd = [sys.executable, os.path.join(repo_root(), "microbench.py"),
           "--json"]
    if quick:
        cmd.append("--quick")
    if only:
        cmd += ["--only", only]
    out = subprocess.run(cmd, capture_output=True, text=True,
                         timeout=timeout, cwd=repo_root())
    if out.returncode != 0:
        raise RuntimeError(
            f"microbench failed (rc={out.returncode}):\n"
            f"{out.stdout[-1500:]}\n{out.stderr[-1500:]}")
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            payload = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(payload, dict):
            return payload
    raise RuntimeError(f"microbench produced no JSON:\n{out.stdout[-1500:]}")


def check(only: Optional[str] = None, quick: bool = True,
          history: Optional[str] = None, update: bool = False,
          note: str = "") -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """The ``perf check`` workflow: measure now, compare against the ledger
    head, optionally append the measurement. Returns (report, measurement).
    With no ledger yet every metric lands as ``new`` and the gate passes —
    the first ``--update`` run seeds the baseline."""
    result = run_microbench(only=only, quick=quick)
    metrics, reps = load_result(result)
    base = load_baseline(history)
    report = compare(base["metrics"] if base else {}, metrics,
                     base_reps=base.get("reps", 1) if base else 1,
                     cur_reps=reps)
    if base:
        report["baseline_time"] = base.get("iso") or base.get("time")
        report["baseline_git"] = base.get("git", "")
        base_cpus = (base.get("host") or {}).get("cpus")
        cur_cpus = (result.get("host") or {}).get("cpus")
        if base_cpus and cur_cpus and base_cpus != cur_cpus:
            # cross-core-count comparison: the multi-process rows scale
            # with the core count, so this gates the runner, not the code
            # (cmd_perf demotes regressions to advisory)
            report["host_mismatch"] = {"baseline_cpus": base_cpus,
                                       "current_cpus": cur_cpus}
    _publish_gate_metrics(report)
    if update:
        append_history(metrics, path=history, reps=reps, quick=quick,
                       note=note or ("perf check" + (f" --only {only}" if only
                                                     else "")),
                       detail=result.get("metrics"))
    return report, result


def _publish_gate_metrics(report: Dict[str, Any]) -> None:
    """Best-effort ``ray_tpu_perf_*`` series (stability contract in
    util/metrics.py). Only lands on Prometheus when a worker is connected
    to flush them; the CLI path just accumulates in-process and exits."""
    try:
        from ray_tpu.util.metrics import Counter, Gauge

        reg = Counter("ray_tpu_perf_regressions_total",
                      "perf-gate comparisons beyond the noise band",
                      tag_keys=("metric",))
        ratio = Gauge("ray_tpu_perf_gate_ratio",
                      "latest perf-gate current/baseline ratio",
                      tag_keys=("metric",))
        for name, row in report["metrics"].items():
            if "ratio" in row:
                ratio.set(row["ratio"], tags={"metric": name})
            if row["status"] == "regression":
                reg.inc(tags={"metric": name})
    except Exception:
        pass
