"""Plasma-backed submit ring: syscall-free task submission.

The RPC submit path costs one socket write per PushTask frame; at
many-drivers-storm scale those writes (plus the per-frame reactor wakeups
on the receiving side) dominate tiny-task throughput. This module gives a
driver a fixed-size shared-memory ring — one sealed plasma object used as
a mailbox — into which it memcpys serialized task specs. The raylet
drains the ring in batches per loop tick; the only RPC left on the hot
path is a single doorbell notify on every empty→non-empty transition.

The ring rides the same mapped-shm discipline as the PR 2 zero-copy data
plane (serialization.write_blob): the producer writes payload bytes
straight into a slice of the plasma arena mmap; nothing is ever
re-pickled or staged through a socket. Sealing the object here only
*publishes* the region — both sides hold a plasma pin so the store cannot
reclaim it, and both sides map it read-write (the arena mapping is always
RW; see _native/plasma.PlasmaClient).

Layout (all cursors 8-byte aligned; little-endian)::

    [0:8)    tail   producer write cursor, bytes, monotonically increasing
    [8:16)   head   consumer read cursor,  bytes, monotonically increasing
    [16:24)  consumer heartbeat, float64 wall-clock seconds (liveness)
    [24:32)  flags  (FLAG_CLOSED = producer detached cleanly)
    [32:40)  magic
    [40:64)  reserved
    [64:...] data region; entries are [u32 length][payload] padded to a
             4-byte boundary and never wrap — a u32 SKIP marker burns the
             tail of the region when an entry would cross the end.

Concurrency contract: strict SPSC. Exactly one producer thread (the
driver's io loop) advances ``tail``; exactly one consumer thread (the
raylet's loop) advances ``head``. Each 8-byte cursor store is a single
aligned write — atomic on every platform this runs on — and each side
publishes its cursor only AFTER the bytes it covers are written (producer)
or copied out (consumer), so the peer can never observe a torn entry.

Doorbell rule (who wakes the consumer): after publishing ``tail`` the
producer re-reads ``head``; if ``head`` equals the pre-push tail the ring
was drained empty at publish time, meaning the consumer either is asleep
or is about to sleep — exactly then a doorbell RPC is required. Any other
interleaving guarantees the consumer will still observe the new entry on
its way to the empty check, so no doorbell is needed.

Failure semantics: the ring is an *optimization*, never a source of
truth. A full ring, a missing ring, or a dead consumer all fall back to
the RPC submit path. The consumer heartbeats the header every drain tick;
a producer whose doorbell connection drops or whose consumer heartbeat
goes stale resubmits every not-yet-replied spec via RPC (the raylet that
died took its undispatched backlog — and the workers that would have run
it — with it, so resubmission cannot double-execute those; an executed
task whose reply was lost retries under the same at-least-once contract
as the ordinary worker-crash path).
"""

from __future__ import annotations

import struct
from typing import List, Optional

HEADER_BYTES = 64
# Sizing hint: RTPU_submit_ring_slots is a slot COUNT; each slot budgets
# this many bytes (a tiny-task spec packs to a few hundred bytes).
SLOT_HINT_BYTES = 1024

MAGIC = 0x52494E47  # "RING"
FLAG_CLOSED = 1
_SKIP = 0xFFFFFFFF

_U64 = struct.Struct("<Q")
_U32 = struct.Struct("<I")
_F64 = struct.Struct("<d")

_OFF_TAIL = 0
_OFF_HEAD = 8
_OFF_BEAT = 16
_OFF_FLAGS = 24
_OFF_MAGIC = 32


class RingCorrupt(Exception):
    pass


def ring_bytes(slots: int) -> int:
    """Total object size for a ring of ``slots`` budgeted entries."""
    return HEADER_BYTES + max(1, int(slots)) * SLOT_HINT_BYTES


class _RingBase:
    def __init__(self, view: memoryview, init: bool = False):
        view = view if isinstance(view, memoryview) else memoryview(view)
        if view.nbytes < HEADER_BYTES + 64:
            raise ValueError(f"ring backing too small: {view.nbytes}")
        self._mv = view.cast("B") if view.format != "B" else view
        # capacity must stay a multiple of 4 so entry slots always align
        self._cap = (view.nbytes - HEADER_BYTES) & ~3
        if init:
            self._mv[:HEADER_BYTES] = bytes(HEADER_BYTES)
            self._put_u64(_OFF_MAGIC, MAGIC)
        elif self._get_u64(_OFF_MAGIC) != MAGIC:
            raise RingCorrupt("bad ring magic")

    # -- header accessors (single aligned stores; see module docstring) --

    def _get_u64(self, off: int) -> int:
        return _U64.unpack_from(self._mv, off)[0]

    def _put_u64(self, off: int, value: int):
        _U64.pack_into(self._mv, off, value)

    @property
    def capacity(self) -> int:
        return self._cap

    def used_bytes(self) -> int:
        return self._get_u64(_OFF_TAIL) - self._get_u64(_OFF_HEAD)

    def empty(self) -> bool:
        return self.used_bytes() == 0

    def closed(self) -> bool:
        return bool(self._get_u64(_OFF_FLAGS) & FLAG_CLOSED)

    def consumer_beat(self) -> float:
        return _F64.unpack_from(self._mv, _OFF_BEAT)[0]


class RingProducer(_RingBase):
    """Driver side: enqueue serialized specs with one memcpy each."""

    def try_push(self, payload) -> Optional[bool]:
        """Enqueue one entry. Returns None when the ring lacks room (the
        caller falls back to the RPC path), else whether the ring
        transitioned empty→non-empty (the caller rings the doorbell)."""
        payload = payload if isinstance(payload, (bytes, bytearray)) \
            else bytes(payload)
        need = 4 + len(payload)
        need += (-need) % 4  # keep every slot 4-byte aligned
        if need > self._cap:
            return None
        tail = self._get_u64(_OFF_TAIL)
        head = self._get_u64(_OFF_HEAD)
        used = tail - head
        pos = tail % self._cap
        room_to_end = self._cap - pos
        if room_to_end < need:
            # entries never wrap: burn the region tail with a SKIP marker
            if used + room_to_end + need > self._cap:
                return None
            _U32.pack_into(self._mv, HEADER_BYTES + pos, _SKIP)
            tail += room_to_end
            pos = 0
        elif used + need > self._cap:
            return None
        base = HEADER_BYTES + pos
        _U32.pack_into(self._mv, base, len(payload))
        self._mv[base + 4:base + 4 + len(payload)] = payload
        orig_tail = self._get_u64(_OFF_TAIL)
        # publish: the entry bytes above are fully written before the
        # cursor store makes them visible
        self._put_u64(_OFF_TAIL, tail + need)
        # doorbell rule: empty at publish time ⇒ the consumer is (about to
        # go) asleep and needs a wakeup; see module docstring for why this
        # read must happen AFTER the tail store
        return self._get_u64(_OFF_HEAD) == orig_tail

    def close(self):
        """Mark a clean producer detach; the consumer reclaims the ring."""
        self._put_u64(_OFF_FLAGS, self._get_u64(_OFF_FLAGS) | FLAG_CLOSED)


class RingConsumer(_RingBase):
    """Raylet side: drain batches of entries per tick."""

    def drain(self, max_items: int = 256) -> List[bytes]:
        """Pop up to ``max_items`` entries. Payloads are copied out BEFORE
        the head cursor is published, so the producer can never overwrite
        bytes a drained entry still aliases."""
        out: List[bytes] = []
        head = self._get_u64(_OFF_HEAD)
        tail = self._get_u64(_OFF_TAIL)
        while head < tail and len(out) < max_items:
            pos = head % self._cap
            base = HEADER_BYTES + pos
            (length,) = _U32.unpack_from(self._mv, base)
            if length == _SKIP:
                head += self._cap - pos
                continue
            if length > self._cap - 4 or pos + 4 + length > self._cap:
                raise RingCorrupt(f"entry length {length} out of bounds")
            out.append(bytes(self._mv[base + 4:base + 4 + length]))
            adv = 4 + length
            head += adv + (-adv) % 4
        self._put_u64(_OFF_HEAD, head)
        return out

    def beat(self, now: float):
        """Liveness heartbeat, written every drain tick — producers treat
        a stale beat as a dead consumer and fall back to RPC."""
        _F64.pack_into(self._mv, _OFF_BEAT, now)
