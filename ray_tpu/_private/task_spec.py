"""Task specification: the wire representation of a task/actor call.

Counterpart of the reference's TaskSpecification (reference:
src/ray/common/task/task_spec.h, protobuf common.proto TaskSpec). Plain
msgpack-able dicts; helpers here keep construction/parsing in one place.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import serialization
from ray_tpu._private.ids import ActorID, JobID, ObjectID, TaskID
from ray_tpu._private.object_ref import ObjectRef

TASK_NORMAL = 0
TASK_ACTOR_CREATION = 1
TASK_ACTOR = 2


def normalize_resources(
    num_cpus=None, num_tpus=None, memory=None, resources=None, default_cpus=1.0
) -> Dict[str, float]:
    out: Dict[str, float] = {}
    out["CPU"] = float(num_cpus) if num_cpus is not None else float(default_cpus)
    if num_tpus:
        out["TPU"] = float(num_tpus)
    if memory:
        out["memory"] = float(memory)
    for k, v in (resources or {}).items():
        if k in ("CPU", "TPU", "memory"):
            raise ValueError(f"Use the dedicated option for {k}, not resources=")
        out[k] = float(v)
    return {k: v for k, v in out.items() if v != 0}


def serialize_args(
    args: tuple, kwargs: dict, inline_threshold: int
) -> Tuple[list, List[ObjectRef], list]:
    """Returns (wire_args, contained_refs, large_values).

    Each wire arg is one of:
      {"v": inline_payload}          — plain value (may contain nested refs)
      {"ref": [id_bytes, owner]}     — top-level ObjectRef arg (resolved by executor)
    Values larger than inline_threshold are returned in large_values as
    (position_key, (pickle_bytes, raw_buffers)) for the caller to store via
    put_serialized() and replace with a ref — the value is serialized
    exactly once and its buffers stay raw until they stream into plasma.
    """
    wire = []
    refs: List[ObjectRef] = []
    large = []

    def one(pos_key, val):
        if isinstance(val, ObjectRef):
            refs.append(val)
            return {"ref": [val.object_id().binary(), list(val.owner_address or ())]}
        p, bufs, contained = serialization.serialize(val)
        if len(p) + serialization.buffers_nbytes(bufs) > inline_threshold:
            large.append((pos_key, (p, bufs)))
            return {"big": pos_key}
        refs.extend(contained)
        return {"v": serialization.inline_payload(p, bufs)}

    for i, a in enumerate(args):
        wire.append(["p", i, one(("p", i), a)])
    for k, v in (kwargs or {}).items():
        wire.append(["k", k, one(("k", k), v)])
    return wire, refs, large


def build_task_spec(
    *,
    task_id: TaskID,
    job_id: JobID,
    name: str,
    fn_key: bytes,
    wire_args: list,
    num_returns: int,
    resources: Dict[str, float],
    owner_addr: Tuple[str, int],
    owner_worker_id: bytes,
    max_retries: int = 0,
    retry_exceptions: bool = False,
    scheduling_strategy: Optional[dict] = None,
    task_type: int = TASK_NORMAL,
    actor_id: Optional[ActorID] = None,
    seq_no: int = 0,
    method_name: str = "",
    runtime_env: Optional[dict] = None,
    max_concurrency: int = 1,
    max_restarts: int = 0,
    caller_id: bytes = b"",
) -> dict:
    return {
        "task_id": task_id.binary(),
        "job_id": job_id.binary(),
        "name": name,
        "fn_key": fn_key,
        "args": wire_args,
        "num_returns": num_returns,
        "resources": resources,
        "owner_addr": list(owner_addr),
        "owner_worker_id": owner_worker_id,
        "max_retries": max_retries,
        "retry_exceptions": retry_exceptions,
        "strategy": scheduling_strategy or {},
        "type": task_type,
        "actor_id": actor_id.binary() if actor_id else b"",
        "seq_no": seq_no,
        "method_name": method_name,
        "runtime_env": runtime_env or {},
        "max_concurrency": max_concurrency,
        "max_restarts": max_restarts,
        "caller_id": caller_id,
    }


def return_object_ids(spec: dict) -> List[ObjectID]:
    tid = TaskID(spec["task_id"])
    return [ObjectID.from_task(tid, i + 1) for i in range(spec["num_returns"])]


def scheduling_key(spec: dict) -> tuple:
    """Leases are cached per (function, resource shape, strategy, runtime
    env) like the reference's SchedulingKey (reference:
    normal_task_submitter.h — runtime_env_hash is part of the key so tasks
    with different environments never share a leased worker)."""
    res = tuple(sorted(spec["resources"].items()))
    strat = tuple(sorted((k, str(v)) for k, v in spec["strategy"].items()))
    return (spec["fn_key"], res, strat, runtime_env_key(spec.get("runtime_env")))


RUNTIME_ENV_SUPPORTED = (
    "env_vars", "working_dir", "pip", "py_modules", "conda", "container",
)


def normalize_pip(pip) -> Optional[dict]:
    """Canonical pip spec: {"packages": [...], "pip_install_options": [...]}
    (reference: _private/runtime_env/pip.py accepts a list or dict)."""
    if pip is None:
        return None
    if isinstance(pip, (list, tuple)):
        pip = {"packages": list(pip)}
    if not isinstance(pip, dict) or not isinstance(pip.get("packages"), list):
        raise ValueError(
            "runtime_env pip must be a list of requirements or "
            '{"packages": [...], "pip_install_options": [...]}'
        )
    unknown = set(pip) - {"packages", "pip_install_options"}
    if unknown:
        # silent drops are worse than errors (same rule as the top-level
        # runtime_env fields)
        raise ValueError(f"unsupported pip option(s): {sorted(unknown)}")
    return {
        "packages": [str(p) for p in pip["packages"]],
        "pip_install_options": [
            str(o) for o in pip.get("pip_install_options", [])
        ],
    }


def runtime_env_key(runtime_env: Optional[dict]) -> str:
    """Canonical string form; '' for the default environment. JSON so
    values containing separator characters cannot make two distinct
    environments share a scheduling key / pooled worker."""
    if not runtime_env:
        return ""
    import json

    env_vars = runtime_env.get("env_vars") or {}
    return json.dumps(
        {"env_vars": dict(sorted(env_vars.items())),
         "working_dir": runtime_env.get("working_dir") or "",
         "pip": runtime_env.get("pip") or None,
         "py_modules": list(runtime_env.get("py_modules") or [])},
        sort_keys=True,
    )


def validate_runtime_env(runtime_env: Optional[dict]) -> Optional[dict]:
    """Reject unsupported runtime_env fields loudly.

    The reference supports many plugins (python/ray/_private/runtime_env/
    plugin.py); this framework implements env_vars, working_dir, pip, and
    py_modules. Accepting-and-ignoring an option would be a silent no-op,
    which is worse than an error.
    """
    if not runtime_env:
        return runtime_env
    unknown = set(runtime_env) - set(RUNTIME_ENV_SUPPORTED)
    if unknown:
        raise ValueError(
            f"unsupported runtime_env field(s) {sorted(unknown)}; "
            f"supported: {list(RUNTIME_ENV_SUPPORTED)}"
        )
    env_vars = runtime_env.get("env_vars")
    if env_vars is not None:
        if not isinstance(env_vars, dict) or not all(
            isinstance(k, str) and isinstance(v, str) for k, v in env_vars.items()
        ):
            raise ValueError("runtime_env env_vars must be a Dict[str, str]")
    wd = runtime_env.get("working_dir")
    if wd is not None and not isinstance(wd, str):
        raise ValueError("runtime_env working_dir must be a path string")
    out = dict(runtime_env)
    if "pip" in runtime_env:
        out["pip"] = normalize_pip(runtime_env["pip"])
    pm = runtime_env.get("py_modules")
    if pm is not None:
        if not isinstance(pm, (list, tuple)) or not all(
            isinstance(p, str) for p in pm
        ):
            raise ValueError(
                "runtime_env py_modules must be a list of directory paths"
            )
        out["py_modules"] = list(pm)
    return out
