

import os as _os


def repo_root() -> str:
    """Directory containing the ray_tpu package — prepended to PYTHONPATH
    for spawned daemons/workers so they import this same checkout."""
    return _os.path.dirname(_os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))))
