"""Central config-flag system.

Mirrors the reference's single-source-of-truth flag table
(reference: src/ray/common/ray_config_def.h — ~900 RAY_CONFIG(type, name, default)
entries, overridable via RAY_<name> env vars). Here every flag is declared once in
_FLAGS and overridable via ``RTPU_<name>`` environment variables or an explicit
``system_config`` dict passed at init time.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict

_FLAGS: Dict[str, Any] = {
    # --- object store / serialization -------------------------------------
    # Results at or below this size are returned inline in the task reply and live
    # in the owner's in-process memory store; larger ones go to plasma.
    "max_direct_call_object_size": 100 * 1024,
    # Shared-memory object store capacity per node (bytes).
    "object_store_memory": 2 * 1024**3,
    # Chunk size for node-to-node object transfer.
    "object_manager_chunk_size": 4 * 1024**2,
    # --- object spilling / memory pressure ---------------------------------
    # Watermark: spill pinned primaries to disk when plasma use crosses this
    # fraction (reference: object_spilling_threshold).
    "object_spilling_threshold": 0.8,
    "object_spilling_check_period_ms": 500,
    # Node memory fraction beyond which the raylet kills a worker to avert
    # host OOM (reference: memory_monitor.h memory_usage_threshold). Set
    # memory_monitor_refresh_ms to 0 to disable.
    "memory_usage_threshold": 0.95,
    "memory_monitor_refresh_ms": 250,
    # --- control-plane parallelism (stability contract) ---------------------
    # Operators size the control plane with these (README "Scaling the
    # control plane"); renaming any is a breaking change — add new flags
    # instead.
    #   rpc_reactor_shards     event-loop shards per RpcServer: accepted
    #                          connections round-robin across N loops
    #                          (shard 0 = the server's home loop, handlers
    #                          hop home unless marked shard-safe — see the
    #                          rpc.py module docstring). 0 = auto
    #                          (min(4, cpus)); 1 = the classic single-loop
    #                          reactor (what any 1-core box resolves to)
    #   submit_ring_slots      per-submitter plasma-backed submit ring
    #                          capacity in budgeted entries (~1 KiB each):
    #                          eligible tiny-task specs are memcpy'd into
    #                          shared memory and the raylet drains them in
    #                          batches, leaving one doorbell RPC per
    #                          empty→non-empty transition on the hot path.
    #                          0 disables (every submit rides RPC); a full
    #                          or dead ring always falls back to RPC
    #   submit_ring_dead_s     consumer-heartbeat staleness after which a
    #                          producer declares the raylet-side drain dead
    #                          and resubmits pending ring specs via RPC
    #   lease_starvation_passes  batched lease-grant passes a queued lease
    #                          request may be skipped (smaller later
    #                          requests fitting first) before it becomes a
    #                          FIFO barrier that later overlapping requests
    #                          cannot leapfrog — bounds large-request
    #                          starvation under a stream of small leases
    "rpc_reactor_shards": 0,
    "submit_ring_slots": 128,
    "submit_ring_dead_s": 5.0,
    "lease_starvation_passes": 32,
    # --- scheduling --------------------------------------------------------
    # Hybrid policy: pack onto nodes until utilization crosses this, then spread.
    "scheduler_spread_threshold": 0.5,
    "worker_lease_timeout_ms": 30_000,
    # Max tasks shipped per PushTasks RPC when the submit queue is deep
    # (adaptive: batch stays 1 unless queue >> leased workers).
    "task_push_max_batch": 16,
    # Cap on concurrent RequestWorkerLease RPCs per scheduling key.
    "max_lease_requests_in_flight": 16,
    # Direct call channels: blocking-socket fast path for serial sync actor
    # calls (direct_channel.py). RTPU_direct_channels=0 disables.
    "direct_channels": True,
    # Per-node dashboard agent process (dashboard/agent.py): host stats,
    # metrics, profiling, log serving off the raylet's loop. The test
    # suite disables it (conftest) — one extra python process per raylet
    # is pure boot cost on a 1-core CI box.
    "dashboard_agent": True,
    # How many actor-creation lease BATCHES the GCS drives concurrently;
    # each batch pays one GCS->raylet round-trip for up to
    # actor_creation_lease_batch actors (reference: gcs_actor_scheduler.cc
    # leases per-actor in parallel; we batch on top).
    "actor_creation_parallelism": 8,
    "actor_creation_lease_batch": 16,
    # Warm worker pool: after a lease, top idle workers for that job back
    # up to this many in the background (reference: worker_pool.h:359
    # PrestartWorkers). 0 disables.
    "prestart_workers_min_idle": 2,
    # Actor-task pushes pipeline up to this many batch RPCs per actor
    # (reference: actor_task_submitter.h pushes without waiting for prior
    # replies; the receiver's seq_no reorder buffer restores order).
    "actor_push_max_inflight": 4,
    # Thread cap of the persistent pool serving batched normal-task
    # execution (tasks in one batch may synchronize with each other, so
    # each needs its own thread while running).
    "batch_exec_max_threads": 256,
    # How long a PG-bound task waits for its group's 2PC to finish before failing.
    "placement_group_ready_timeout_s": 60.0,
    # Max idle workers kept alive per node (soft cap, like num_cpus in reference).
    "idle_worker_keep_alive_s": 120.0,
    "worker_startup_timeout_s": 60.0,
    # --- fault tolerance ---------------------------------------------------
    "task_max_retries_default": 3,
    "actor_max_restarts_default": 0,
    "health_check_period_ms": 1000,
    "health_check_failure_threshold": 5,
    "max_lineage_bytes": 64 * 1024**2,
    # --- GCS fault tolerance ----------------------------------------------
    # Persist GCS tables to <session_dir>/gcs.log so a restarted GCS resumes
    # the cluster (reference: redis_store_client.h).
    "gcs_persistence": True,
    # fsync every log append (durability vs throughput).
    "gcs_log_fsync": False,
    # Compact the append log into a snapshot once it exceeds this size.
    "gcs_log_compact_bytes": 64 * 1024**2,
    # How long clients retry connecting to a dead GCS before giving up.
    "gcs_reconnect_timeout_s": 30.0,
    # --- timeouts ----------------------------------------------------------
    "gcs_rpc_timeout_s": 30.0,
    "get_timeout_warning_s": 10.0,
    "resource_report_period_ms": 250,
    # --- pubsub ------------------------------------------------------------
    "pubsub_poll_timeout_s": 30.0,
    "pubsub_max_batch": 1000,
    # --- task events / observability --------------------------------------
    "task_events_flush_period_ms": 1000,
    "task_events_max_buffer": 10_000,
    "metrics_report_period_ms": 2000,
    # Flight recorder (_private/flight_recorder.py): per-process ring of
    # structured runtime events, always on (RTPU_flight_recorder=0 disables,
    # e.g. for A/B overhead measurement). Size is events per process.
    "flight_recorder": True,
    "flight_recorder_size": 4096,
    # Stall watchdog (_private/watchdog.py + raylet loop): check cadence;
    # <= 0 disables. A RUNNING/leased task older than watchdog_task_timeout_s,
    # a submitter making no completions for that long, or train-step
    # telemetry silent for watchdog_step_timeout_s raises a GCS incident
    # with captured stacks + a flight-recorder snapshot.
    "watchdog_interval_s": 10.0,
    "watchdog_task_timeout_s": 600.0,
    "watchdog_step_timeout_s": 300.0,
    # --- profiling plane (stability contract) ------------------------------
    # The flag names below are a public interface (operators set them in
    # automation, the README documents them); renaming any is a breaking
    # change — add new flags instead.
    #   profile_slow_step_factor     a train step slower than factor x the
    #                                trailing-median step time triggers an
    #                                automatic cluster profile capture +
    #                                slow_step incident (0 disables)
    #   profile_slow_step_cooldown_s minimum gap between slow-step captures
    #   profile_trigger_duration_s   capture window for triggered profiles
    #   profile_trigger_hz           sampling rate for triggered profiles
    #   profile_on_incident          attach a cluster profile to watchdog
    #                                incidents (stuck_task/no_progress/...)
    #   profile_max_samples          per-process cap on timestamped samples
    #                                kept for the timeline (folded counts
    #                                keep aggregating past it)
    #   device_trace_steps           arm a JAX device trace (jax.profiler)
    #                                for N steps at the next train step;
    #                                no-ops on CPU unless
    #                                RTPU_device_trace_force=1
    #   device_trace_force           capture device traces even on the
    #                                CPU backend (tests / chip-free
    #                                debugging of the trace plumbing)
    "profile_slow_step_factor": 3.0,
    "profile_slow_step_cooldown_s": 600.0,
    "profile_trigger_duration_s": 1.5,
    "profile_trigger_hz": 99.0,
    "profile_on_incident": True,
    "profile_max_samples": 200_000,
    "device_trace_steps": 0,
    "device_trace_force": False,
    # --- perf regression plane (stability contract) -------------------------
    # Same contract as the profiling flags above: operators and CI key on
    # these names (perf.yml, README "Catching a perf regression").
    #   perf_history_path            the perf ledger (JSONL, one entry per
    #                                committed measurement); relative paths
    #                                resolve against the repo root
    #   perf_band_scale              multiplier applied to every noise band
    #                                in _private/perf_gate.py (set >1 on
    #                                boxes noisier than the reference box)
    #   perf_compile_storm_k         >= K post-warmup jit compiles within
    #                                perf_compile_storm_window_s raise a
    #                                jit_cache_miss_storm incident
    #                                (0 disables the check)
    #   perf_compile_storm_window_s  the storm counting window
    #   perf_compile_warmup_steps    compiles while total recorded steps
    #                                <= N are expected (first trace /
    #                                shape priming) and never counted
    "perf_history_path": "PERF_HISTORY.jsonl",
    "perf_band_scale": 1.0,
    "perf_compile_storm_k": 3,
    "perf_compile_storm_window_s": 120.0,
    "perf_compile_warmup_steps": 4,
    # --- memory observability plane (stability contract) --------------------
    # Same contract as the profiling/perf flags above: operators key on
    # these names (README "Hunting a memory leak", alerting automation).
    #   memory_ledger_callsite       capture the user callsite (file:line)
    #                                of every ray.put-shaped object
    #                                creation in the ownership ledger
    #                                (one bounded frame walk per put;
    #                                0 disables, rows show "")
    #   memory_snapshot_period_s     cadence of the per-worker on-disk
    #                                memory snapshot
    #                                (<session>/logs/memory_worker-<pid>
    #                                .json) that OOM forensics attaches to
    #                                death reports; 0 disables
    #   memory_report_top_n          ledger rows per worker in RPC reports
    #                                and snapshots (top holders by size)
    #   memory_leak_sweep_period_s   cadence of the raylet's leak sweep
    #                                (pinned/spilled primaries with no
    #                                live ref in any owner's ledger,
    #                                confirmed across two sweeps);
    #                                0 disables
    #   memory_leak_min_age_s        objects younger than this are never
    #                                leak candidates (in-flight guard on
    #                                top of the two-sweep cross-check)
    #   memory_leak_cooldown_s       minimum gap between object_leak
    #                                incidents from one raylet (each leaked
    #                                object is reported at most once)
    "memory_ledger_callsite": True,
    "memory_snapshot_period_s": 10.0,
    "memory_report_top_n": 50,
    "memory_leak_sweep_period_s": 60.0,
    "memory_leak_min_age_s": 30.0,
    "memory_leak_cooldown_s": 300.0,
    # --- serve.llm continuous-batching engine (stability contract) ----------
    # Same contract as the profiling/perf/memory flags above: operators size
    # replicas with these (README "Serving an LLM"); renaming any is a
    # breaking change — add new flags instead.
    #   llm_block_size        tokens per paged-KV block; admission cost is
    #                         ceil(prompt/block_size) blocks
    #   llm_num_blocks        KV pool size per replica (blocks); with
    #                         block_size 16 the default holds 16k tokens
    #   llm_max_batch         max sequences per fused engine step (prefill
    #                         admits only into spare slots)
    #   llm_max_waiting       admission control: past this many queued
    #                         prompts, submits are shed with a structured
    #                         LLMBackpressure error instead of OOMing the
    #                         cache
    #   llm_pull_wait_s       long-poll window of a token pull (the stream
    #                         ingress re-pulls after an empty reply)
    #   llm_prefix_cache      share full prompt blocks between sequences
    #                         (chained content hash + copy-on-write block
    #                         tables); admission then only prefills the
    #                         un-hit tail. Outputs stay byte-equal to the
    #                         uncached path; 0 disables (cold cache)
    #   llm_spec_k            draft tokens proposed per speculative-decode
    #                         step (verified by the target model in one
    #                         fused forward); only greedy sequences
    #                         speculate. 0 disables even with a draft
    #   llm_draft_model       zoo name of the draft model every LLMReplica
    #                         loads for speculative decoding ("" = off;
    #                         per-deploy `draft_model=` overrides)
    "llm_block_size": 16,
    "llm_num_blocks": 1024,
    "llm_max_batch": 32,
    "llm_max_waiting": 512,
    "llm_pull_wait_s": 2.0,
    "llm_prefix_cache": True,
    "llm_spec_k": 4,
    "llm_draft_model": "",
    # --- chaos / robustness plane (stability contract) ----------------------
    # Same contract as the sections above: CI chaos plans and operator
    # runbooks key on these names (README "Surviving failures").
    #   chaos_plan               declarative fault-injection plan, JSON:
    #                            {"seed": s, "rules": [{"site", "action",
    #                            "after_n"/"after_steps", "every_n",
    #                            "count", "prob", "delay_s", <match>}]}.
    #                            "" disarms and the injection sites cost
    #                            one module attribute read. Drivers publish
    #                            their env plan to GCS KV (ns "chaos", key
    #                            "plan") at init so every joining process
    #                            replays ONE schedule. Site names are a
    #                            contract — see _private/chaos.py.
    #   llm_stream_timeout_s     client-side per-pull timeout of a
    #                            serve.llm token stream (LlmStream); on
    #                            expiry the stream raises a structured
    #                            LlmStreamTimeoutError carrying the stream
    #                            id + tokens received, instead of a raw
    #                            get() timeout
    #   serve_failover_retries   resubmission attempts when a replica dies
    #                            mid-llm-stream (the remaining generation
    #                            moves to a surviving replica, riding the
    #                            prefix cache) and the ActorDiedError retry
    #                            budget of idempotent DeploymentHandle
    #                            calls; 0 disables failover
    #   serve_failover_backoff_s      base of the capped exponential
    #                                 backoff (+/-50% jitter) between
    #                                 failover attempts
    #   serve_failover_backoff_max_s  backoff cap
    #   incident_on_worker_crash publish a worker_crash incident when a
    #                            worker dies by signal with no recorded
    #                            kill reason (OOM kills, scale-downs and
    #                            idle reaps stay incident-free) — the
    #                            chaos suite asserts exactly one incident
    #                            per induced kill
    "chaos_plan": "",
    "llm_stream_timeout_s": 120.0,
    "serve_failover_retries": 6,
    "serve_failover_backoff_s": 0.25,
    "serve_failover_backoff_max_s": 4.0,
    "incident_on_worker_crash": True,
    # --- TPU ---------------------------------------------------------------
    # Autodetect TPU chips on this host; override with RTPU_num_tpu_chips.
    "num_tpu_chips": -1,
    "tpu_pod_type": "",
}


class _Config:
    """Attribute access over the flag table with env-var overrides.

    Precedence: explicit ``apply_system_config`` > ``RTPU_<name>`` env var > default.
    """

    def __init__(self):
        self._overrides: Dict[str, Any] = {}

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        if name in self._overrides:
            return self._overrides[name]
        if name not in _FLAGS:
            raise AttributeError(f"Unknown config flag: {name}")
        default = _FLAGS[name]
        env = os.environ.get(f"RTPU_{name}")
        if env is None:
            return default
        if isinstance(default, bool):
            return env.lower() in ("1", "true", "yes")
        if isinstance(default, int):
            return int(env)
        if isinstance(default, float):
            return float(env)
        return env

    def apply_system_config(self, cfg: Dict[str, Any] | str | None):
        if cfg is None:
            return
        if isinstance(cfg, str):
            cfg = json.loads(cfg)
        for k, v in cfg.items():
            if k not in _FLAGS:
                raise ValueError(f"Unknown config flag: {k}")
            self._overrides[k] = v

    def dump(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in _FLAGS}


RTPU_CONFIG = _Config()
