"""Incident auto-analysis: turn an attached profile capture into a "why".

The watchdog already attaches a merged Perfetto capture (CPU samples +
task/span timeline + device-trace links) to every incident it opens — but a
multi-MB trace is an artifact an operator has to go open. This pass closes
the loop: it inspects the capture the moment it is written and records a
compact, human-readable analysis *inside the incident record itself*, so
``ray-tpu debug incidents`` / ``GET /api/perf`` show the probable cause
without anyone loading Perfetto:

  - **top folded stacks** — where the cluster's CPU time actually went
    during the capture window (per-stack share of all samples);
  - **compile share** — fraction of CPU samples inside jit/XLA compile
    frames, plus the wall-clock share of ``train_step.compile`` spans (the
    StepRecorder's jit-cache-miss bookkeeping): the smoking gun for a
    ``jit_cache_miss_storm`` or a compile-dominated slow step;
  - **scheduling delay** — from the timeline's SUBMITTED→RUNNING flow
    events (``ph:"s"``/``ph:"f"`` pairs): how long tasks sat between
    submission and execution, the signature of a saturated control plane.

Everything here is read-only over the already-written capture file; a
failure to analyze must never lose the incident (callers guard)."""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

# Frames that indicate tracing/lowering/compilation rather than execution.
# Conservative on purpose: matching real XLA/jax internals, not any frame
# that happens to contain "run".
_COMPILE_MARKERS = (
    "compile", "xla_bridge", "pxla", "lower", "jaxpr", "trace_to_",
    "make_jaxpr", "backend_compile",
)

_TOP_STACKS = 5
_STACK_TAIL_FRAMES = 5  # keep the leaf-most frames; full stacks are huge


def _is_compile_stack(stack: str) -> bool:
    s = stack.lower()
    return any(m in s for m in _COMPILE_MARKERS)


def _short_stack(stack: str) -> str:
    frames = stack.split(";")
    if len(frames) <= _STACK_TAIL_FRAMES + 1:
        return stack
    # keep the thread name (first element) + the leaf-most frames
    return frames[0] + ";…;" + ";".join(frames[-_STACK_TAIL_FRAMES:])


def analyze_trace(trace: Dict[str, Any]) -> Dict[str, Any]:
    """Analyze one merged-profile trace object (timeline.merged_profile_trace
    shape: {"traceEvents": [...]}). Pure function over the event list."""
    events = trace.get("traceEvents", []) or []

    stack_us: Dict[str, float] = {}
    total_cpu_us = 0.0
    compile_cpu_us = 0.0
    span_step_us = 0.0
    span_compile_us = 0.0
    task_run_us = 0.0
    flow_starts: Dict[str, float] = {}
    delays_us: List[float] = []

    for ev in events:
        cat = ev.get("cat")
        ph = ev.get("ph")
        if cat == "cpu_sample" and ph == "X":
            dur = float(ev.get("dur") or 0.0)
            stack = (ev.get("args") or {}).get("stack") or ev.get("name", "?")
            stack_us[stack] = stack_us.get(stack, 0.0) + dur
            total_cpu_us += dur
            if _is_compile_stack(stack):
                compile_cpu_us += dur
        elif cat == "span" and ph == "X":
            name = ev.get("name") or ""
            if name.startswith("train_step"):
                dur = float(ev.get("dur") or 0.0)
                span_step_us += dur
                if name == "train_step.compile":
                    span_compile_us += dur
        elif cat == "task" and ph == "X":
            task_run_us += float(ev.get("dur") or 0.0)
        elif cat == "task_flow":
            fid = ev.get("id")
            if ph == "s":
                flow_starts[fid] = float(ev.get("ts") or 0.0)
            elif ph == "f" and fid in flow_starts:
                delays_us.append(
                    max(0.0, float(ev.get("ts") or 0.0)
                        - flow_starts.pop(fid)))

    top = sorted(stack_us.items(), key=lambda kv: -kv[1])[:_TOP_STACKS]
    out: Dict[str, Any] = {
        "cpu_seconds": round(total_cpu_us / 1e6, 3),
        "top_stacks": [
            {"stack": _short_stack(s),
             "share": round(us / total_cpu_us, 4) if total_cpu_us else 0.0,
             "cpu_s": round(us / 1e6, 3)}
            for s, us in top
        ],
        "compile_share": (round(compile_cpu_us / total_cpu_us, 4)
                          if total_cpu_us else None),
    }
    if span_step_us:
        out["compile_span_share"] = round(span_compile_us / span_step_us, 4)
    if delays_us:
        sched = {
            "count": len(delays_us),
            "mean_ms": round(sum(delays_us) / len(delays_us) / 1e3, 3),
            "max_ms": round(max(delays_us) / 1e3, 3),
        }
        busy = sum(delays_us) + task_run_us
        if busy:
            sched["share"] = round(sum(delays_us) / busy, 4)
        out["sched_delay"] = sched
    return out


def summarize(analysis: Dict[str, Any], kind: str = "") -> str:
    """One operator-readable sentence chain — the incident record's 'why'."""
    parts: List[str] = []
    top = analysis.get("top_stacks") or []
    if top:
        leaf = top[0]["stack"].rsplit(";", 1)[-1]
        parts.append(
            f"hottest stack: {leaf} "
            f"({top[0]['share'] * 100:.0f}% of {analysis['cpu_seconds']:.1f} "
            "sampled CPU-s)")
    cs = analysis.get("compile_share")
    if cs is not None:
        span_share = analysis.get("compile_span_share")
        msg = f"jit/XLA compile frames: {cs * 100:.0f}% of CPU samples"
        if span_share is not None:
            msg += (f" (train_step.compile spans: {span_share * 100:.0f}% "
                    "of step wall time)")
        parts.append(msg)
        if kind == "jit_cache_miss_storm" and (cs > 0.2 or
                                               (span_share or 0) > 0.2):
            parts.append("likely cause: recompilation — check for unstable "
                         "input shapes/dtypes or non-hashable static args")
    sd = analysis.get("sched_delay")
    if sd:
        msg = (f"scheduling delay: {sd['count']} submits, "
               f"mean {sd['mean_ms']:.1f} ms, max {sd['max_ms']:.1f} ms")
        if "share" in sd:
            msg += f" ({sd['share'] * 100:.0f}% of task wall time)"
        parts.append(msg)
    if not parts:
        return "capture attached but contained no analyzable events"
    return "; ".join(parts)


def analyze_file(path: str) -> Dict[str, Any]:
    with open(path) as f:
        trace = json.load(f)
    return analyze_trace(trace)


def attach_analysis(incident: Dict[str, Any]) -> bool:
    """Analyze ``incident['profile_path']`` and write the result (plus the
    human-readable ``summary``) into ``incident['analysis']``. Returns
    False — leaving the incident untouched — when there is no capture or it
    is unreadable."""
    path = incident.get("profile_path")
    if not path:
        return False
    try:
        analysis = analyze_file(path)
    except Exception:
        return False
    analysis["summary"] = summarize(analysis, kind=incident.get("kind", ""))
    incident["analysis"] = analysis
    return True


def latest_incident_analysis(gcs, limit: int = 20) -> Optional[Dict[str, Any]]:
    """Newest incident that carries an analysis (dashboard convenience)."""
    try:
        incidents = gcs.call(
            "ListIncidents", {"limit": limit}, timeout=10)["incidents"]
    except Exception:
        return None
    for inc in reversed(incidents):
        if inc.get("analysis"):
            return {"id": inc.get("id"), "kind": inc.get("kind"),
                    "time": inc.get("time"),
                    "analysis": inc["analysis"]}
    return None
