"""Serialization of task args/returns and stored objects.

Mirrors the reference's SerializationContext
(reference: python/ray/_private/serialization.py): cloudpickle for arbitrary
Python, pickle protocol-5 out-of-band buffers for zero-copy numpy/arrow, and
interception of ObjectRefs nested inside values so the runtime can track
borrowed references and resolve dependencies.

Wire format (RPC-inline): {"p": pickle_bytes, "b": [buffer_bytes...], "r": [ref_info...]}
Store format (plasma): a single contiguous byte string:
    [u32 magic][u32 pickle_len][pickle][u32 nbuf]([u64 buf_len][pad to 64][buf])*
Buffers are 64-byte aligned inside the blob so numpy/jax can map them directly.

Copy discipline: `serialize()` returns the protocol-5 out-of-band buffers RAW
(pickle.PickleBuffer views aliasing the caller's arrays). Store-bound paths
must keep them raw and stream them with `write_blob` straight into the mapped
destination — one copy total. Only `inline_payload()` materializes buffer
bytes, and only because msgpack frames require real `bytes`.
"""

from __future__ import annotations

import io
import pickle
import struct
from typing import Any, List, Optional, Tuple

import cloudpickle

from ray_tpu._private.ids import ObjectID
from ray_tpu._private.object_ref import ObjectRef

_MAGIC = 0x52545055  # 'RTPU'
_ALIGN = 64


def _to_host(value):
    """Move a jax.Array to host memory as numpy (device buffers can't be
    pickled). Probes sys.modules instead of importing: if jax was never
    imported in this process the value cannot be a jax array, and a cold
    `import jax` costs ~2 s — a nasty surprise on a first put()/channel
    write in a non-jax process."""
    import sys

    jax = sys.modules.get("jax")
    # getattr guard: another thread may be mid-`import jax`, in which case
    # sys.modules already holds a partially initialized module
    jax_array = getattr(jax, "Array", None) if jax is not None else None
    if jax_array is not None and isinstance(value, jax_array):
        import numpy as np

        return np.asarray(value)
    return value


class _Pickler(cloudpickle.CloudPickler):
    """CloudPickler that collects out-of-band buffers and nested ObjectRefs."""

    def __init__(self, file, buffers: list, refs: list):
        super().__init__(file, protocol=5, buffer_callback=buffers.append)
        self._refs = refs

    def persistent_id(self, obj):
        if isinstance(obj, ObjectRef):
            self._refs.append(obj)
            return ("rtpu_ref", obj.object_id().binary(), obj.owner_address)
        return None


class _Unpickler(pickle.Unpickler):
    def __init__(self, file, buffers, refs_out: list):
        super().__init__(file, buffers=buffers)
        self._refs_out = refs_out

    def persistent_load(self, pid):
        tag, id_bytes, owner = pid
        if tag != "rtpu_ref":
            raise pickle.UnpicklingError(f"unknown persistent id {tag}")
        ref = ObjectRef(ObjectID(id_bytes), owner)
        self._refs_out.append(ref)
        return ref


_PLAIN = (bytes, bytearray, str, int, float, bool, type(None))


def _fast_safe(value, depth: int = 3) -> bool:
    """True if value is a composition of plain types the C pickler handles
    identically to cloudpickle (no functions/classes/refs — those need
    by-value pickling or persistent ids). Exact type checks: subclasses may
    carry custom __reduce__."""
    t = type(value)
    if t in _PLAIN:
        return True
    if t.__module__ == "numpy":
        import numpy as np

        if t is np.ndarray:
            # hasobject also catches object fields nested in structured
            # dtypes, which plain `dtype != object` misses
            return not value.dtype.hasobject
        return isinstance(value, np.generic)  # numpy scalar
    if depth:
        if t in (list, tuple, set):
            return all(_fast_safe(v, depth - 1) for v in value)
        if t is dict:
            return all(
                type(k) in _PLAIN and _fast_safe(v, depth - 1)
                for k, v in value.items()
            )
    return False


def serialize(value: Any) -> Tuple[bytes, List, List[ObjectRef]]:
    """Returns (pickle_bytes, buffers, contained_refs)."""
    value = _to_host(value)
    buffers: List = []
    if _fast_safe(value):
        # C pickler: ~20x faster than the pure-Python CloudPickler for the
        # small control-plane payloads that dominate task/actor-call rates;
        # protocol-5 buffer_callback still gives zero-copy numpy.
        return (
            pickle.dumps(value, protocol=5, buffer_callback=buffers.append),
            buffers,
            [],
        )
    refs: List[ObjectRef] = []
    f = io.BytesIO()
    _Pickler(f, buffers, refs).dump(value)
    return f.getvalue(), buffers, refs


def deserialize(
    pickle_bytes: bytes, buffers: Optional[List] = None
) -> Tuple[Any, List[ObjectRef]]:
    """Returns (value, contained_refs)."""
    refs: List[ObjectRef] = []
    f = io.BytesIO(pickle_bytes)
    value = _Unpickler(f, buffers or [], refs).load()
    return value, refs


# ---------------------------------------------------------------------------
# Inline (RPC) representation
# ---------------------------------------------------------------------------


def buffers_nbytes(buffers: List) -> int:
    """Total payload bytes across raw out-of-band buffers (no copies)."""
    return sum(memoryview(b).nbytes for b in buffers)


def inline_payload(p: bytes, bufs: List) -> dict:
    """Materialize raw buffers into the msgpack-safe inline dict. This is
    the ONLY place out-of-band buffers become `bytes`; plasma-bound values
    must bypass it and ride write_blob instead."""
    return {"p": p, "b": [bytes(b) for b in bufs]}


def serialize_inline(value: Any):
    p, bufs, refs = serialize(value)
    return inline_payload(p, bufs), refs


def deserialize_inline(msg) -> Tuple[Any, List[ObjectRef]]:
    return deserialize(msg["p"], [memoryview(b) for b in msg["b"]])


# ---------------------------------------------------------------------------
# Contiguous blob representation (for the shared-memory store)
# ---------------------------------------------------------------------------

_HDR = struct.Struct("<II")
_BUFHDR = struct.Struct("<Q")


def _aligned(off: int) -> int:
    return (off + _ALIGN - 1) & ~(_ALIGN - 1)


def blob_size(pickle_bytes: bytes, buffers: List) -> int:
    size = _HDR.size + len(pickle_bytes) + 4
    for b in buffers:
        size += _BUFHDR.size
        size = _aligned(size)
        size += memoryview(b).nbytes
    return size


def write_blob(dest: memoryview, pickle_bytes: bytes, buffers: List) -> int:
    """Write the store format into dest; returns bytes written."""
    off = 0
    _HDR.pack_into(dest, off, _MAGIC, len(pickle_bytes))
    off += _HDR.size
    dest[off : off + len(pickle_bytes)] = pickle_bytes
    off += len(pickle_bytes)
    struct.pack_into("<I", dest, off, len(buffers))
    off += 4
    for b in buffers:
        mv = memoryview(b)
        # cast("B") rejects empty views ("zeros in shape"); a 0-byte buffer
        # is just its header
        nbytes = mv.nbytes
        _BUFHDR.pack_into(dest, off, nbytes)
        off += _BUFHDR.size
        off = _aligned(off)
        if nbytes:
            dest[off : off + nbytes] = mv.cast("B")
            off += nbytes
    return off


def serialize_to_blob(value: Any) -> bytearray:
    """Store-format blob as a bytearray sized exactly to content — callers
    (spill files, socket channels) write it out directly; no bytes() copy."""
    p, bufs, _refs = serialize(value)
    out = bytearray(blob_size(p, bufs))
    n = write_blob(memoryview(out), p, bufs)
    assert n == len(out), f"blob_size mismatch: wrote {n} of {len(out)}"
    return out


def read_blob(
    src: memoryview, buffer_wrapper=None
) -> Tuple[Any, List[ObjectRef]]:
    """Deserialize the store format; buffers alias src (zero-copy).

    ``buffer_wrapper(mv)``, when given, wraps each out-of-band buffer view
    before it reaches the unpickler — the worker uses it to tie plasma pins
    to buffer lifetime (worker._pinned_buffer). It is not called when the
    blob has no out-of-band buffers, so callers can release src immediately
    if nothing was wrapped.
    """
    src = memoryview(src).cast("B")
    off = 0
    magic, plen = _HDR.unpack_from(src, off)
    if magic != _MAGIC:
        raise ValueError("corrupt object blob")
    off += _HDR.size
    pickle_bytes = bytes(src[off : off + plen])
    off += plen
    (nbuf,) = struct.unpack_from("<I", src, off)
    off += 4
    buffers = []
    for _ in range(nbuf):
        (blen,) = _BUFHDR.unpack_from(src, off)
        off += _BUFHDR.size
        off = _aligned(off)
        mv = src[off : off + blen]
        buffers.append(mv if buffer_wrapper is None else buffer_wrapper(mv))
        off += blen
    return deserialize(pickle_bytes, buffers)
