"""Node: process supervisor that boots and monitors cluster services.

Counterpart of the reference's Node
(reference: python/ray/_private/node.py — start_head_processes :1353,
start_gcs_server :1150, start_raylet :1181). A head node starts the GCS then a
raylet; worker nodes start only a raylet pointed at an existing GCS. Service
ports are communicated back through port files (the reference uses the same
trick via redis/GCS registration).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import threading
import time
import uuid
from typing import Dict, Optional

from ray_tpu._private.config import RTPU_CONFIG
from ray_tpu._private.ids import NodeID


def _wait_port_file(path: str, proc: subprocess.Popen, timeout: float = 30.0) -> int:
    deadline = time.time() + timeout
    while time.time() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"process exited with code {proc.returncode} before publishing port "
                f"(see logs next to {path})"
            )
        if os.path.exists(path):
            with open(path) as f:
                content = f.read().strip()
            if content:
                return int(content)
        time.sleep(0.02)
    raise TimeoutError(f"timed out waiting for {path}")


def new_session_dir(base: Optional[str] = None) -> str:
    base = base or os.path.join(tempfile.gettempdir(), "ray_tpu")
    session = os.path.join(base, f"session_{time.strftime('%Y-%m-%d_%H-%M-%S')}_{os.getpid()}_{uuid.uuid4().hex[:6]}")
    os.makedirs(os.path.join(session, "logs"), exist_ok=True)
    return session


class Node:
    """Starts/monitors gcs_server and raylet subprocesses on this machine."""

    def __init__(
        self,
        head: bool = False,
        gcs_address: Optional[str] = None,
        host: str = "127.0.0.1",
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        object_store_memory: Optional[int] = None,
        session_dir: Optional[str] = None,
        node_name: str = "",
        gcs_port: int = 0,
    ):
        if not head and not gcs_address:
            raise ValueError("worker node requires gcs_address")
        self.head = head
        self.host = host
        self.session_dir = session_dir or new_session_dir()
        self.node_id = NodeID.from_random()
        self.node_name = node_name or self.node_id.hex()[:8]
        self.resources = dict(resources or {})
        self.labels = dict(labels or {})
        self.object_store_memory = object_store_memory
        self.processes: Dict[str, subprocess.Popen] = {}
        self.gcs_address = gcs_address
        self.raylet_port: Optional[int] = None
        self.gcs_port: Optional[int] = None
        self._shutting_down = False
        self._gcs_monitor: Optional[threading.Thread] = None
        os.makedirs(os.path.join(self.session_dir, "logs"), exist_ok=True)
        if head:
            self._start_gcs(port=gcs_port)
            self._gcs_monitor = threading.Thread(
                target=self._monitor_gcs, name="gcs-monitor", daemon=True
            )
            self._gcs_monitor.start()
        self._start_raylet()

    def _log_files(self, name: str):
        log_dir = os.path.join(self.session_dir, "logs")
        return (
            open(os.path.join(log_dir, f"{name}.out"), "ab"),
            open(os.path.join(log_dir, f"{name}.err"), "ab"),
        )

    def _env(self):
        env = dict(os.environ)
        from ray_tpu._private import repo_root as _repo_root

        repo_root = _repo_root()
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        return env

    def _start_gcs(self, port: int = 0):
        port_file = os.path.join(self.session_dir, f"gcs_port_{self.node_name}")
        # Always clear the stale port file: on a fixed-port restart a
        # leftover file would make _wait_port_file report success even when
        # the new GCS died at startup.
        if os.path.exists(port_file):
            os.remove(port_file)
        out, err = self._log_files("gcs_server")
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "ray_tpu._private.gcs.server",
                f"--host={self.host}",
                f"--port={port}",
                f"--session-dir={self.session_dir}",
                f"--port-file={port_file}",
            ],
            stdout=out, stderr=err, env=self._env(), start_new_session=True,
        )
        self.processes["gcs_server"] = proc
        self.gcs_port = _wait_port_file(port_file, proc)
        self.gcs_address = f"{self.host}:{self.gcs_port}"

    def _monitor_gcs(self):
        """Restart the GCS if it dies unexpectedly (same port, same log).

        The GCS replays <session_dir>/gcs.log on startup and the cluster
        resumes: raylets/workers retry their connections and re-register
        (reference: GCS fault tolerance via Redis persistence + client-side
        gcs_rpc_server_reconnect_timeout_s).
        """
        backoff = 0.5
        while not self._shutting_down:
            proc = self.processes.get("gcs_server")
            if proc is not None and proc.poll() is not None and not self._shutting_down:
                try:
                    self._start_gcs(port=self.gcs_port or 0)
                    backoff = 0.5
                except Exception:
                    time.sleep(backoff)
                    backoff = min(backoff * 2, 10.0)
                    continue
                if self._shutting_down:
                    # shutdown() raced our restart; don't leak the new GCS.
                    try:
                        self.processes["gcs_server"].kill()
                    except Exception:
                        pass
            time.sleep(0.2)

    def _start_raylet(self):
        port_file = os.path.join(self.session_dir, f"raylet_port_{self.node_name}")
        out, err = self._log_files(f"raylet_{self.node_name}")
        cmd = [
            sys.executable, "-m", "ray_tpu._private.raylet.main",
            f"--host={self.host}",
            f"--gcs-address={self.gcs_address}",
            f"--node-id={self.node_id.hex()}",
            f"--resources={json.dumps(self.resources)}",
            f"--labels={json.dumps(self.labels)}",
            f"--session-dir={self.session_dir}",
            f"--port-file={port_file}",
        ]
        if self.head:
            cmd.append("--is-head")
        if self.object_store_memory:
            cmd.append(f"--object-store-memory={self.object_store_memory}")
        proc = subprocess.Popen(
            cmd, stdout=out, stderr=err, env=self._env(), start_new_session=True
        )
        self.processes[f"raylet_{self.node_name}"] = proc
        self.raylet_port = _wait_port_file(port_file, proc)

    @property
    def raylet_address(self):
        return (self.host, self.raylet_port)

    def kill_raylet(self):
        """Fault-injection: kill this node's raylet (chaos testing)."""
        for name, proc in self.processes.items():
            if name.startswith("raylet"):
                proc.kill()

    def kill_gcs(self):
        """Fault-injection: kill -9 the GCS (the monitor restarts it)."""
        proc = self.processes.get("gcs_server")
        if proc is not None:
            proc.kill()

    def shutdown(self):
        self._shutting_down = True
        if self._gcs_monitor is not None and self._gcs_monitor.is_alive():
            # Let an in-flight restart finish (and self-reap) before we
            # sweep self.processes, so no freshly-spawned GCS escapes.
            self._gcs_monitor.join(timeout=5.0)
        for proc in self.processes.values():
            try:
                proc.terminate()
            except Exception:
                pass
        deadline = time.time() + 3
        for proc in self.processes.values():
            try:
                proc.wait(max(0.1, deadline - time.time()))
            except Exception:
                try:
                    proc.kill()
                except Exception:
                    pass
        self.processes.clear()
