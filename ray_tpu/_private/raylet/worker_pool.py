"""Worker pool: leases Python worker processes forked from a warm fork server.

Counterpart of the reference's WorkerPool
(reference: src/ray/raylet/worker_pool.h:159 — StartWorkerProcess :425,
PrestartWorkers :359). Workers are forked from a per-node fork server that has
preimported the runtime (ray_tpu/_private/workers/fork_server.py), so spawn
latency is ~tens of ms. Each spawn carries a startup token; when the new
process's CoreWorker registers back, the token pairs it with its spawn record.
Idle workers are cached per job and reaped after an idle timeout; actors get
dedicated workers that live until the actor dies.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ray_tpu._private.config import RTPU_CONFIG


@dataclass(eq=False)  # identity semantics: handles live in sets/lists
class WorkerHandle:
    worker_id: bytes
    pid: int
    job_id: bytes
    addr: Tuple[str, int] = ("", 0)
    registered: bool = False
    startup_token: int = 0
    alive: bool = True
    # lease state
    leased: bool = False
    lease_id: bytes = b""
    actor_id: bytes = b""
    returncode: Optional[int] = None
    idle_since: float = field(default_factory=time.time)
    register_event: Optional[asyncio.Event] = None
    # canonical runtime-env key: idle reuse only pairs identical envs
    # (reference: worker_pool.h keys pooled workers by runtime_env_hash)
    env_key: str = ""
    log_prefix: str = ""  # session-dir path stem of this worker's .out/.err
    # actor-in-spawn fast path: set when the spawn message carried an actor
    # spec; the creation result arrives inside the child's RegisterWorker
    actor_ready: Optional[asyncio.Event] = None
    actor_result: Optional[dict] = None
    # pool-initiated kill (idle reap, job teardown, shutdown): the death
    # callback must not publish a worker_crash incident for it
    expected_death: bool = False


class WorkerPool:
    def __init__(
        self,
        node_id: bytes,
        raylet_addr: Tuple[str, int],
        gcs_addr: str,
        plasma_name: str,
        session_dir: str,
        on_worker_death=None,
    ):
        self._node_id = node_id
        self._raylet_addr = raylet_addr
        self._gcs_addr = gcs_addr
        self._plasma_name = plasma_name
        self._session_dir = session_dir
        self._on_worker_death_cb = on_worker_death
        self._next_token = 1
        # startup_token -> handle (not yet registered)
        self._starting: Dict[int, WorkerHandle] = {}
        # worker_id -> handle (registered)
        self.workers: Dict[bytes, WorkerHandle] = {}
        self._by_pid: Dict[int, WorkerHandle] = {}
        self._idle: List[WorkerHandle] = []
        self._fs_proc: Optional[asyncio.subprocess.Process] = None
        self._fs_ready: Optional[asyncio.Event] = None
        self._fs_lock = asyncio.Lock()
        # pids whose death arrived before their "spawned" message (the fork
        # server's reaper thread can win that race for insta-crashing workers)
        self._dead_pids: Dict[int, Optional[int]] = {}
        # direct-exec workers (conda/container): (handle, Popen) — their
        # deaths are polled (no fork-server reaper covers them)
        self._exec_procs: list = []

    # ----------------------------------------------------------- fork server

    async def _ensure_fork_server(self):
        """Start (or restart) the fork server; raises if it fails to come up."""
        if self._fs_proc is not None and self._fs_proc.returncode is None:
            await self._await_fs_ready()
            return
        async with self._fs_lock:
            if self._fs_proc is not None and self._fs_proc.returncode is None:
                await self._await_fs_ready()
                return
            self._fs_ready = asyncio.Event()
            env = dict(os.environ)
            repo_root = os.path.dirname(
                os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
            )
            env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
            log_dir = os.path.join(self._session_dir, "logs")
            os.makedirs(log_dir, exist_ok=True)
            err = open(os.path.join(log_dir, "fork_server.err"), "ab")
            self._fs_proc = await asyncio.create_subprocess_exec(
                sys.executable, "-u", "-m", "ray_tpu._private.workers.fork_server",
                f"--raylet-host={self._raylet_addr[0]}",
                f"--raylet-port={self._raylet_addr[1]}",
                f"--gcs-address={self._gcs_addr}",
                f"--session-dir={self._session_dir}",
                stdin=asyncio.subprocess.PIPE,
                stdout=asyncio.subprocess.PIPE,
                stderr=err,
                env=env,
            )
            asyncio.ensure_future(self._fs_read_loop(self._fs_proc, self._fs_ready))
            await self._await_fs_ready()

    async def _await_fs_ready(self):
        try:
            await asyncio.wait_for(
                self._fs_ready.wait(), RTPU_CONFIG.worker_startup_timeout_s
            )
        except asyncio.TimeoutError:
            raise RuntimeError("fork server did not become ready") from None
        if self._fs_proc is None or self._fs_proc.returncode is not None:
            raise RuntimeError("fork server died during startup")

    async def _fs_read_loop(self, proc, ready_event):
        while True:
            line = await proc.stdout.readline()
            if not line:
                break
            try:
                msg = json.loads(line)
            except json.JSONDecodeError:
                continue
            if msg.get("ready"):
                ready_event.set()
            elif "spawned" in msg:
                handle = self._starting.get(msg["spawned"])
                if handle is not None:
                    handle.pid = msg["pid"]
                    if msg["pid"] in self._dead_pids:
                        # the worker crashed before its spawn was announced
                        self._mark_dead(handle, self._dead_pids.pop(msg["pid"]))
                    else:
                        self._by_pid[msg["pid"]] = handle
            elif "dead" in msg:
                handle = self._by_pid.pop(msg["dead"], None)
                if handle is not None:
                    self._mark_dead(handle, msg.get("rc"))
                else:
                    self._dead_pids[msg["dead"]] = msg.get("rc")
        # Fork server EOF: wake any waiters so they fail fast instead of
        # hanging; a later spawn restarts it.
        ready_event.set()

    def _mark_dead(self, handle: WorkerHandle, rc: Optional[int]):
        if not handle.alive:
            return
        handle.alive = False
        handle.returncode = rc
        self._by_pid.pop(handle.pid, None)
        self.workers.pop(handle.worker_id, None)
        self._starting.pop(handle.startup_token, None)
        if handle in self._idle:
            self._idle.remove(handle)
        if handle.register_event is not None:
            handle.register_event.set()
        if handle.actor_ready is not None:
            handle.actor_ready.set()
        if self._on_worker_death_cb is not None:
            asyncio.ensure_future(self._on_worker_death_cb(handle))

    async def _fs_send(self, msg: dict):
        self._fs_proc.stdin.write((json.dumps(msg) + "\n").encode())
        await self._fs_proc.stdin.drain()

    # -------------------------------------------------------------- spawning

    @staticmethod
    def _env_key(env_overrides) -> str:
        if not env_overrides:
            return ""
        # JSON, not delimiter-joining: raw values may contain ';'/'=' and
        # must not let distinct environments collide onto one pooled worker.
        return json.dumps(sorted(env_overrides.items()))

    async def start_worker(
        self, job_id: bytes, env_overrides=None, spawn_extra: Optional[dict] = None
    ) -> WorkerHandle:
        from ray_tpu._private import chaos as _chaos

        if _chaos.ARMED:
            act = _chaos.hit("raylet.spawn", job=job_id.hex())
            if act is not None:
                if act["action"] == "delay":
                    await asyncio.sleep(act["delay_s"])
                elif act["action"] in ("fail", "error", "drop"):
                    raise RuntimeError("chaos: worker spawn failed (injected)")
        if env_overrides and ("RTPU_SPAWN_PYTHON" in env_overrides
                              or "RTPU_SPAWN_PREFIX" in env_overrides):
            # conda / container runtime_env: the worker must run under a
            # DIFFERENT interpreter or inside a container, which a fork of
            # this interpreter can never provide — exec default_worker.py
            # directly (reference: conda.py worker command rewrite,
            # image_uri.py worker-in-container).
            return await self._start_worker_exec(
                job_id, env_overrides, spawn_extra)
        await self._ensure_fork_server()
        token = self._next_token
        self._next_token += 1
        log_prefix = os.path.join(self._session_dir, "logs", f"worker-{token}")
        handle = WorkerHandle(
            worker_id=b"", pid=0, job_id=job_id,
            startup_token=token, register_event=asyncio.Event(),
            env_key=self._env_key(env_overrides),
        )
        handle.log_prefix = log_prefix
        self._starting[token] = handle
        msg = {
            "token": token,
            "job_id": job_id.hex(),
            "env": env_overrides or {},
            "log_prefix": log_prefix,
        }
        if spawn_extra:
            msg.update(spawn_extra)
            if "actor" in spawn_extra:
                handle.actor_ready = asyncio.Event()
        await self._fs_send({"spawn": msg})
        return handle

    async def _start_worker_exec(
        self, job_id: bytes, env_overrides: dict,
        spawn_extra: Optional[dict] = None,
    ) -> WorkerHandle:
        """Spawn a worker as a fresh subprocess of an arbitrary interpreter
        (conda env python) and/or under a command prefix (docker run ...).
        No actor-in-spawn fast path here: the handle's actor_ready stays
        None, so the actor lease path drives CreateActor over RPC exactly
        like the idle-reuse branch."""
        import subprocess

        from ray_tpu._private import repo_root

        env_overrides = dict(env_overrides)
        # env_key must cover the FULL overrides (incl. spawn keys): a conda
        # worker must never be pooled/reused for a different env's task.
        env_key = self._env_key(env_overrides)
        python = env_overrides.pop("RTPU_SPAWN_PYTHON", "") or sys.executable
        prefix = json.loads(env_overrides.pop("RTPU_SPAWN_PREFIX", "") or "[]")
        token = self._next_token
        self._next_token += 1
        log_prefix = os.path.join(self._session_dir, "logs", f"worker-{token}")
        handle = WorkerHandle(
            worker_id=b"", pid=0, job_id=job_id,
            startup_token=token, register_event=asyncio.Event(),
            env_key=env_key,
        )
        handle.log_prefix = log_prefix
        self._starting[token] = handle
        cmd = prefix + [
            python, "-m", "ray_tpu._private.workers.default_worker",
            "--raylet-host", self._raylet_addr[0],
            "--raylet-port", str(self._raylet_addr[1]),
            "--gcs-address", self._gcs_addr,
            "--node-id", self._node_id.hex(),
            "--plasma-name", self._plasma_name,
            "--job-id", job_id.hex(),
            "--startup-token", str(token),
            "--session-dir", self._session_dir,
        ]
        child_env = dict(os.environ)
        child_env.update({k: str(v) for k, v in env_overrides.items()})
        child_env["PYTHONPATH"] = (
            repo_root() + os.pathsep + child_env.get("PYTHONPATH", ""))
        os.makedirs(os.path.dirname(log_prefix), exist_ok=True)
        try:
            out = open(log_prefix + ".out", "ab")
            err = open(log_prefix + ".err", "ab")
            try:
                # Own session: kill_worker kills by PROCESS GROUP (the fork
                # server's killpg) — without setsid this worker would share
                # the raylet's group and a routine idle-reap would SIGKILL
                # the whole node.
                proc = subprocess.Popen(cmd, env=child_env, stdout=out,
                                        stderr=err, stdin=subprocess.DEVNULL,
                                        start_new_session=True)
            finally:
                out.close()
                err.close()
        except Exception:
            # Never leak the _starting entry (it would skew prestart's
            # accounting forever and hold the cap).
            self._starting.pop(token, None)
            raise
        handle.pid = proc.pid
        self._by_pid[proc.pid] = handle
        self._exec_procs.append((handle, proc))
        return handle

    def on_worker_registered(
        self, startup_token: int, worker_id: bytes, addr: Tuple[str, int]
    ) -> Optional[WorkerHandle]:
        handle = self._starting.pop(startup_token, None)
        if handle is None:
            return None
        handle.worker_id = worker_id
        handle.addr = addr
        handle.registered = True
        self.workers[worker_id] = handle
        handle.register_event.set()
        return handle

    async def pop_worker(
        self, job_id: bytes, env_overrides=None, spawn_extra: Optional[dict] = None
    ) -> Optional[WorkerHandle]:
        """Get an idle worker for the job or fork a fresh one. Awaits
        registration — or, when `spawn_extra` carries an actor spec, the
        creation result folded into the child's RegisterWorker request (the
        actor initializes during boot, so the lease path pays one
        round-trip instead of lease+create).

        An idle hit returns a registered worker with `actor_ready is None`;
        the caller then drives CreateActor over RPC itself."""
        env_key = self._env_key(env_overrides)
        for i, h in enumerate(self._idle):
            if h.job_id == job_id and h.alive and h.env_key == env_key:
                self._idle.pop(i)
                h.leased = True
                return h
        try:
            handle = await self.start_worker(job_id, env_overrides, spawn_extra)
        except Exception:
            # fork server failed to start or its stdin pipe broke; callers
            # (lease handlers) must release their resource grants on None.
            return None
        wait_event = handle.actor_ready or handle.register_event
        try:
            await asyncio.wait_for(
                wait_event.wait(), RTPU_CONFIG.worker_startup_timeout_s
            )
        except asyncio.TimeoutError:
            await self.kill_worker(handle)
            return None
        if not handle.registered:
            return None
        handle.leased = True
        return handle

    def on_actor_created(self, worker_id: bytes, startup_token: int,
                         result: dict):
        """Spawn-time actor creation outcome (from RegisterWorker)."""
        handle = self.workers.get(worker_id)
        if handle is None:
            handle = self._starting.get(startup_token)
        if handle is not None and handle.actor_ready is not None:
            handle.actor_result = result
            handle.actor_ready.set()

    def push_idle(self, handle: WorkerHandle):
        handle.leased = False
        handle.lease_id = b""
        handle.idle_since = time.time()
        if handle.alive:
            self._idle.append(handle)

    async def kill_worker(self, handle: WorkerHandle):
        # Pool-initiated: the death callback must not treat it as a crash.
        handle.expected_death = True
        if handle.pid:
            if self._fs_proc is not None and self._fs_proc.returncode is None:
                try:
                    await self._fs_send({"kill": handle.pid})
                except Exception:
                    self._kill_pid(handle.pid)
            else:
                # fork server gone: the worker is orphaned to init; kill it
                # directly (same host) — the liveness poll reports the death.
                self._kill_pid(handle.pid)
        self.workers.pop(handle.worker_id, None)
        if handle in self._idle:
            self._idle.remove(handle)
        self._starting.pop(handle.startup_token, None)

    @staticmethod
    def _kill_pid(pid: int):
        try:
            os.killpg(os.getpgid(pid), 9)
        except Exception:
            try:
                os.kill(pid, 9)
            except Exception:
                pass

    def reap_idle(self):
        now = time.time()
        keep = []
        for h in self._idle:
            if now - h.idle_since > RTPU_CONFIG.idle_worker_keep_alive_s:
                asyncio.ensure_future(self.kill_worker(h))
            else:
                keep.append(h)
        self._idle = keep

    def check_liveness(self):
        """Fallback death detection: if the fork server died, its orphaned
        workers can't be waitpid-ed by anyone — poll pid liveness directly.
        Direct-exec (conda/container) workers are OUR subprocesses and are
        always polled (reaps the zombie too)."""
        for handle, proc in list(self._exec_procs):
            if proc.poll() is not None:
                self._exec_procs.remove((handle, proc))
                self._mark_dead(handle, proc.returncode)
        if self._fs_proc is not None and self._fs_proc.returncode is None:
            return
        for handle in list(self._by_pid.values()):
            try:
                os.kill(handle.pid, 0)
            except ProcessLookupError:
                self._mark_dead(handle, None)
            except Exception:
                pass

    def kill_job_workers(self, job_id: bytes):
        for h in list(self.workers.values()):
            if h.job_id == job_id and not h.actor_id:
                asyncio.ensure_future(self.kill_worker(h))

    def shutdown(self):
        # include workers still starting (forked but not yet registered)
        handles = (
            set(self.workers.values())
            | set(self._starting.values())
            | set(self._by_pid.values())
        )
        for h in handles:
            h.expected_death = True
            if h.pid:
                self._kill_pid(h.pid)
        if self._fs_proc is not None and self._fs_proc.returncode is None:
            try:
                self._fs_proc.kill()
            except Exception:
                pass

    def num_idle(self) -> int:
        return len(self._idle)

    async def prestart(self, job_id: bytes, env_overrides=None,
                       target_idle: int = 2, cap_starting: int = 8):
        """Keep warm registered workers ready for this job (reference:
        worker_pool.h:359 PrestartWorkers). Called fire-and-forget after
        lease activity: tops idle+starting up to `target_idle` so the next
        lease pops a booted worker instead of paying fork+boot latency.
        On a saturated single core this converts nothing (boot CPU is the
        bound — measured: creation runs at 0% idle); on real multi-core
        hosts the boots overlap the caller's work."""
        env_key = self._env_key(env_overrides)
        have = sum(
            1 for h in self._idle
            if h.job_id == job_id and h.alive and h.env_key == env_key
        )
        # In-flight starts for this job count toward the target, or a lease
        # burst fires N prestarts that each see have=0 and over-spawn to
        # the global cap.
        have += sum(
            1 for h in self._starting.values()
            if h.job_id == job_id and h.env_key == env_key
        )
        need = min(target_idle - have, cap_starting - len(self._starting))
        if need <= 0:
            return
        handles = []
        try:
            for _ in range(need):
                handles.append(
                    await self.start_worker(job_id, env_overrides))
        except Exception:
            pass  # fork server broke; still settle what did start
        for handle in handles:
            try:
                await asyncio.wait_for(
                    handle.register_event.wait(),
                    RTPU_CONFIG.worker_startup_timeout_s)
            except asyncio.TimeoutError:
                await self.kill_worker(handle)
                continue
            if handle.registered and not handle.leased:
                self.push_idle(handle)
