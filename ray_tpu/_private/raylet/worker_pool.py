"""Worker pool: spawns and leases Python worker processes.

Counterpart of the reference's WorkerPool
(reference: src/ray/raylet/worker_pool.h:159 — StartWorkerProcess :425,
PrestartWorkers :359). Workers are spawned with a startup token; when the new
process's CoreWorker connects back and registers, the token pairs it with its
spawn record. Idle workers are cached per job and reaped after an idle
timeout; actors get dedicated workers that live until the actor dies.
"""

from __future__ import annotations

import asyncio
import os
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ray_tpu._private.config import RTPU_CONFIG


@dataclass
class WorkerHandle:
    worker_id: bytes
    pid: int
    proc: subprocess.Popen
    job_id: bytes
    addr: Tuple[str, int] = ("", 0)
    registered: bool = False
    startup_token: int = 0
    # lease state
    leased: bool = False
    lease_id: bytes = b""
    actor_id: bytes = b""
    idle_since: float = field(default_factory=time.time)
    register_event: Optional[asyncio.Event] = None


class WorkerPool:
    def __init__(
        self,
        node_id: bytes,
        raylet_addr: Tuple[str, int],
        gcs_addr: str,
        plasma_name: str,
        session_dir: str,
        node_manager_port: int = 0,
    ):
        self._node_id = node_id
        self._raylet_addr = raylet_addr
        self._gcs_addr = gcs_addr
        self._plasma_name = plasma_name
        self._session_dir = session_dir
        self._next_token = 1
        # startup_token -> handle (not yet registered)
        self._starting: Dict[int, WorkerHandle] = {}
        # worker_id -> handle (registered)
        self.workers: Dict[bytes, WorkerHandle] = {}
        self._idle: List[WorkerHandle] = []

    def start_worker(self, job_id: bytes, env_overrides=None) -> WorkerHandle:
        token = self._next_token
        self._next_token += 1
        log_dir = os.path.join(self._session_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        stdout = open(os.path.join(log_dir, f"worker-{token}.out"), "ab")
        stderr = open(os.path.join(log_dir, f"worker-{token}.err"), "ab")
        env = dict(os.environ)
        env.update(env_overrides or {})
        env["PYTHONPATH"] = (
            os.path.dirname(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
            + os.pathsep
            + env.get("PYTHONPATH", "")
        )
        cmd = [
            sys.executable,
            "-m",
            "ray_tpu._private.workers.default_worker",
            f"--raylet-host={self._raylet_addr[0]}",
            f"--raylet-port={self._raylet_addr[1]}",
            f"--gcs-address={self._gcs_addr}",
            f"--node-id={self._node_id.hex()}",
            f"--plasma-name={self._plasma_name}",
            f"--job-id={job_id.hex()}",
            f"--startup-token={token}",
            f"--session-dir={self._session_dir}",
        ]
        proc = subprocess.Popen(
            cmd, stdout=stdout, stderr=stderr, env=env, start_new_session=True
        )
        handle = WorkerHandle(
            worker_id=b"", pid=proc.pid, proc=proc, job_id=job_id,
            startup_token=token, register_event=asyncio.Event(),
        )
        self._starting[token] = handle
        return handle

    def on_worker_registered(
        self, startup_token: int, worker_id: bytes, addr: Tuple[str, int]
    ) -> Optional[WorkerHandle]:
        handle = self._starting.pop(startup_token, None)
        if handle is None:
            return None
        handle.worker_id = worker_id
        handle.addr = addr
        handle.registered = True
        self.workers[worker_id] = handle
        handle.register_event.set()
        return handle

    async def pop_worker(self, job_id: bytes, env_overrides=None) -> Optional[WorkerHandle]:
        """Get an idle worker for the job or start a fresh one. Awaits registration."""
        for i, h in enumerate(self._idle):
            if h.job_id == job_id and h.proc.poll() is None:
                self._idle.pop(i)
                h.leased = True
                return h
        handle = self.start_worker(job_id, env_overrides)
        try:
            await asyncio.wait_for(
                handle.register_event.wait(), RTPU_CONFIG.worker_startup_timeout_s
            )
        except asyncio.TimeoutError:
            self.kill_worker(handle)
            return None
        handle.leased = True
        return handle

    def push_idle(self, handle: WorkerHandle):
        handle.leased = False
        handle.lease_id = b""
        handle.idle_since = time.time()
        if handle.proc.poll() is None:
            self._idle.append(handle)

    def kill_worker(self, handle: WorkerHandle):
        try:
            handle.proc.kill()
        except Exception:
            pass
        self.workers.pop(handle.worker_id, None)
        if handle in self._idle:
            self._idle.remove(handle)
        self._starting.pop(handle.startup_token, None)

    def reap_dead(self) -> List[WorkerHandle]:
        """Poll children; return handles of workers that exited."""
        dead = []
        for h in list(self.workers.values()):
            if h.proc.poll() is not None:
                dead.append(h)
                self.workers.pop(h.worker_id, None)
                if h in self._idle:
                    self._idle.remove(h)
        for token, h in list(self._starting.items()):
            if h.proc.poll() is not None:
                self._starting.pop(token)
        return dead

    def reap_idle(self):
        now = time.time()
        keep = []
        for h in self._idle:
            if now - h.idle_since > RTPU_CONFIG.idle_worker_keep_alive_s:
                self.kill_worker(h)
            else:
                keep.append(h)
        self._idle = keep

    def kill_job_workers(self, job_id: bytes):
        for h in list(self.workers.values()):
            if h.job_id == job_id and not h.actor_id:
                self.kill_worker(h)

    def shutdown(self):
        for h in list(self.workers.values()):
            self.kill_worker(h)
        for h in list(self._starting.values()):
            try:
                h.proc.kill()
            except Exception:
                pass

    def num_idle(self) -> int:
        return len(self._idle)
