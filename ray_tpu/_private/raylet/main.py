"""Raylet — the per-node agent: worker pool, leases, local scheduling, object plane.

Counterpart of the reference's raylet/NodeManager
(reference: src/ray/raylet/node_manager.h:119, main.cc:123). One asyncio loop
runs: the lease protocol (RequestWorkerLease/ReturnWorker — reference:
node_manager.cc:1794), placement-group bundle 2PC
(reference: placement_group_resource_manager.h), the node-to-node object
manager (pull + chunked fetch — reference: object_manager/object_manager.cc),
worker lifecycle (spawn/reap, death reports to GCS), heartbeats and resource
reports. The plasma segment for the node is created here and shared with every
worker on the host.

Scheduling is the reference's two-level design: owners cache leases per
scheduling key and push tasks worker-to-worker; the raylet only places
*leases*, locally when it can, spilling to a peer picked from the
GCS-maintained cluster view otherwise (hybrid pack-then-spread policy,
reference: raylet/scheduling/policy/hybrid_scheduling_policy.cc).
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import sys
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from ray_tpu._native.plasma import PlasmaClient, PlasmaOOM
from ray_tpu._private import accelerators
from ray_tpu._private import chaos as _chaos
from ray_tpu._private import flight_recorder as _fr
from ray_tpu._private import runtime_env as renv
from ray_tpu._private.config import RTPU_CONFIG
from ray_tpu._private.gcs.client import GcsAioClient
from ray_tpu._private.ids import NodeID
from ray_tpu._private.raylet.resources import ResourceSet
from ray_tpu._private.raylet.worker_pool import WorkerPool
from ray_tpu._private.rpc import ClientPool, OobPayload, RpcServer

import msgpack

logger = logging.getLogger("ray_tpu.raylet")


class NodeManager:
    def __init__(
        self,
        node_id: NodeID,
        host: str,
        gcs_address: str,
        resources: Dict[str, float],
        labels: Dict[str, str],
        session_dir: str,
        is_head: bool = False,
        object_store_memory: Optional[int] = None,
    ):
        self.node_id = node_id
        self.host = host
        self.gcs_address = gcs_address
        self.session_dir = session_dir
        self.is_head = is_head
        self.server = RpcServer(host)
        from ray_tpu._private import schema as _schema

        self.server.set_validator(_schema.make_validator(_schema.RAYLET_SCHEMAS))
        gcs_host, gcs_port = gcs_address.rsplit(":", 1)
        self.gcs = GcsAioClient(gcs_host, int(gcs_port))
        self.pool = ClientPool()

        self.total = ResourceSet(resources)
        self.available = ResourceSet(resources)
        self.labels = labels
        self._resources_dirty = True
        # Per-instance accelerator IDs (reference: scheduling_ids.h:162 —
        # GPU_0-style instances; here TPU chip ids). Integer-TPU leases get
        # specific chips via TPU_VISIBLE_CHIPS so two concurrent workers
        # never see the same chip; fractional demands share the pool.
        self._free_chips: List[int] = list(range(int(resources.get("TPU", 0))))

        self.plasma_name = f"/rtpu_plasma_{node_id.hex()[:12]}"
        self.plasma = PlasmaClient(
            self.plasma_name,
            capacity=object_store_memory or RTPU_CONFIG.object_store_memory,
            create=True,
        )

        self.worker_pool: Optional[WorkerPool] = None  # needs our port first

        # lease_id -> {"worker_id", "resources": ResourceSet, "bundle": key|None}
        self.leases: Dict[bytes, dict] = {}
        self._lease_seq = 0
        # queued lease requests waiting for local resources, FIFO. Releases
        # coalesce into one _lease_grant_pass per loop tick (no per-release
        # thundering herd); a waiter skipped lease_starvation_passes times
        # becomes a barrier later overlapping requests cannot leapfrog.
        self._lease_waiters: List[dict] = []
        self._lease_pass_scheduled = False
        self._starve_limit = max(1, RTPU_CONFIG.lease_starvation_passes)
        # plasma-backed submit rings (one per attached submitter):
        # ring object id -> {consumer, backlog, idle leases, ...}
        self._rings: Dict[bytes, dict] = {}
        self._ring_event: Optional[asyncio.Event] = None
        self._ring_task = None
        # (pg_id, bundle_index) -> {"reserved": ResourceSet, "available": ResourceSet,
        #                            "committed": bool}
        self.bundles: Dict[Tuple[bytes, int], dict] = {}
        # worker_id -> actor_id for dedicated actor workers
        self._actor_workers: Dict[bytes, bytes] = {}
        self._job_sys_path_cache: Dict[bytes, list] = {}
        self._fn_blob_cache: Dict[bytes, bytes] = {}
        # cluster view: node_id -> info (from GCS)
        self.cluster_view: Dict[bytes, dict] = {}
        self._autoscaler_active = False
        # object pulls in flight: object_id bytes -> asyncio.Event
        self._pulls: Dict[bytes, asyncio.Event] = {}
        self._recv: Dict[bytes, dict] = {}  # inbound pushes mid-transfer
        # Explicit guard for the _recv landing counters: chunk sinks run on
        # reactor shard threads (ReceiveChunk is shard-safe) while aborts
        # run on the home loop — the counter read-modify-writes must not
        # rely on single-loop serialization anymore.
        import threading as _threading

        self._recv_lock = _threading.Lock()
        self._venv_locks: Dict[str, asyncio.Lock] = {}
        self._venv_jobs: Dict[str, set] = {}  # venv hash -> jobs using it
        # pinned primary copies: object_id bytes -> memoryview
        self._pinned: Dict[bytes, memoryview] = {}
        # spilled primaries: object_id bytes -> (path, size). A spilled object
        # may ALSO be in plasma (restored); then re-spilling is a free drop.
        # (reference: raylet/local_object_manager.h:41 spill/restore)
        self._spilled: Dict[bytes, Tuple[str, int]] = {}
        self._spill_dir = os.path.join(
            session_dir or ".", f"spilled_{node_id.hex()[:12]}"
        )
        self._spill_lock = asyncio.Lock()
        # worker_id -> reason, for deaths we caused (OOM kills)
        self._kill_reasons: Dict[bytes, str] = {}
        # --- memory observability plane --------------------------------
        # object_id -> ownership attribution shipped with PinObject
        # ({owner_addr, job_id, actor_id, task_id, callsite, size, t});
        # joined against _pinned/_spilled by GetMemoryReport and the leak
        # sweep, dropped with the object in FreeObjects.
        self._pin_meta: Dict[bytes, dict] = {}
        # leak detector state: first-unowned-seen time per candidate, the
        # confirmed-leak records (still present), and ids already reported
        self._leak_candidates: Dict[bytes, float] = {}
        self._leaks: Dict[bytes, dict] = {}
        self._leak_fired: set = set()
        self._last_leak_incident = 0.0
        # OOM forensics: live-grabbed memory report of a worker we are
        # about to kill (worker_id -> report), attached to its death report
        self._death_memory: Dict[bytes, dict] = {}
        self._bg = []
        try:
            import psutil

            psutil.cpu_percent(interval=None)  # prime: first call reads 0.0
        except Exception:
            pass

    # ------------------------------------------------------------- lifecycle

    async def start(self, port: int = 0) -> int:
        self.server.register_all(self)
        # Inbound push chunks stream from the socket straight into the
        # pre-created plasma buffer at their offset (zero intermediate
        # buffering) — see _receive_chunk_sink.
        self.server.set_oob_sink("ReceiveChunk", self._receive_chunk_sink)
        # Sharded-reactor dispatch contract (rpc.py docstring): handlers
        # default to the home loop so the lease/bundle/lifecycle state
        # above keeps its single-threaded invariants; only the bulk
        # data-plane methods — whose state is either read-only here or
        # guarded by the plasma store's native in-segment mutex and the
        # _recv landing counters — run directly on a connection's shard.
        self.server.set_shard_safe(
            {"Ping", "ReceiveChunk", "FetchChunk", "FetchObjectInfo"})
        port = await self.server.start(port)
        self.port = port
        self.worker_pool = WorkerPool(
            self.node_id.binary(),
            (self.host, port),
            self.gcs_address,
            self.plasma_name,
            self.session_dir,
            on_worker_death=self._on_worker_death,
        )
        # Warm the fork server immediately so the first lease forks in ~ms
        # (reference: worker_pool.h:359 PrestartWorkers).
        asyncio.ensure_future(self.worker_pool._ensure_fork_server())
        try:
            from ray_tpu._private.metrics import start_metrics_http_server

            self._metrics_server, self.metrics_port = await start_metrics_http_server(
                self.host, self._collect_metrics
            )
        except Exception:
            logger.exception("metrics endpoint failed to start")
            self.metrics_port = 0
        await self._register_node()
        # Chaos plane: arm from the env plan or the one the driver
        # published to GCS KV, so this raylet replays the cluster schedule.
        try:
            if RTPU_CONFIG.chaos_plan:
                _chaos.load_plan(RTPU_CONFIG.chaos_plan)
            else:
                plan = await self.gcs.kv_get(b"chaos", b"plan")
                if plan:
                    _chaos.load_plan(plan)
        except Exception:
            pass
        if RTPU_CONFIG.dashboard_agent:
            try:
                self._spawn_agent()
            except Exception:
                logger.exception("dashboard agent failed to start")
        self._bg.append(asyncio.ensure_future(self._heartbeat_loop()))
        self._bg.append(asyncio.ensure_future(self._reaper_loop()))
        self._bg.append(asyncio.ensure_future(self._cluster_view_loop()))
        self._bg.append(asyncio.ensure_future(self._spill_loop()))
        self._bg.append(asyncio.ensure_future(self._memory_monitor_loop()))
        if RTPU_CONFIG.memory_leak_sweep_period_s > 0:
            self._bg.append(asyncio.ensure_future(self._leak_sweep_loop()))
        self._bg.append(asyncio.ensure_future(self._log_monitor_loop()))
        if RTPU_CONFIG.watchdog_interval_s > 0:
            self._bg.append(asyncio.ensure_future(self._watchdog_loop()))
        if self.session_dir:
            try:
                _fr.install_exit_dump(os.path.join(
                    self.session_dir, "logs",
                    f"flight_raylet-{os.getpid()}.jsonl"))
            except Exception:
                pass
        logger.info(
            "raylet %s on %s:%s resources=%s",
            self.node_id.hex()[:12], self.host, port, self.total.to_dict(),
        )
        return port

    async def _register_node(self):
        await self.gcs.call(
            "RegisterNode",
            {
                "node_id": self.node_id.binary(),
                "ip": self.host,
                "raylet_port": self.port,
                "plasma_name": self.plasma_name,
                "resources": self.total.to_dict(),
                "labels": self.labels,
                "is_head": self.is_head,
                "metrics_port": getattr(self, "metrics_port", 0),
            },
        )

    def _collect_metrics(self) -> str:
        """Prometheus samples for this node (reference: stats/metric_defs.cc
        resource/object-store/scheduler gauges)."""
        from ray_tpu._private.metrics import render_prometheus

        node = self.node_id.hex()[:12]
        samples = []
        for k, v in self.total.to_dict().items():
            samples.append(
                ("ray_tpu_node_resource_total", {"node": node, "resource": k}, v)
            )
        for k, v in self.available.to_dict().items():
            samples.append(
                ("ray_tpu_node_resource_available", {"node": node, "resource": k}, v)
            )
        idle = self.worker_pool.num_idle()
        total_workers = len(self.worker_pool.workers)
        samples.append(("ray_tpu_node_workers", {"node": node, "state": "idle"}, idle))
        samples.append(
            ("ray_tpu_node_workers", {"node": node, "state": "leased"},
             max(0, total_workers - idle))
        )
        samples.append(("ray_tpu_node_leases", {"node": node}, len(self.leases)))
        samples.append(
            ("ray_tpu_node_pg_bundles", {"node": node}, len(self.bundles))
        )
        try:
            s = self.plasma.stats()
            samples.append(("ray_tpu_object_store_used_bytes", {"node": node}, s["used_bytes"]))
            samples.append(("ray_tpu_object_store_capacity_bytes", {"node": node}, s["capacity_bytes"]))
            samples.append(("ray_tpu_object_store_num_objects", {"node": node}, s["num_objects"]))
            samples.append(("ray_tpu_object_store_evicted_bytes", {"node": node}, s["evicted_bytes"]))
        except Exception:
            pass
        samples.append(("ray_tpu_spilled_objects", {"node": node}, len(self._spilled)))
        samples.append(
            ("ray_tpu_spilled_bytes", {"node": node},
             sum(size for _, size in self._spilled.values()))
        )
        samples.append(("ray_tpu_pulls_in_flight", {"node": node}, len(self._pulls)))
        # memory observability plane (stability contract, util/metrics.py)
        samples.append(
            ("ray_tpu_object_store_pinned_bytes", {"node": node},
             sum(v.nbytes for v in self._pinned.values()))
        )
        samples.append(
            ("ray_tpu_object_store_leaked_bytes", {"node": node},
             sum(r["size"] for r in self._leaks.values()))
        )
        try:
            from ray_tpu._private import memory_report as _mr

            samples.append(
                ("ray_tpu_memory_rss_bytes", {"node": node, "role": "raylet"},
                 _mr.process_rss())
            )
            samples.append(
                ("ray_tpu_memory_rss_bytes", {"node": node, "role": "worker"},
                 sum(_mr.process_rss(h.pid)
                     for h in self.worker_pool.workers.values() if h.pid))
            )
            agent_pid = getattr(getattr(self, "_agent_proc", None), "pid", None)
            if agent_pid:
                samples.append(
                    ("ray_tpu_memory_rss_bytes",
                     {"node": node, "role": "agent"},
                     _mr.process_rss(agent_pid))
                )
        except Exception:
            pass
        # per-node host stats (reference: dashboard reporter_agent.py:314
        # psutil cpu/mem/per-worker probes)
        try:
            import psutil

            samples.append(
                ("ray_tpu_node_cpu_percent", {"node": node},
                 psutil.cpu_percent(interval=None))
            )
            vm = psutil.virtual_memory()
            samples.append(
                ("ray_tpu_node_mem_used_bytes", {"node": node}, vm.used)
            )
            samples.append(
                ("ray_tpu_node_mem_total_bytes", {"node": node}, vm.total)
            )
            for h in self.worker_pool.workers.values():
                try:
                    rss = psutil.Process(h.pid).memory_info().rss
                except Exception:
                    continue
                samples.append(
                    ("ray_tpu_worker_rss_bytes",
                     {"node": node, "pid": str(h.pid)}, rss)
                )
        except Exception:
            pass
        return render_prometheus(samples)

    async def _heartbeat_loop(self):
        period = RTPU_CONFIG.health_check_period_ms / 1000.0
        report_period = RTPU_CONFIG.resource_report_period_ms / 1000.0
        last_report = 0.0
        last_pending: List[dict] = []
        while True:
            try:
                if _chaos.ARMED:
                    act = _chaos.hit("raylet.heartbeat",
                                     node=self.node_id.hex())
                    if act is not None:
                        if act["action"] == "delay":
                            await asyncio.sleep(act["delay_s"])
                        elif act["action"] == "drop":
                            await asyncio.sleep(period)
                            continue  # one silent beat
                beat = await self.gcs.call(
                    "Heartbeat", {"node_id": self.node_id.binary()}, timeout=10
                )
                if beat is not None:
                    self._autoscaler_active = beat.get("autoscaler_active", False)
                    if not beat.get("known", True):
                        # The GCS restarted without our registration
                        # (persistence off or state lost): re-register so
                        # the cluster resumes.
                        logger.warning("GCS lost our registration; re-registering")
                        await self._register_node()
                        self._resources_dirty = True
                now = time.time()
                pending = [dict(w["resources"]) for w in self._lease_waiters
                           if "resources" in w]
                if (
                    self._resources_dirty
                    or pending != last_pending  # incl. drain-to-empty: a
                    # stale pending report makes the autoscaler double-launch
                    or now - last_report > report_period * 4
                ):
                    last_pending = pending
                    await self.gcs.notify(
                        "ReportResources",
                        {
                            "node_id": self.node_id.binary(),
                            "available": self.available.to_dict(),
                            "total": self.total.to_dict(),
                            "pending_demands": pending,
                            "num_leases": len(self.leases),
                            "num_workers": len(self.worker_pool.workers),
                        },
                    )
                    self._resources_dirty = False
                    last_report = now
            except Exception:
                pass
            await asyncio.sleep(min(period, report_period))

    async def _refresh_cluster_view(self):
        nodes = await self.gcs.get_all_node_info()
        new_view = {n["node_id"]: n for n in nodes if n["state"] == "ALIVE"}
        grew = set(new_view) - set(self.cluster_view)
        self.cluster_view = new_view
        if grew:
            # New capacity (e.g. autoscaler launch): re-evaluate queued
            # lease requests so they can spill to it (full wake — waiters
            # must re-run spill logic, not just retry a local acquire).
            self._kick_waiters(wake_all=True)

    async def _cluster_view_loop(self):
        """Push-based cluster view (reference: RaySyncer resource broadcast,
        common/ray_syncer/ray_syncer.h:88 — bidirectional gRPC streams; here
        the GCS pubsub 'node'/'resources' channels drained with batched
        long-polls). Full refetches happen only on membership growth, GCS
        epoch change, or a slow 15s safety net — not on a fixed 500ms poll.
        """
        sub_id = b"raylet-view:" + self.node_id.binary()
        subscribed = False
        epoch = None
        last_full = 0.0
        while True:
            try:
                if not subscribed:
                    for ch in ("node", "resources"):
                        r = await self.gcs.call(
                            "Subscribe", {"sub_id": sub_id, "channel": ch},
                            timeout=10,
                        )
                        # baseline the epoch from the subscribe reply so a
                        # GCS restart before the first poll is detected
                        epoch = r.get("epoch", epoch)
                    subscribed = True
                    await self._refresh_cluster_view()
                    last_full = time.time()
                reply = await self.gcs.call(
                    "PubsubPoll", {"sub_id": sub_id, "timeout": 10.0},
                    timeout=30,
                )
                new_epoch = reply.get("epoch")
                if epoch is not None and new_epoch != epoch:
                    # GCS restarted: its subscriber table is gone
                    subscribed = False
                    epoch = new_epoch
                    continue
                epoch = new_epoch
                refresh = False
                for channel, msg in reply.get("batch", []):
                    if channel == "node":
                        if msg.get("state") == "DEAD":
                            self.cluster_view.pop(msg["node_id"], None)
                        else:
                            refresh = True  # new node: fetch its full record
                    elif channel == "resources":
                        info = self.cluster_view.get(msg["node_id"])
                        if info is not None:
                            info["resources_available"] = msg["available"]
                            info["resources_total"] = msg["total"]
                            info["num_leases"] = msg.get(
                                "num_leases", info.get("num_leases", 0))
                            info["num_workers"] = msg.get(
                                "num_workers", info.get("num_workers", 0))
                if refresh or time.time() - last_full > 15.0:
                    await self._refresh_cluster_view()
                    last_full = time.time()
            except Exception:
                subscribed = False
                await asyncio.sleep(0.5)

    async def _reaper_loop(self):
        while True:
            await asyncio.sleep(1.0)
            try:
                self.worker_pool.reap_idle()
                self.worker_pool.check_liveness()
                self._check_agent()
                _fr.flush_to_file()
            except Exception:
                logger.exception("reaper error")

    # ----------------------------------------------------- stall watchdog

    async def _watchdog_loop(self):
        """Raylet-side stall watchdog: probe every leased worker's
        live-RUNNING registry (GetCoreWorkerStats) and fire one incident
        per task that has been executing past
        ``RTPU_watchdog_task_timeout_s`` — with the worker's stacks and
        this node's flight-recorder tail captured while the hang is live.
        Lease age alone is NOT the signal (actor workers hold their lease
        for the actor's whole life); the executing-task age is.
        watchdog.py is the driver-side counterpart — the raylet also sees
        hangs whose owner/driver is itself wedged."""
        from ray_tpu._private import watchdog as _wd

        fired: set = set()  # task_ids already reported
        interval = RTPU_CONFIG.watchdog_interval_s
        while True:
            await asyncio.sleep(interval)
            try:
                timeout = RTPU_CONFIG.watchdog_task_timeout_s
                seen: set = set()
                for h in list(self.worker_pool.workers.values()):
                    if not (h.alive and h.leased and h.addr[1]):
                        continue
                    try:
                        client = await self.pool.get(*h.addr)
                        stats = await client.call(
                            "GetCoreWorkerStats", {}, timeout=5)
                    except Exception:
                        continue
                    for rt in stats.get("running_tasks", []):
                        task_id = rt.get("task_id", b"")
                        seen.add(task_id)
                        if rt.get("age", 0) <= timeout or task_id in fired:
                            continue
                        fired.add(task_id)
                        await self._fire_stuck_task_incident(_wd, h, rt)
                fired &= seen  # resolved tasks leave; the set stays bounded
            except Exception:
                logger.exception("raylet watchdog error")

    async def _fire_stuck_task_incident(self, _wd, handle, rt: dict):
        worker_id = handle.worker_id
        task_id = rt.get("task_id", b"")
        _fr.record("watchdog.fire", task_id, "stuck_task")
        stacks = []
        try:
            r = await self.handle_ProfileWorker(
                {"worker_id": worker_id, "duration": 0.5})
            stacks.append({
                "target": f"worker:{worker_id.hex()[:12]}",
                "folded": r.get("folded", ""),
                "error": r.get("error", ""),
            })
        except Exception as e:
            stacks.append({"target": f"worker:{worker_id.hex()[:12]}",
                           "folded": "", "error": str(e)})
        actor_id = self._actor_workers.get(worker_id)
        incident = _wd.build_incident(
            "stuck_task", "raylet",
            f"task {rt.get('name', '?')} has been RUNNING for "
            f"{rt.get('age', 0):.0f}s on worker {worker_id.hex()[:12]} "
            f"(pid {handle.pid})"
            + (f", actor {actor_id.hex()[:12]}" if actor_id else ""),
            node_id=self.node_id.hex(),
            worker_id=worker_id.hex(),
            task_id=task_id.hex() if isinstance(task_id, bytes) else "",
            task_name=rt.get("name", ""),
            stacks=stacks,
        )
        try:
            await self.gcs.call(
                "ReportIncident", {"incident": incident}, timeout=10)
        except Exception:
            pass

    # ------------------------------------------------- per-node agent child

    def _spawn_agent(self):
        """Launch the per-node dashboard agent beside this raylet
        (reference: dashboard/agent.py:25 — the raylet starts agent.py and
        the head fans node-scoped work out to it)."""
        import subprocess
        import sys as _sys

        log_dir = self.session_dir or "."
        out = open(os.path.join(
            log_dir, f"agent_{self.node_id.hex()[:12]}.log"), "ab")
        self._agent_proc = subprocess.Popen(
            [_sys.executable, "-m", "ray_tpu.dashboard.agent",
             "--gcs-address", self.gcs_address,
             "--node-id", self.node_id.hex(),
             "--raylet-port", str(self.port),
             "--session-dir", self.session_dir or "",
             "--host", self.host,
             "--raylet-pid", str(os.getpid())],
            stdout=out, stderr=subprocess.STDOUT,
        )
        out.close()

    def _check_agent(self):
        """Agent death detection: report to the GCS failure log (visible in
        GetWorkerFailures / the dashboard) and restart, capped — a
        crash-looping agent must not fork forever."""
        proc = getattr(self, "_agent_proc", None)
        if proc is None or proc.poll() is None:
            return
        rc = proc.returncode
        self._agent_proc = None
        asyncio.ensure_future(self.gcs.notify(
            "ReportWorkerDeath",
            {"worker_id": b"agent-" + self.node_id.binary(),
             "node_id": self.node_id.binary(), "actor_id": None,
             "reason": f"dashboard agent exited with code {rc}"},
        ))
        self._agent_restarts = getattr(self, "_agent_restarts", 0) + 1
        if self._agent_restarts <= 3:
            logger.warning(
                "dashboard agent died (rc=%s); restart %d/3",
                rc, self._agent_restarts)
            self._spawn_agent()
        else:
            logger.error("dashboard agent died (rc=%s); restart cap hit", rc)
            asyncio.ensure_future(self._deregister_agent())

    async def _deregister_agent(self):
        """Drop the agent's KV entry so head fan-outs stop burning connect
        timeouts on a dead address."""
        try:
            await self.gcs.call(
                "KVDel", {"ns": b"agents", "key": self.node_id.hex().encode()},
                timeout=5)
        except Exception:
            pass

    async def _on_worker_death(self, handle):
        # release any leases held by this worker
        for lease_id, lease in list(self.leases.items()):
            if lease["worker_id"] == handle.worker_id:
                self._release_lease(lease_id)
        actor_id = self._actor_workers.pop(handle.worker_id, None)
        rc = handle.returncode
        kill_reason = self._kill_reasons.pop(handle.worker_id, None)
        reason = kill_reason or f"exit code {rc}"
        _fr.record("worker.death", handle.worker_id, reason[:120])
        # An UNATTRIBUTED signal death (no recorded kill reason, not a
        # pool-initiated kill, not shutdown) is a crash worth an incident:
        # chaos kills, segfaults, external OOM killers. Intentional kills —
        # ray_tpu.kill, memory-monitor OOM, idle reap, scale-down — all
        # record a reason or mark the handle first, so they stay
        # incident-free and the chaos suite can assert exactly one
        # worker_crash incident per induced kill.
        if (kill_reason is None and isinstance(rc, int) and rc < 0
                and not getattr(handle, "expected_death", False)
                and not getattr(self, "_draining", False)
                and RTPU_CONFIG.incident_on_worker_crash):
            asyncio.ensure_future(self._report_worker_crash(
                handle, actor_id, rc))
        # Forensics: the dead worker's flight-recorder file (incrementally
        # appended while it lived, so it exists even after SIGKILL) — its
        # tail rides the death report into death_cause / ActorDiedError, so
        # "what was it doing when it died" is IN the error the caller sees.
        tail = self._worker_flight_tail(handle.pid)
        if tail:
            reason = f"{reason}\nlast flight-recorder events of the worker:\n{tail}"
        # OOM forensics: the worker's final memory report — live-grabbed by
        # the memory monitor just before an OOM kill, else the periodic
        # on-disk snapshot (survives SIGKILL, same pattern as the flight
        # tail) — rides the death report into ActorDiedError, so "what was
        # resident when it died" is IN the error the caller sees.
        mem_tail = self._worker_memory_tail(handle)
        if mem_tail:
            reason = f"{reason}\nmemory snapshot at death (top holders):\n{mem_tail}"
        await self.gcs.notify(
            "ReportWorkerDeath",
            {
                "worker_id": handle.worker_id,
                "node_id": self.node_id.binary(),
                "actor_id": actor_id,
                "reason": reason,
            },
        )

    async def _report_worker_crash(self, handle, actor_id, rc: int):
        """Publish a worker_crash incident for an unattributed signal
        death (see _on_worker_death). Attribution: node, pid, signal,
        actor id, plus the worker's flight tail."""
        try:
            from ray_tpu._private.watchdog import build_incident

            detail = f"worker pid={handle.pid} died by signal {-rc}"
            if actor_id:
                detail += f" (actor {bytes(actor_id).hex()[:12]})"
            tail = self._worker_flight_tail(handle.pid)
            if tail:
                detail += f"\nlast flight-recorder events:\n{tail}"
            inc = build_incident(
                "worker_crash", "raylet", detail,
                node_id=self.node_id.hex(),
                worker_id=bytes(handle.worker_id).hex()
                if handle.worker_id else "",
            )
            inc["pid"] = handle.pid
            await self.gcs.call("ReportIncident", {"incident": inc},
                                timeout=10)
        except Exception:
            pass

    def _worker_memory_tail(self, handle) -> str:
        from ray_tpu._private import memory_report as _mr

        report = self._death_memory.pop(handle.worker_id, None)
        if report is None and handle.pid and self.session_dir:
            report = _mr.read_snapshot(self.session_dir, handle.pid)
        if not report:
            return ""
        try:
            return _mr.format_top_holders(report)[:1500]
        except Exception:
            return ""

    def _worker_flight_tail(self, pid, limit: int = 8) -> str:
        if not pid or not self.session_dir:
            return ""
        path = os.path.join(self.session_dir, "logs",
                            f"flight_worker-{pid}.jsonl")
        try:
            events = _fr.read_tail_file(path, limit=limit)
        except Exception:
            return ""
        return _fr.format_tail(events)[:1500]

    # ------------------------------------------------------ resource helpers

    def _pool_for(self, strategy: dict):
        """Returns (acquire_set, bundle_key) — PG tasks draw from their bundle."""
        if strategy.get("type") == "placement_group":
            key = (strategy["pg_id"], strategy.get("bundle_index") or 0)
            bundle = self.bundles.get(key)
            if bundle is None or not bundle["committed"]:
                return None, key
            return bundle["available"], key
        return self.available, None

    def _try_acquire(self, resources: Dict[str, float], strategy: dict):
        demand = ResourceSet(resources)
        pool, bundle_key = self._pool_for(strategy)
        if pool is None:
            return None
        if pool.acquire(demand):
            self._resources_dirty = True
            return {"demand": demand, "bundle": bundle_key}
        return None

    def _allocate_chips(self, num_tpu: float) -> Optional[List[int]]:
        """Assign specific chip ids to an integer-TPU lease; None when the
        demand is fractional/zero (worker then sees the node default)."""
        if num_tpu <= 0 or num_tpu != int(num_tpu):
            return None
        n = int(num_tpu)
        if len(self._free_chips) < n:
            return None
        chips, self._free_chips = self._free_chips[:n], self._free_chips[n:]
        return chips

    def _release_lease(self, lease_id: bytes):
        lease = self.leases.pop(lease_id, None)
        if lease is None:
            return
        _fr.record("lease.return", lease_id, lease["worker_id"].hex()[:12])
        if lease.get("chips"):
            self._free_chips.extend(lease["chips"])
            self._free_chips.sort()
        if lease["bundle"] is not None:
            bundle = self.bundles.get(lease["bundle"])
            if bundle is not None:
                bundle["available"].release(lease["grant"]["demand"])
        else:
            self.available.release(lease["grant"]["demand"])
        self._resources_dirty = True
        self._kick_waiters()
        if self._rings and self._ring_event is not None:
            # freed capacity may unblock a ring backlog
            self._ring_event.set()

    def _kick_waiters(self, wake_all: bool = False):
        """Lease-grant batching: resource releases coalesce into ONE FIFO
        scheduling pass per loop tick (K concurrent drivers' releases cost
        one pass over the queue, not K thundering-herd wakeups that each
        re-run the whole feasibility check). ``wake_all`` keeps the legacy
        wake-everything behavior for topology changes — a new node or a
        returned/removed bundle — where waiters must re-run their full
        spill/PG logic, not just retry a local acquire."""
        if not self._lease_waiters:
            return
        if wake_all:
            waiters, self._lease_waiters = self._lease_waiters, []
            for w in waiters:
                w["event"].set()
            return
        if not self._lease_pass_scheduled:
            self._lease_pass_scheduled = True
            asyncio.get_running_loop().call_soon(self._lease_grant_pass)

    def _lease_grant_pass(self):
        """One batched scheduling pass over ``_lease_waiters`` in FIFO
        order: acquire resources for every waiter that now fits and wake
        only those. Fairness: a waiter skipped ``lease_starvation_passes``
        times becomes a barrier — no later waiter with overlapping demand
        may leapfrog it, so a large request can't be starved indefinitely
        by a stream of small ones that fit first."""
        self._lease_pass_scheduled = False
        waiters = self._lease_waiters
        if not waiters:
            return
        remaining: List[dict] = []
        barriers: List[dict] = []
        for w in waiters:
            if w["event"].is_set():
                continue  # woken elsewhere; handler will clean up
            if any(self._demands_overlap(b, w) for b in barriers):
                remaining.append(w)
                continue
            grant = self._try_acquire(w["res"], w["strat"])
            if grant is not None:
                w["grant"] = grant
                w["event"].set()
                continue
            w["skips"] += 1
            if w["skips"] >= self._starve_limit:
                barriers.append(w)
            remaining.append(w)
        self._lease_waiters = remaining

    @staticmethod
    def _demands_overlap(a: dict, b: dict) -> bool:
        """Do two queued lease demands draw from the same pool/resources?
        (the unit of the starvation barrier)"""
        a_pg = a["strat"].get("type") == "placement_group"
        b_pg = b["strat"].get("type") == "placement_group"
        if a_pg != b_pg:
            return False
        if a_pg:
            return (a["strat"]["pg_id"], a["strat"].get("bundle_index") or 0) \
                == (b["strat"]["pg_id"], b["strat"].get("bundle_index") or 0)
        return any(v > 0 and b["res"].get(k, 0) > 0
                   for k, v in a["res"].items())

    def _blocked_by_starving(self, resources: Dict[str, float],
                             strategy: dict) -> bool:
        """Fresh lease requests must not leapfrog a starving queued waiter
        with overlapping demand — they queue behind it instead."""
        if not self._lease_waiters:
            return False
        probe = {"res": resources, "strat": strategy}
        return any(w["skips"] >= self._starve_limit
                   and self._demands_overlap(w, probe)
                   for w in self._lease_waiters)

    def _waiter_abandon(self, waiter: dict):
        """A timed-out waiter leaves the queue; a grant that raced the
        timeout is returned to its pool (the client is about to retry)."""
        if waiter in self._lease_waiters:
            self._lease_waiters.remove(waiter)
        grant = waiter.pop("grant", None)
        if grant is not None:
            pool, _ = self._pool_for(waiter["strat"])
            if pool is not None:
                pool.release(grant["demand"])
            self._resources_dirty = True
            self._kick_waiters()

    def _local_feasible(self, resources: Dict[str, float], strategy: dict) -> bool:
        if strategy.get("type") == "placement_group":
            key = (strategy["pg_id"], strategy.get("bundle_index") or 0)
            bundle = self.bundles.get(key)
            return bundle is not None and bundle["committed"]
        return self.total.fits(ResourceSet(resources))

    @staticmethod
    def _labels_match(labels: Dict[str, str], selector) -> bool:
        return all(labels.get(k) == v for k, v in (selector or {}).items())

    def _pick_spill_node(
        self, resources: Dict[str, float], strategy: dict, require_available: bool
    ) -> Optional[dict]:
        """Hybrid policy over the GCS cluster view; returns peer node info or
        None. node_label strategies (reference:
        raylet/scheduling/policy/node_label_scheduling_policy.cc) restrict
        candidates to hard-label matches and prefer soft-label matches."""
        demand = ResourceSet(resources)
        is_label = strategy.get("type") == "node_label"
        hard = strategy.get("hard") if is_label else None
        soft = strategy.get("soft") if is_label else None
        best = None
        best_score = None
        for nid, info in self.cluster_view.items():
            if nid == self.node_id.binary():
                continue
            if is_label and not self._labels_match(info.get("labels", {}), hard):
                continue
            total = ResourceSet(info.get("resources_total", {}))
            avail = ResourceSet(info.get("resources_available", {}))
            if not total.fits(demand):
                continue
            if require_available and not avail.fits(demand):
                continue
            td, ad = total.to_dict(), avail.to_dict()
            used = sum(1 - ad.get(k, 0) / v for k, v in td.items() if v > 0)
            if strategy.get("type") == "spread":
                score = used  # least loaded wins
            else:
                score = -used  # pack: most loaded feasible wins
            if soft and self._labels_match(info.get("labels", {}), soft):
                score -= 100.0  # soft matches dominate the load score
            if best_score is None or score < best_score:
                best, best_score = info, score
        return best

    # ------------------------------------------------------------ worker RPC

    async def handle_RegisterWorker(self, req):
        addr = (self.host, req["port"])
        token = req.get("startup_token", -1)
        _fr.record("worker.spawn", req["worker_id"], req.get("pid", 0))
        if token >= 0:
            self.worker_pool.on_worker_registered(token, req["worker_id"], addr)
        if "actor_result" in req:
            # spawn-time actor creation result riding the registration
            self.worker_pool.on_actor_created(
                req["worker_id"], token, req.get("actor_result") or {}
            )
        return {
            "node_id": self.node_id.binary(),
            "plasma_name": self.plasma_name,
            "gcs_address": self.gcs_address,
        }

    async def handle_RequestWorkerLease(self, req):
        """Grant a local worker, tell the caller to spill, or queue."""
        resources = req.get("resources", {})
        strategy = req.get("strategy", {})
        job_id = req["job_id"]
        deadline = time.time() + RTPU_CONFIG.worker_lease_timeout_ms / 1000.0

        affinity = strategy.get("type") == "node_affinity"
        if affinity and strategy.get("node_id") != self.node_id.binary():
            target = self.cluster_view.get(strategy.get("node_id"))
            if target is None:
                if strategy.get("soft"):
                    strategy = {}
                else:
                    return {"error": "affinity node not alive"}
            else:
                return {"spill": {"ip": target["ip"], "port": target["raylet_port"],
                                   "node_id": target["node_id"]}}

        if strategy.get("type") == "node_label":
            hard = strategy.get("hard") or {}
            soft = strategy.get("soft") or {}
            if not self._labels_match(self.labels, hard):
                target = self._pick_spill_node(
                    resources, strategy, require_available=False
                )
                if target is None:
                    return {"error": (
                        f"no alive node matches required labels {hard}"
                    )}
                return {"spill": {
                    "ip": target["ip"], "port": target["raylet_port"],
                    "node_id": target["node_id"],
                }}
            if soft and not self._labels_match(self.labels, soft):
                # Local node satisfies hard but not soft: prefer a peer that
                # satisfies both and has free capacity; otherwise stay local
                # (soft preference never makes placement infeasible).
                target = self._pick_spill_node(
                    resources, strategy, require_available=True
                )
                if target is not None and self._labels_match(
                    target.get("labels", {}), soft
                ):
                    return {"spill": {
                        "ip": target["ip"], "port": target["raylet_port"],
                        "node_id": target["node_id"],
                    }}

        # PG-bound tasks are routed by the owner to the raylet holding the
        # bundle; they queue on that bundle and never spill (reference:
        # local_task_manager keeps PG tasks local to the committed bundle).
        is_pg = strategy.get("type") == "placement_group"
        if is_pg:
            pg_key = (strategy["pg_id"], strategy.get("bundle_index") or 0)
            bundle = self.bundles.get(pg_key)
            if bundle is None or not bundle["committed"]:
                return {"retry_pg": True}
            if not bundle["reserved"].fits(ResourceSet(resources)):
                # Fail fast like the reference's submission-time bundle check.
                return {"error": (
                    f"task demands {resources} which can never fit in "
                    f"placement group bundle {bundle['reserved'].to_dict()}"
                )}

        try:
            env_overrides = await self._runtime_env_overrides(
                req.get("runtime_env"), req.get("job_id", b"")
            )
        except Exception as e:
            return {"error": f"runtime_env setup failed: {e}"}

        waiter = None
        while True:
            if is_pg and pg_key not in self.bundles:
                return {"error": "placement group removed"}
            grant = None
            if waiter is not None:
                # woken by the batched grant pass: it may have acquired on
                # our behalf (FIFO, starvation-bounded); a grant-less wake
                # (topology change) re-runs the full logic below
                grant = waiter.pop("grant", None)
                waiter = None
            if grant is None and not self._blocked_by_starving(resources,
                                                               strategy):
                grant = self._try_acquire(resources, strategy)
            if grant is not None:
                chips = self._allocate_chips(resources.get("TPU", 0))
                worker_env = dict(env_overrides or {})
                if chips is not None:
                    worker_env.update(accelerators.visible_chip_env(chips))
                handle = await self.worker_pool.pop_worker(
                    job_id, worker_env or None
                )
                prestart = RTPU_CONFIG.prestart_workers_min_idle
                if prestart > 0 and not chips:
                    # Top the warm pool back up in the background so the
                    # NEXT lease pops a booted worker (reference:
                    # worker_pool.h:359 PrestartWorkers). Fired AFTER
                    # pop_worker so the observed idle count no longer
                    # includes the worker just taken — scheduling it before
                    # the pop settled the pool one below the target.
                    # Chip-bound leases are excluded — their env is
                    # per-lease.
                    asyncio.ensure_future(self.worker_pool.prestart(
                        job_id, worker_env or None,
                        target_idle=prestart))
                if handle is None:
                    # worker failed to start; release and retry
                    pool, _ = self._pool_for(strategy)
                    pool.release(grant["demand"])
                    if chips:
                        self._free_chips.extend(chips)
                        self._free_chips.sort()
                    return {"error": "worker startup failed"}
                self._lease_seq += 1
                lease_id = self._lease_seq.to_bytes(8, "little") + os.urandom(4)
                handle.lease_id = lease_id
                self.leases[lease_id] = {
                    "worker_id": handle.worker_id,
                    "grant": grant,
                    "bundle": grant["bundle"],
                    "chips": chips,
                    "t": time.time(),
                }
                _fr.record("lease.grant", lease_id,
                           handle.worker_id.hex()[:12])
                return {
                    "granted": True,
                    "worker_addr": list(handle.addr),
                    "worker_id": handle.worker_id,
                    "lease_id": lease_id,
                }

            if not is_pg:
                # Can't grant now. Spread tasks and locally-infeasible tasks spill.
                spill_now = self._pick_spill_node(resources, strategy, require_available=True)
                local_ok = self._local_feasible(resources, strategy)
                if strategy.get("type") == "spread" and spill_now is not None:
                    # crude spread: alternate between local queue and remote
                    return {"spill": {"ip": spill_now["ip"], "port": spill_now["raylet_port"],
                                       "node_id": spill_now["node_id"]}}
                if not local_ok:
                    if spill_now is not None:
                        return {"spill": {"ip": spill_now["ip"], "port": spill_now["raylet_port"],
                                           "node_id": spill_now["node_id"]}}
                    spill_any = self._pick_spill_node(resources, strategy, require_available=False)
                    if spill_any is None:
                        # Authoritative view refresh before declaring
                        # infeasibility: a just-registered node may not have
                        # reached our pushed view yet (rare path, one RPC).
                        try:
                            await self._refresh_cluster_view()
                        except Exception:
                            pass
                        spill_any = self._pick_spill_node(
                            resources, strategy, require_available=False
                        )
                    if spill_any is not None:
                        return {"spill": {"ip": spill_any["ip"], "port": spill_any["raylet_port"],
                                           "node_id": spill_any["node_id"]}}
                    if not self._autoscaler_active:
                        # Authoritative check (the heartbeat may not have
                        # seen a just-started autoscaler): only on this
                        # rare infeasible path.
                        try:
                            r = await self.gcs.call(
                                "GetAutoscalerActive", {}, timeout=5
                            )
                            self._autoscaler_active = bool(r.get("active"))
                        except Exception:
                            pass
                    if not self._autoscaler_active:
                        return {"error": f"infeasible resource request {resources}"}
                    # else: queue below — the recorded demand will drive an
                    # autoscaler launch, and the new node kicks the waiter.
                if spill_now is not None:
                    return {"spill": {"ip": spill_now["ip"], "port": spill_now["raylet_port"],
                                       "node_id": spill_now["node_id"]}}
            # queue locally until resources free up; the recorded shape
            # feeds the GCS load report that drives the autoscaler
            # (reference: gcs_autoscaler_state_manager.h cluster load).
            # PG-bound tasks are excluded: their bundle is already placed,
            # so a new node could never serve them — reporting them would
            # trigger pointless slice launches.
            new_waiter = {"event": asyncio.Event(), "res": dict(resources),
                          "strat": strategy, "skips": 0}
            if not is_pg:
                new_waiter["resources"] = dict(resources)
            self._lease_waiters.append(new_waiter)
            timeout = deadline - time.time()
            if timeout <= 0:
                self._waiter_abandon(new_waiter)
                return {"retry": True}
            try:
                await asyncio.wait_for(new_waiter["event"].wait(), timeout)
            except asyncio.TimeoutError:
                self._waiter_abandon(new_waiter)
                return {"retry": True}
            waiter = new_waiter

    async def handle_ReturnWorker(self, req):
        lease = self.leases.get(req["lease_id"])
        if lease is not None:
            self._release_lease(req["lease_id"])
            handle = self.worker_pool.workers.get(lease["worker_id"])
            if handle is not None:
                if req.get("kill"):
                    await self.worker_pool.kill_worker(handle)
                else:
                    self.worker_pool.push_idle(handle)
        return {"ok": True}

    # ---------------------------------------------- plasma-backed submit ring
    # (_private/submit_ring.py) A submitter memcpys serialized tiny-task
    # specs into a shared-memory ring; this raylet drains batches per loop
    # tick and dispatches them onto its own locally-leased workers, sending
    # replies back as ONE batched notify per push batch. The only hot-path
    # RPC left is the submitter's doorbell on empty→non-empty transitions.

    async def handle_AttachSubmitRing(self, req):
        from ray_tpu._private.submit_ring import RingConsumer

        oid = req["object_id"]
        old = self._rings.pop(oid, None)
        if old is not None:
            self._detach_ring_state(old)
        view = self.plasma.get(oid)
        if view is None:
            return {"ok": False, "error": "ring object not in plasma"}
        try:
            consumer = RingConsumer(view)
        except Exception as e:
            try:
                view.release()
            except Exception:
                pass
            self.plasma.release(oid)
            return {"ok": False, "error": f"bad ring: {e}"}
        self._rings[oid] = {
            "oid": oid,
            "view": view,
            "consumer": consumer,
            "reply_addr": tuple(req["reply_addr"]),
            "job_id": req["job_id"],
            "backlog": deque(),
            "runners": 0,
        }
        if self._ring_event is None:
            self._ring_event = asyncio.Event()
        if self._ring_task is None:
            self._ring_task = asyncio.ensure_future(self._submit_ring_loop())
            self._bg.append(self._ring_task)
        self._ring_event.set()
        return {"ok": True}

    async def handle_SubmitRingDoorbell(self, req):
        if self._ring_event is not None:
            self._ring_event.set()
        return {"ok": True}

    async def handle_DetachSubmitRing(self, req):
        ring = self._rings.pop(req["object_id"], None)
        if ring is not None:
            self._detach_ring_state(ring)
        return {"ok": True}

    def _detach_ring_state(self, ring: dict):
        try:
            ring["view"].release()
        except Exception:
            pass
        self.plasma.release(ring["oid"])
        self.plasma.delete(ring["oid"])

    async def _submit_ring_loop(self):
        """Drain every attached ring per tick. The doorbell notify wakes
        the loop on empty→non-empty transitions; the short timeout is only
        a lost-doorbell safety net and the consumer-heartbeat cadence."""
        while True:
            try:
                await asyncio.wait_for(self._ring_event.wait(), 0.2)
            except asyncio.TimeoutError:
                pass
            except asyncio.CancelledError:
                return
            self._ring_event.clear()
            now = time.time()
            for oid, ring in list(self._rings.items()):
                try:
                    self._ring_tick(oid, ring, now)
                except Exception:
                    logger.exception("submit ring tick failed; detaching")
                    self._rings.pop(oid, None)
                    self._detach_ring_state(ring)

    def _ring_tick(self, oid: bytes, ring: dict, now: float):
        c = ring["consumer"]
        c.beat(now)  # producers treat a stale beat as a dead consumer
        drained = 0
        while drained < 4096:
            entries = c.drain(max_items=256)
            if not entries:
                break
            drained += len(entries)
            for raw in entries:
                try:
                    spec = msgpack.unpackb(raw, raw=False,
                                           strict_map_key=False)
                except Exception:
                    logger.exception("undecodable submit-ring entry")
                    continue
                ring["backlog"].append(spec)
        if not c.empty():
            self._ring_event.set()  # more arrived mid-drain: next tick now
        if ring["backlog"]:
            self._ring_pump(ring)
        elif ring["runners"] == 0 and c.closed():
            # clean producer detach: reclaim the ring object
            self._rings.pop(oid, None)
            self._detach_ring_state(ring)

    def _ring_pump(self, ring: dict):
        """One runner per grantable backlog task (mirroring the driver's
        one-lease-request-per-queued-task pumping, so blocking tasks keep
        real concurrency); each runner is HANDED its first spec here so a
        bounce can never strand a spawned runner without work. When local
        resources run out, the leftover backlog bounces back to the
        submitter if a peer has free capacity (the RPC path knows how to
        spill); otherwise it queues here until a release re-kicks us."""
        while ring["backlog"]:
            spec0 = ring["backlog"][0]
            resources = dict(spec0.get("resources") or {})
            grant = self._try_acquire(resources, {})
            if grant is None:
                if self.cluster_view and self._pick_spill_node(
                        resources, {}, require_available=True) is not None:
                    bounced = list(ring["backlog"])
                    ring["backlog"].clear()
                    self._ring_post_replies(ring, [
                        (s["task_id"], {"ring_bounce": True})
                        for s in bounced])
                break
            first = ring["backlog"].popleft()
            ring["runners"] += 1
            asyncio.ensure_future(self._ring_spawn(ring, grant, first))

    async def _ring_spawn(self, ring: dict, grant: dict, first: dict):
        try:
            handle = await self.worker_pool.pop_worker(ring["job_id"], None)
        except Exception:
            logger.exception("ring worker spawn failed")
            handle = None
        if handle is None:
            self.available.release(grant["demand"])
            self._resources_dirty = True
            ring["runners"] -= 1
            self._ring_post_replies(ring, [
                (first["task_id"],
                 {"status": "error", "worker_crashed": True,
                  "error": "ring worker startup failed"})])
            return
        self._lease_seq += 1
        lease_id = self._lease_seq.to_bytes(8, "little") + os.urandom(4)
        handle.lease_id = lease_id
        self.leases[lease_id] = {
            "worker_id": handle.worker_id,
            "grant": grant,
            "bundle": None,
            "chips": None,
            "t": time.time(),
        }
        _fr.record("lease.grant", lease_id, handle.worker_id.hex()[:12])
        await self._ring_runner(ring, handle, lease_id, first)

    async def _ring_runner(self, ring: dict, handle, lease_id: bytes,
                           first: dict):
        """Run the handed spec, then keep draining backlog batches on this
        lease until nothing is left; release the lease immediately after
        (holding it idle would starve every other lease waiter) while the
        warm worker returns to the pool for the next pump."""
        push_batch = RTPU_CONFIG.task_push_max_batch
        batch = [first]
        try:
            while batch:
                try:
                    client = await self.pool.get(*handle.addr)
                    r = await client.call("PushTasks", {"specs": batch},
                                          timeout=None)
                    replies = r["replies"]
                except Exception as e:
                    # worker died mid-batch: the submitter retries through
                    # its ordinary worker-crash path (lease cleanup rides
                    # _on_worker_death)
                    self._ring_post_replies(ring, [
                        (s["task_id"],
                         {"status": "error", "worker_crashed": True,
                          "error": f"ring worker died: "
                                   f"{type(e).__name__}: {e}"})
                        for s in batch])
                    return
                self._ring_post_replies(
                    ring, [(s["task_id"], rep)
                           for s, rep in zip(batch, replies)])
                batch = []
                while ring["backlog"] and len(batch) < push_batch:
                    batch.append(ring["backlog"].popleft())
            if lease_id in self.leases:
                self._release_lease(lease_id)
                if handle.alive:
                    self.worker_pool.push_idle(handle)
        finally:
            ring["runners"] -= 1

    def _ring_post_replies(self, ring: dict, replies):
        payload = {"replies": [[tid, rep] for tid, rep in replies]}

        async def _send():
            try:
                client = await self.pool.get(*ring["reply_addr"])
                await client.notify("SubmitRingReplies", payload)
            except Exception:
                _fr.record("rpc.error", b"", "SubmitRingReplies dropped")

        asyncio.ensure_future(_send())

    async def handle_GetNodeInfo(self, req):
        return {
            "node_id": self.node_id.binary(),
            "ip": self.host,
            "port": self.port,
            "plasma_name": self.plasma_name,
            "resources_total": self.total.to_dict(),
            "resources_available": self.available.to_dict(),
            "labels": self.labels,
            "num_workers": len(self.worker_pool.workers),
            "object_store": self.plasma.stats(),
        }

    # --------------------------------------------------------------- actors

    async def handle_LeaseWorkerForActor(self, req):
        """GCS asks us to supply a dedicated worker for an actor.

        When the request carries the creation `spec`, the actor initializes
        as part of the worker's boot (spec rides the fork-server spawn
        message; the creation result rides the child's RegisterWorker
        request) — collapsing the GCS's lease-then-create two-step, and its
        per-actor TCP connection to the new worker, into this one RPC."""
        grant = self._try_acquire(req["resources"], req.get("strategy", {}))
        if grant is None:
            return {"granted": False}
        try:
            env = await self._runtime_env_overrides(
                req.get("runtime_env"), req.get("job_id", b"")
            )
        except Exception as e:
            pool, _ = self._pool_for(req.get("strategy", {}))
            pool.release(grant["demand"])
            return {"granted": False, "error": f"runtime_env setup failed: {e}"}
        chips = self._allocate_chips(req["resources"].get("TPU", 0))
        if chips is not None:
            env.update(accelerators.visible_chip_env(chips))
        spec = req.get("spec")
        spawn_extra = {
            "node_id": self.node_id.hex(),
            "plasma_name": self.plasma_name,
        }
        sys_path = await self._job_sys_path(req["job_id"])
        if sys_path is not None:
            # None = transiently unknown: omit so the child runs its own
            # GetJob fallback instead of trusting an empty path list.
            spawn_extra["sys_path"] = sys_path
        if spec is not None:
            import base64

            actor_payload = {
                "spec_b64": base64.b64encode(
                    msgpack.packb(spec, use_bin_type=True)
                ).decode(),
            }
            fn_blob = await self._fn_blob(spec.get("fn_key"))
            if fn_blob is not None:
                actor_payload["fn_blob_b64"] = base64.b64encode(fn_blob).decode()
            spawn_extra["actor"] = actor_payload
        prestart = RTPU_CONFIG.prestart_workers_min_idle
        if prestart > 0 and not chips:
            # Warm-pool top-up: an idle hit below skips fork+boot entirely
            # (pop_worker drives CreateActor on the reused worker).
            asyncio.ensure_future(self.worker_pool.prestart(
                req["job_id"], env or None, target_idle=prestart))
        handle = await self.worker_pool.pop_worker(
            req["job_id"], env or None, spawn_extra
        )
        if handle is None:
            pool, _ = self._pool_for(req.get("strategy", {}))
            pool.release(grant["demand"])
            if chips:
                self._free_chips.extend(chips)
                self._free_chips.sort()
            return {"granted": False}
        created = False
        create_error = ""
        if spec is not None:
            if handle.actor_ready is not None:
                # spawn-time creation: result already reported by the child
                result = handle.actor_result or {}
                created = bool(result.get("ok"))
                create_error = result.get("error", "")
            else:
                # idle-worker reuse: drive CreateActor ourselves
                try:
                    client = await self.pool.get(*handle.addr)
                    result = await client.call(
                        "CreateActor",
                        {"spec": spec, "actor_id": req["actor_id"]},
                        timeout=RTPU_CONFIG.worker_startup_timeout_s,
                    )
                    created = bool(result.get("ok"))
                    create_error = result.get("error", "")
                except Exception as e:
                    created, create_error = False, ""
                    logger.warning("CreateActor on reused worker failed: %s", e)
            if not created:
                # creation failed: release everything; a deterministic
                # __init__ error propagates so the GCS marks the actor DEAD
                await self.worker_pool.kill_worker(handle)
                pool, _ = self._pool_for(req.get("strategy", {}))
                pool.release(grant["demand"])
                if chips:
                    self._free_chips.extend(chips)
                    self._free_chips.sort()
                if create_error:
                    return {"granted": False, "error": create_error}
                return {"granted": False}
        self._lease_seq += 1
        lease_id = self._lease_seq.to_bytes(8, "little") + os.urandom(4)
        handle.lease_id = lease_id
        handle.actor_id = req["actor_id"]
        self.leases[lease_id] = {
            "worker_id": handle.worker_id,
            "grant": grant,
            "bundle": grant["bundle"],
            "chips": chips,
            "t": time.time(),
        }
        _fr.record("lease.grant", lease_id, handle.worker_id.hex()[:12])
        self._actor_workers[handle.worker_id] = req["actor_id"]
        return {
            "granted": True,
            "created": created,
            "worker_addr": list(handle.addr),
            "worker_id": handle.worker_id,
            "lease_id": lease_id,
        }

    async def handle_LeaseWorkersForActors(self, req):
        """Batched actor lease: one RPC from the GCS creates N actors on
        this node; each item forks+boots concurrently raylet-side."""
        results = await asyncio.gather(
            *(self.handle_LeaseWorkerForActor(item) for item in req["items"]),
            return_exceptions=True,
        )
        out = []
        for r in results:
            if isinstance(r, BaseException):
                logger.warning("batched actor lease item failed: %r", r)
                out.append({"granted": False})
            else:
                out.append(r)
        return {"results": out}

    async def _job_sys_path(self, job_id: bytes) -> "Optional[list]":
        """driver_sys_path for a job, fetched from the GCS once and cached —
        saves every spawned worker its own GetJob round-trip."""
        cached = self._job_sys_path_cache.get(job_id)
        if cached is not None:
            return cached
        try:
            reply = await self.gcs.call("GetJob", {"job_id": job_id})
            paths = reply.get("job", {}).get("driver_sys_path", []) or []
        except Exception:
            return None  # transient: don't cache, let the child fall back
        self._job_sys_path_cache[job_id] = paths
        return paths

    async def _fn_blob(self, fn_key) -> "Optional[bytes]":
        """Actor-class blob from the GCS function table, cached per key so a
        burst of same-class actors ships the class in the spawn message
        instead of each child fetching it."""
        if not fn_key:
            return None
        blob = self._fn_blob_cache.get(fn_key)
        if blob is None:
            try:
                r = await self.gcs.call("KVGet", {"ns": "fn", "key": fn_key})
            except Exception:
                return None
            blob = r.get("value")
            if blob is None:
                return None
            if len(self._fn_blob_cache) > 128:
                self._fn_blob_cache.clear()
            self._fn_blob_cache[fn_key] = blob
        return blob

    async def _materialize_uri(self, uri: str) -> str:
        """Fetch + extract a kv:<hash> packaged directory (idempotent)."""
        base = self.session_dir or "."
        target = renv.materialized_path(uri, base)
        if os.path.isdir(target):
            return target
        digest = uri[len(renv.URI_PREFIX):]
        r = await self.gcs.call(
            "KVGet", {"ns": renv.KV_NAMESPACE, "key": digest.encode()}
        )
        blob = r.get("value")
        if blob is None:
            raise RuntimeError(f"runtime_env package {uri} missing from GCS KV")
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None, renv.extract_working_dir, uri, blob, base
        )

    async def _runtime_env_overrides(self, runtime_env,
                                     job_id: bytes = b"") -> Dict[str, str]:
        """Turn a spec's runtime_env into worker env overrides, extracting an
        uploaded working_dir / py_modules and building pip venvs on first
        use (reference: the per-node runtime-env agent,
        _private/runtime_env/agent/runtime_env_agent.py + pip.py)."""
        env: Dict[str, str] = {}
        if not runtime_env:
            return env
        for k, v in (runtime_env.get("env_vars") or {}).items():
            env[str(k)] = str(v)
        wd = runtime_env.get("working_dir")
        if wd:
            if renv.is_uploaded(wd):
                env[renv.WORKING_DIR_ENV] = await self._materialize_uri(wd)
            else:
                # Raw local path (same-machine clusters / tests).
                env[renv.WORKING_DIR_ENV] = str(wd)
        pypath: list = []
        for mod in runtime_env.get("py_modules") or []:
            if renv.is_uploaded(mod):
                pypath.append(await self._materialize_uri(mod))
            else:
                pypath.append(str(mod))
        pip = runtime_env.get("pip")
        if pip:
            pypath.append(await self._ensure_pip_env(pip, job_id))
        if pypath:
            env["RTPU_PYPATH_PREPEND"] = os.pathsep.join(pypath)
        conda = runtime_env.get("conda")
        if conda:
            prefix = await self._ensure_conda_env(conda, job_id)
            python = os.path.join(prefix, "bin", "python")
            if not os.path.exists(python):
                raise RuntimeError(
                    f"conda env {prefix!r} has no bin/python")
            # Workers for this env spawn via the env's own interpreter
            # (worker_pool direct-exec path), like the reference's
            # conda-activated worker command (runtime_env/conda.py:260).
            env["RTPU_SPAWN_PYTHON"] = python
            env["CONDA_PREFIX"] = prefix
            env["PATH"] = (os.path.join(prefix, "bin") + os.pathsep
                           + os.environ.get("PATH", ""))
        container = runtime_env.get("container")
        if container:
            import json as _json

            env["RTPU_SPAWN_PREFIX"] = _json.dumps(
                self._container_argv(container))
        return env

    def _container_argv(self, container: dict) -> list:
        """`docker run` prefix wrapping the worker command (reference:
        runtime_env/image_uri.py:96 — worker-in-container). host network so
        the worker's RPC server/ports work unchanged; /dev/shm and the
        session dir shared so plasma and logs keep functioning. The engine
        binary comes from RTPU_CONTAINER_EXE (tests install a fake docker
        on PATH, like the reference's mocked container runs)."""
        image = container.get("image")
        if not image:
            raise RuntimeError('runtime_env["container"] needs an "image"')
        exe = os.environ.get("RTPU_CONTAINER_EXE", "docker")
        argv = [exe, "run", "--rm", "--network=host",
                "-v", "/dev/shm:/dev/shm"]
        session = os.path.abspath(self.session_dir or ".")
        argv += ["-v", f"{session}:{session}"]
        for opt in container.get("run_options", []) or []:
            argv.append(str(opt))
        argv.append(str(image))
        return argv

    async def _ensure_conda_env(self, conda, job_id: bytes) -> str:
        """Resolve or build a conda env; returns its prefix directory.

        - str that is a directory: used as a prefix as-is.
        - other str: named env, resolved via `conda env list --json`.
        - dict: an environment.yml-shaped spec, built once per spec hash
          with `conda env create -p` and cached/evicted exactly like the
          pip target dirs (reference: runtime_env/conda.py:260
          get_or_create_conda_env; same job-refcounted eviction).
        """
        import hashlib
        import json as _json
        import subprocess

        conda_exe = os.environ.get("RTPU_CONDA_EXE", "conda")
        if isinstance(conda, str):
            if os.path.isdir(conda):
                return conda
            cache = getattr(self, "_conda_name_cache", None)
            if cache is None:
                cache = self._conda_name_cache = {}
            if conda in cache:
                return cache[conda]
            loop = asyncio.get_running_loop()

            def lookup():
                out = subprocess.run(
                    [conda_exe, "env", "list", "--json"],
                    capture_output=True, text=True, timeout=60)
                if out.returncode != 0:
                    raise RuntimeError(
                        f"conda env list failed: {out.stderr.strip()}")
                for prefix in _json.loads(out.stdout).get("envs", []):
                    if os.path.basename(prefix) == conda:
                        return prefix
                raise RuntimeError(f"no conda env named {conda!r}")

            prefix = await loop.run_in_executor(None, lookup)
            cache[conda] = prefix  # one conda-CLI shellout per name, ever
            return prefix
        spec = _json.dumps(conda, sort_keys=True)
        h = "conda-" + hashlib.sha1(spec.encode()).hexdigest()[:16]
        base = os.path.join(self.session_dir or ".", "runtime_envs", "venvs")
        env_dir = os.path.join(base, h)
        marker = os.path.join(env_dir, ".rtpu_ready")
        if job_id:
            self._venv_jobs.setdefault(h, set()).add(job_id)
        lock = self._venv_locks.setdefault(h, asyncio.Lock())
        async with lock:
            if not os.path.exists(marker):
                loop = asyncio.get_running_loop()

                def build():
                    import shutil
                    import tempfile

                    shutil.rmtree(env_dir, ignore_errors=True)
                    os.makedirs(base, exist_ok=True)
                    with tempfile.NamedTemporaryFile(
                            "w", suffix=".yml", delete=False) as f:
                        import yaml as _yaml

                        _yaml.safe_dump(conda, f)
                        yml = f.name
                    try:
                        out = subprocess.run(
                            [conda_exe, "env", "create", "--yes",
                             "-p", env_dir, "-f", yml],
                            capture_output=True, text=True, timeout=1800)
                        if out.returncode != 0:
                            raise RuntimeError(
                                "conda env create failed:\n"
                                + out.stderr[-2000:])
                    finally:
                        os.unlink(yml)
                    with open(marker, "w") as f:
                        f.write("ok")

                await loop.run_in_executor(None, build)
        return env_dir

    async def _ensure_pip_env(self, pip: dict, job_id: bytes) -> str:
        """Per-spec-hash package dir built by `pip install --target`, shared
        by every worker that asks for the same pip spec; reference-counted
        per job and evicted when the last job using it finishes (reference:
        runtime_env/agent/runtime_env_agent.py:162 + pip.py).

        --target instead of a nested venv: the base interpreter is itself a
        venv, and `python -m venv` from inside one resolves "system site
        packages" to the ORIGINAL interpreter, hiding the baked-in stack.
        A plain target dir prepended to sys.path adds packages on top of
        the full base env — exactly the per-job-deps semantics wanted."""
        import hashlib
        import json as _json
        import shutil
        import subprocess
        import sys as _sys

        spec = _json.dumps(pip, sort_keys=True)
        h = hashlib.sha1(spec.encode()).hexdigest()[:16]
        base = os.path.join(self.session_dir or ".", "runtime_envs", "venvs")
        env_dir = os.path.join(base, h)
        marker = os.path.join(env_dir, ".rtpu_ready")
        if job_id:
            self._venv_jobs.setdefault(h, set()).add(job_id)
        lock = self._venv_locks.setdefault(h, asyncio.Lock())
        async with lock:
            if not os.path.exists(marker):
                loop = asyncio.get_running_loop()

                def build():
                    shutil.rmtree(env_dir, ignore_errors=True)  # half-built
                    os.makedirs(base, exist_ok=True)
                    cmd = [
                        _sys.executable, "-m", "pip", "install",
                        "--no-input", "--target", env_dir,
                        *pip.get("pip_install_options", []),
                        *pip["packages"],
                    ]
                    r = subprocess.run(cmd, capture_output=True, text=True)
                    if r.returncode != 0:
                        raise RuntimeError(
                            f"pip install failed:\n{r.stdout[-2000:]}\n"
                            f"{r.stderr[-2000:]}"
                        )

                await loop.run_in_executor(None, build)
                with open(marker, "w") as f:
                    f.write(spec)
        return env_dir

    async def handle_KillWorker(self, req):
        handle = self.worker_pool.workers.get(req["worker_id"])
        if handle is not None:
            if req.get("reason"):
                self._kill_reasons[req["worker_id"]] = req["reason"]
            # death is reported once, by the fork server's reap (or the
            # liveness poll) — not here, to avoid double ReportWorkerDeath
            await self.worker_pool.kill_worker(handle)
        return {"ok": True}

    async def handle_JobFinished(self, req):
        # submit rings of the finished job's drivers/workers are garbage now
        for oid, ring in list(self._rings.items()):
            if ring["job_id"] == req["job_id"]:
                self._rings.pop(oid, None)
                self._detach_ring_state(ring)
        self.worker_pool.kill_job_workers(req["job_id"])
        # evict pip venvs no job still references (reference: runtime_env
        # agent deletes per-job URIs on job exit)
        import shutil

        job_id = req["job_id"]
        loop = asyncio.get_running_loop()
        for h, jobs in list(self._venv_jobs.items()):
            jobs.discard(job_id)
            if not jobs:
                self._venv_jobs.pop(h, None)
                self._venv_locks.pop(h, None)
                path = os.path.join(
                    self.session_dir or ".", "runtime_envs", "venvs", h
                )
                # Atomic rename FIRST: the ready marker vanishes with the
                # dir, so a new job with the same spec rebuilds instead of
                # adopting a tree that is mid-deletion; then rmtree off the
                # loop (heartbeats/leases must not stall on fs work).
                trash = f"{path}.evict.{os.getpid()}"
                try:
                    os.rename(path, trash)
                except OSError:
                    continue
                loop.run_in_executor(None, shutil.rmtree, trash, True)
                logger.info("evicting pip venv %s (last job finished)", h)

    # ------------------------------------------------------ placement groups

    async def handle_PrepareBundle(self, req):
        key = (req["pg_id"], req["bundle_index"])
        if key in self.bundles:
            return {"ok": True}
        demand = ResourceSet(req["resources"])
        if not self.available.acquire(demand):
            return {"ok": False}
        self._resources_dirty = True
        self.bundles[key] = {
            "reserved": demand,
            "available": demand.copy(),
            "committed": False,
        }
        return {"ok": True}

    async def handle_CommitBundle(self, req):
        key = (req["pg_id"], req["bundle_index"])
        bundle = self.bundles.get(key)
        if bundle is None:
            return {"ok": False}
        bundle["committed"] = True
        return {"ok": True}

    async def handle_PrepareBundles(self, req):
        """Batched 2PC prepare: every bundle this node hosts in ONE RPC
        (a 2-bundle PG on one node was 2 prepare + 2 commit round-trips).
        All-or-nothing per node: partial acquisitions roll back here.
        With `commit: true` (single-participant groups) the 2PC degenerates
        to one phase — sole-node atomicity needs no separate commit."""
        acquired = []
        for item in req["items"]:
            r = await self.handle_PrepareBundle(item)
            if not r.get("ok"):
                for done in acquired:
                    await self._return_bundle(done)
                return {"ok": False}
            acquired.append(item)
        if req.get("commit"):
            for item in req["items"]:
                await self.handle_CommitBundle(item)
        return {"ok": True}

    async def handle_CommitBundles(self, req):
        ok = True
        for item in req["items"]:
            r = await self.handle_CommitBundle(item)
            ok = ok and bool(r.get("ok"))
        return {"ok": ok}

    async def handle_CancelBundle(self, req):
        await self._return_bundle(req)

    async def handle_ReturnBundle(self, req):
        await self._return_bundle(req)

    async def _return_bundle(self, req):
        key = (req["pg_id"], req["bundle_index"])
        bundle = self.bundles.pop(key, None)
        if bundle is not None:
            self.available.release(bundle["reserved"])
            self._resources_dirty = True
            # full wake: waiters bound to this PG must observe its removal
            self._kick_waiters(wake_all=True)

    # ----------------------------------------------------- spilling / OOM

    @staticmethod
    def _write_spill_file(path: str, data):
        """data is any bytes-like — the plasma view itself is passed so the
        spill write streams shm -> page cache with no heap copy."""
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    async def _spill_bytes(self, needed: int) -> int:
        """Spill pinned primary copies to disk until ``needed`` bytes of
        plasma are reclaimable. Oldest pins first (insertion order ~= LRU).

        Reference: LocalObjectManager::SpillObjectsOfSize
        (src/ray/raylet/local_object_manager.h:41). The primary copy moves
        to <session>/spilled_<node>/<oid>; remote pulls are served straight
        from the file and local access restores it into plasma on demand.
        """
        async with self._spill_lock:
            victims: List[Tuple[bytes, memoryview]] = []
            planned = 0
            for oid, view in list(self._pinned.items()):
                if planned >= needed:
                    break
                victims.append((oid, view))
                planned += view.nbytes
            if not victims:
                return 0
            os.makedirs(self._spill_dir, exist_ok=True)
            loop = asyncio.get_running_loop()
            freed = 0
            for oid, view in victims:
                if oid not in self._pinned:
                    # Freed (handle_FreeObjects) while an earlier victim was
                    # being written: its view is released — don't touch it.
                    continue
                nbytes = view.nbytes  # capture before any await
                rec = self._spilled.get(oid)
                if rec is None:
                    path = os.path.join(self._spill_dir, oid.hex())
                    try:
                        # the pin (self._pinned) holds the view alive for
                        # the duration of the executor write — no bytes()
                        await loop.run_in_executor(
                            None, self._write_spill_file, path, view
                        )
                    except Exception:
                        logger.exception("spill of %s failed", oid.hex()[:12])
                        continue
                    if oid not in self._pinned:
                        # Freed during the write: don't resurrect the entry.
                        try:
                            os.remove(path)
                        except OSError:
                            pass
                        continue
                    self._spilled[oid] = (path, nbytes)
                self._pinned.pop(oid, None)
                try:
                    view.release()
                except Exception:
                    pass
                self.plasma.release(oid)
                # delete may fail if a reader still holds it; its memory
                # frees when that reader releases — still progress.
                self.plasma.delete(oid)
                freed += nbytes
                # per-object (oid, bytes) so the timeline can render each
                # spill as an instant on this node's lane
                _fr.record("obj.spill", oid, nbytes)
            if freed:
                logger.info(
                    "spilled %d objects / %d bytes to %s",
                    len(victims), freed, self._spill_dir,
                )
            return freed

    async def _restore_spilled(self, oid: bytes) -> bool:
        """Bring a spilled object back into local plasma (re-pinned)."""
        rec = self._spilled.get(oid)
        if rec is None:
            return False
        path, size = rec
        dest = None
        for attempt in range(6):
            try:
                dest = await self._plasma_create_with_room(oid, size)
                break
            except FileExistsError:
                if self.plasma.contains(oid):
                    return True  # sealed — someone beat us to it
                # Unsealed leftover of a crashed restore: reclaim and retry.
                self.plasma.abort(oid)
                continue
            except PlasmaOOM:
                # Transient: spill victims whose memory is still held by an
                # in-flight reader free up once that reader releases.
                await asyncio.sleep(0.1 * (attempt + 1))
        if dest is None:
            logger.warning("restore of %s: no room after retries", oid.hex()[:12])
            return False
        loop = asyncio.get_running_loop()

        def _read_into():
            # page cache -> plasma shm directly; no intermediate bytes
            with open(path, "rb") as f:
                if f.readinto(dest) != size:
                    raise RuntimeError(f"spill file {path} truncated")

        try:
            await loop.run_in_executor(None, _read_into)
            dest.release()
            self.plasma.seal(oid)
        except Exception:
            logger.exception("restore of %s failed", oid.hex()[:12])
            try:
                dest.release()
            except Exception:
                pass
            self.plasma.abort(oid)
            return False
        # Primary copy again: re-pin. The spill file stays so a future
        # re-spill is a free drop; FreeObjects removes it with the object.
        _fr.record("obj.restore", oid, size)
        view = self.plasma.get(oid)
        if view is not None:
            self._pinned[oid] = view
        return True

    async def _plasma_create_with_room(self, oid: bytes, size: int):
        """plasma create that makes room: evict unpinned, then spill."""
        try:
            return self.plasma.create(oid, size)
        except PlasmaOOM:
            self.plasma.evict(size)
        try:
            return self.plasma.create(oid, size)
        except PlasmaOOM:
            await self._spill_bytes(size)
        return self.plasma.create(oid, size)

    async def handle_SpillObjects(self, req):
        """A worker hit plasma OOM: free up ``bytes`` by spilling primaries."""
        freed = await self._spill_bytes(req["bytes"])
        return {"freed": freed}

    async def _spill_loop(self):
        """Watermark spilling: keep plasma below the high threshold so task
        returns never stall on a store packed with pinned primaries."""
        period = RTPU_CONFIG.object_spilling_check_period_ms / 1000.0
        high = RTPU_CONFIG.object_spilling_threshold
        while True:
            await asyncio.sleep(period)
            try:
                # reclaim unsealed inbound-push buffers whose pusher died
                for oid, rec in list(self._recv.items()):
                    if time.time() - rec["t"] > 120:
                        logger.warning(
                            "aborting stale inbound push %s", oid.hex()[:12]
                        )
                        self._abort_recv(oid)
                if not self._pinned:
                    continue
                s = self.plasma.stats()
                cap = s["capacity_bytes"]
                if cap and s["used_bytes"] > high * cap:
                    target = max(0.0, (high - 0.1)) * cap
                    await self._spill_bytes(int(s["used_bytes"] - target))
            except Exception:
                logger.exception("spill loop error")

    # -- OOM monitor (reference: src/ray/common/memory_monitor.h:52 +
    #    raylet/worker_killing_policy_group_by_owner.h) -------------------

    @staticmethod
    def _memory_usage_fraction() -> Optional[float]:
        try:
            info = {}
            with open("/proc/meminfo") as f:
                for line in f:
                    parts = line.split()
                    if parts[0] in ("MemTotal:", "MemAvailable:"):
                        info[parts[0]] = int(parts[1])
            total = info.get("MemTotal:")
            avail = info.get("MemAvailable:")
            if not total or avail is None:
                return None
            return 1.0 - avail / total
        except Exception:
            return None

    def _pick_oom_victim(self):
        """Kill-priority: leased task workers (their tasks retry) before
        actor workers (restart costs state), newest first within a class."""
        candidates = [
            h
            for h in self.worker_pool.workers.values()
            if h.alive and h.leased and h.pid
        ]
        if not candidates:
            return None
        candidates.sort(
            key=lambda h: (
                h.worker_id in self._actor_workers,  # tasks first
                -h.startup_token,  # newest first
            )
        )
        return candidates[0]

    async def _memory_monitor_loop(self):
        period = RTPU_CONFIG.memory_monitor_refresh_ms / 1000.0
        threshold = RTPU_CONFIG.memory_usage_threshold
        if period <= 0:
            return
        while True:
            await asyncio.sleep(period)
            try:
                frac = self._memory_usage_fraction()
                if frac is None or frac < threshold:
                    continue
                victim = self._pick_oom_victim()
                if victim is None:
                    continue
                reason = (
                    f"worker killed by the memory monitor: node memory usage "
                    f"{frac:.2f} exceeded threshold {threshold:.2f} (OOM "
                    f"prevention; task will be retried if retriable)"
                )
                logger.warning("%s (pid=%d)", reason, victim.pid)
                _fr.record("worker.oom_kill", victim.worker_id,
                           f"pid {victim.pid} frac {frac:.2f}")
                self._kill_reasons[victim.worker_id] = reason
                # OOM forensics: grab the victim's final memory report
                # while it still breathes — _on_worker_death attaches it
                # (or the on-disk snapshot fallback) to the death report.
                try:
                    client = await self.pool.get(*victim.addr)
                    r = await client.call(
                        "GetMemoryReport", {"limit": 10}, timeout=2)
                    if r.get("report"):
                        self._death_memory[victim.worker_id] = r["report"]
                except Exception:
                    pass
                await self.worker_pool.kill_worker(victim)
            except Exception:
                logger.exception("memory monitor error")

    # ------------------------------------- memory plane: ledger + leaks

    async def _leak_sweep_loop(self):
        """Leak detector: a pinned/spilled primary whose owner's ledger
        holds no live reference — in two consecutive sweeps — is leaked
        (one sweep alone can race an in-flight free/borrow handoff). Fires
        one ``object_leak`` incident per batch of newly confirmed leaks
        through the PR 3 incident path, cooldown-limited, each object
        reported at most once."""
        period = RTPU_CONFIG.memory_leak_sweep_period_s
        while True:
            await asyncio.sleep(period)
            try:
                await self._leak_sweep_once()
            except Exception:
                logger.exception("leak sweep error")

    async def _leak_sweep_once(self):
        now = time.time()
        min_age = RTPU_CONFIG.memory_leak_min_age_s
        # 1. group this node's primaries by owner address
        by_owner: Dict[tuple, List[bytes]] = {}
        for oid in set(self._pinned) | set(self._spilled):
            meta = self._pin_meta.get(oid)
            if not meta or not meta.get("owner_addr"):
                continue  # no attribution: nothing to cross-check against
            if now - meta.get("t", now) < min_age:
                continue  # too young — likely still being wired up
            by_owner.setdefault(tuple(meta["owner_addr"]), []).append(oid)
        # 2. ask each owner which ids its ledger still holds
        unowned: List[bytes] = []
        for owner, ids in by_owner.items():
            try:
                client = await self.pool.get(owner[0], owner[1])
                reply = await client.call("CheckRefs", {"ids": ids},
                                          timeout=10)
                owned = reply.get("owned", [])
                unowned.extend(
                    oid for oid, ok in zip(ids, owned) if not ok)
            except Exception:
                # unreachable owner (died without the raylet learning, or
                # network partition): every primary it pinned is suspect
                unowned.extend(ids)
        # 3. two-sweep cross-check: confirmed = unowned now AND last sweep
        confirmed = [oid for oid in unowned if oid in self._leak_candidates]
        self._leak_candidates = {
            oid: self._leak_candidates.get(oid, now) for oid in unowned}
        self._leaks = {
            oid: self._leak_record(oid) for oid in confirmed}
        # 4. publish newly confirmed leaks (once per object, cooldown gap)
        new = [oid for oid in confirmed if oid not in self._leak_fired]
        if not new:
            return
        cooldown = RTPU_CONFIG.memory_leak_cooldown_s
        if now - self._last_leak_incident < cooldown:
            return  # they stay in _leaks/_leak_candidates; next window
        self._last_leak_incident = now
        self._leak_fired.update(new)
        records = [self._leaks[oid] for oid in new]
        for rec in records:
            _fr.record("obj.leak", bytes.fromhex(rec["object_id"]),
                       rec["size"])
        await self._fire_leak_incident(records)

    def _leak_record(self, oid: bytes) -> dict:
        meta = self._pin_meta.get(oid, {})
        view = self._pinned.get(oid)
        size = view.nbytes if view is not None else (
            self._spilled.get(oid, (None, meta.get("size", 0)))[1])

        def _hex(v):
            return v.hex() if isinstance(v, (bytes, bytearray)) else (v or "")

        return {
            "object_id": oid.hex(),
            "size": size,
            "node_id": self.node_id.hex(),
            "job_id": _hex(meta.get("job_id")),
            "actor_id": _hex(meta.get("actor_id")),
            "task_id": _hex(meta.get("task_id")),
            "callsite": meta.get("callsite", ""),
            "owner_addr": list(meta.get("owner_addr") or []),
            "spilled": oid in self._spilled and oid not in self._pinned,
            "first_unowned": self._leak_candidates.get(oid, 0.0),
        }

    async def _fire_leak_incident(self, records: List[dict]):
        from ray_tpu._private import watchdog as _wd

        total = sum(r["size"] for r in records)
        top = max(records, key=lambda r: r["size"])
        where = f" @ {top['callsite']}" if top.get("callsite") else ""
        incident = _wd.build_incident(
            "object_leak", "raylet",
            f"{len(records)} leaked object(s) / {total} bytes in plasma on "
            f"node {self.node_id.hex()[:12]}: no live reference in any "
            f"owner's ledger across two sweeps — largest "
            f"{top['object_id'][:12]} ({top['size']} bytes, job "
            f"{top['job_id'][:12] or '?'}"
            + (f", actor {top['actor_id'][:12]}" if top["actor_id"] else "")
            + f"){where}",
            node_id=self.node_id.hex(),
        )
        incident["leaks"] = records
        try:
            await self.gcs.call(
                "ReportIncident", {"incident": incident}, timeout=10)
        except Exception:
            pass

    async def handle_GetMemoryReport(self, req):
        """Memory plane fan-in: this node's plasma + spill + pin tables
        joined with every live worker's ownership ledger and per-role RSS
        in one reply (util.state aggregates the cluster view).
        ``sweep=True`` forces a leak sweep first (`ray-tpu memory --leaks`
        wants current truth, not the last cadence's)."""
        from ray_tpu._private import memory_report as _mr

        if req.get("sweep"):
            try:
                await self._leak_sweep_once()
            except Exception:
                logger.exception("forced leak sweep failed")
        limit = req.get("limit") or RTPU_CONFIG.memory_report_top_n
        try:
            plasma_stats = self.plasma.stats()
        except Exception:
            plasma_stats = {}
        pinned_bytes = sum(v.nbytes for v in self._pinned.values())
        spilled_bytes = sum(size for _, size in self._spilled.values())

        def _meta_out(oid):
            meta = self._pin_meta.get(oid, {})
            return {
                "job_id": meta.get("job_id") or b"",
                "actor_id": meta.get("actor_id") or b"",
                "task_id": meta.get("task_id") or b"",
                "callsite": meta.get("callsite", ""),
                "owner_addr": list(meta.get("owner_addr") or []),
            }

        objects = []
        seen = set()
        for oid in self.plasma.list_object_ids():
            b = oid.binary()
            seen.add(b)
            size = None
            view = self.plasma.get(b)
            if view is not None:
                size = view.nbytes
                view.release()
                self.plasma.release(b)
            objects.append({
                "object_id": b, "size": size,
                "pinned": b in self._pinned, "spilled": b in self._spilled,
                **_meta_out(b),
            })
        for oid, (_path, size) in self._spilled.items():
            if oid not in seen:
                objects.append({
                    "object_id": oid, "size": size,
                    "pinned": False, "spilled": True, **_meta_out(oid),
                })
        out = {
            "node_id": self.node_id.binary(),
            "time": time.time(),
            "plasma": plasma_stats,
            "pinned_count": len(self._pinned),
            "pinned_bytes": pinned_bytes,
            "spilled_count": len(self._spilled),
            "spilled_bytes": spilled_bytes,
            "objects": objects,
            "leaks": list(self._leaks.values()),
            "leak_candidates": len(self._leak_candidates),
            "raylet_rss": _mr.process_rss(),
            "agent_rss": _mr.process_rss(
                getattr(getattr(self, "_agent_proc", None), "pid", None)),
            "workers": [],
        }
        if req.get("include_workers", True):
            async def _one(h):
                try:
                    client = await self.pool.get(*h.addr)
                    r = await client.call(
                        "GetMemoryReport", {"limit": limit}, timeout=10)
                    return r.get("report")
                except Exception:
                    return None

            live = [h for h in self.worker_pool.workers.values()
                    if h.alive and h.addr[1]]
            replies = await asyncio.gather(*(_one(h) for h in live))
            out["workers"] = [r for r in replies if r]
        return out

    # ------------------------------------------------------------ log monitor

    async def _log_monitor_loop(self):
        """Tail this node's worker logs and publish new lines over GCS
        pubsub to the owning job's driver (reference:
        python/ray/_private/log_monitor.py:103 — per-node monitor feeding
        the driver's log stream)."""
        tracked: Dict[str, dict] = {}  # path -> {off,job,pid,err,last_growth}

        async def _publish(t, lines) -> bool:
            try:
                await self.gcs.call(
                    "Publish",
                    {
                        "channel": f"logs:{t['job'].hex()}",
                        "message": {
                            "pid": t["pid"],
                            "ip": self.host,
                            "is_err": t["err"],
                            "lines": lines,
                        },
                    },
                    timeout=10,
                )
                return True
            except Exception:
                return False

        while True:
            # Adaptive cadence: each pass stats every tracked file, so at
            # many-worker scale a fixed 250 ms tick becomes thousands of
            # stat()s per second of pure overhead.
            await asyncio.sleep(0.25 if len(tracked) < 400 else 1.0)
            try:
                now = time.time()
                live_paths = set()
                for h in list(self.worker_pool.workers.values()):
                    if not h.log_prefix:
                        continue
                    for suffix, is_err in ((".out", False), (".err", True)):
                        path = h.log_prefix + suffix
                        live_paths.add(path)
                        tracked.setdefault(
                            path,
                            {"off": 0, "job": h.job_id, "pid": h.pid,
                             "err": is_err, "last_growth": now},
                        )
                for path, t in list(tracked.items()):
                    try:
                        size = os.path.getsize(path)
                    except OSError:
                        size = 0
                    if size <= t["off"]:
                        # Drop only files of DEPARTED workers once drained —
                        # a live worker's entry must persist or its path
                        # would be re-registered at off=0 and replayed.
                        if path not in live_paths and now - t["last_growth"] > 10.0:
                            tracked.pop(path, None)
                        continue
                    with open(path, "rb") as f:
                        f.seek(t["off"])
                        data = f.read(min(size - t["off"], 1 << 20))
                    # Hold back a trailing partial line (mid-print or the
                    # 1 MiB cap landing mid-line) until its newline arrives;
                    # flush it anyway once the worker is gone.
                    cut = data.rfind(b"\n")
                    if cut < 0:
                        if path in live_paths:
                            continue
                        cut = len(data) - 1
                    data = data[: cut + 1]
                    lines = [
                        ln.decode("utf-8", "replace")
                        for ln in data.splitlines()
                    ]
                    # Advance the offset only after a successful publish so
                    # lines produced around a GCS outage are retried, not
                    # silently dropped.
                    if not lines or await _publish(t, lines):
                        t["off"] += len(data)
                        t["last_growth"] = now
            except Exception:
                logger.exception("log monitor error")

    # --------------------------------------------------------- object plane

    async def handle_PinObject(self, req):
        """Hold the primary copy of an owned object against LRU eviction."""
        oid = req["object_id"]
        if oid not in self._pinned:
            view = self.plasma.get(oid)
            if view is not None:
                self._pinned[oid] = view
        # Ownership attribution for the memory plane: who to ask (leak
        # sweep) and who to blame (reports) for this primary.
        meta = dict(req.get("meta") or {})
        meta["owner_addr"] = req.get("owner_addr")
        meta.setdefault("t", time.time())
        self._pin_meta[oid] = meta

    async def handle_FreeObjects(self, req):
        for oid in req["ids"]:
            view = self._pinned.pop(oid, None)
            if view is not None:
                try:
                    view.release()
                except Exception:
                    pass
                self.plasma.release(oid)
            self.plasma.delete(oid)
            spilled = self._spilled.pop(oid, None)
            if spilled is not None:
                try:
                    os.remove(spilled[0])
                except OSError:
                    pass
            # freed is not leaked: drop the object's memory-plane state
            self._pin_meta.pop(oid, None)
            self._leak_candidates.pop(oid, None)
            self._leaks.pop(oid, None)
            self._leak_fired.discard(oid)

    async def handle_FetchObjectInfo(self, req):
        oid = req["object_id"]
        view = self.plasma.get(oid)
        if view is None:
            # Spilled here: remote pulls are served straight from disk
            # (reference: spilled-object chunk reader, object_manager/
            # spilled_object_reader.h) — no plasma round-trip.
            spilled = self._spilled.get(oid)
            if spilled is not None:
                return {"found": True, "size": spilled[1]}
            return {"found": False}
        size = view.nbytes
        view.release()
        self.plasma.release(oid)
        return {"found": True, "size": size}

    async def handle_FetchChunk(self, req):
        oid = req["object_id"]
        off, size = req["offset"], req["size"]
        view = self.plasma.get(oid)
        if view is None:
            spilled = self._spilled.get(oid)
            if spilled is not None:
                loop = asyncio.get_running_loop()

                def _read():
                    with open(spilled[0], "rb") as f:
                        f.seek(off)
                        return f.read(size)

                try:
                    data = await loop.run_in_executor(None, _read)
                except OSError:
                    return {"found": False}
                # raw after the header — no msgpack encode of the bulk
                return OobPayload({"found": True}, data)
            return {"found": False}

        def _release(v=view, o=oid):
            try:
                v.release()
            except Exception:
                pass
            self.plasma.release(o)

        # the plasma view slice itself goes on the wire (no bytes() copy);
        # the pin drops once the frame is handed to the transport
        return OobPayload({"found": True}, view[off:off + size], release=_release)

    # ------------------------------------------------- push path (outbound)

    async def handle_PushObject(self, req):
        """Push a locally-held object to a target raylet (reference:
        ObjectManager::Push, object_manager/object_manager.cc:339 +
        push_manager.h). The owner (or the broadcast helper) hints the
        destination; chunks stream holder->target so the target never has
        to discover a source."""
        oid = req["object_id"]
        target = req["target"]  # node_id bytes
        owner_addr = req.get("owner_addr")
        info = self.cluster_view.get(target)
        if info is None:
            return {"ok": False, "error": "unknown target node"}
        view = self.plasma.get(oid)
        size = None
        if view is None:
            spilled = self._spilled.get(oid)
            if spilled is None:
                return {"ok": False, "error": "object not local"}
            size = spilled[1]
        else:
            size = view.nbytes
        try:
            peer = await self.pool.get(info["ip"], info["raylet_port"])
            begin = await peer.call(
                "ReceiveBegin",
                {"object_id": oid, "size": size,
                 "owner_addr": list(owner_addr) if owner_addr else None},
                timeout=30,
            )
            if begin.get("already"):
                return {"ok": True, "already": True}
            if not begin.get("ok"):
                return {"ok": False, "error": begin.get("error", "begin failed")}
            chunk = RTPU_CONFIG.object_manager_chunk_size
            # chunks are offset-addressed, so pipeline them (windowed
            # gather) instead of paying one RTT per 4 MiB — same treatment
            # the pull path's striped fetch got
            sem = asyncio.Semaphore(8)
            loop = asyncio.get_running_loop()

            async def send_one(offset):
                n = min(chunk, size - offset)
                async with sem:
                    if view is not None:
                        # zero-copy: the plasma view slice rides raw after
                        # the out-of-band frame header — never bytes()'d,
                        # never msgpack-encoded
                        r = await peer.call(
                            "ReceiveChunk",
                            {"object_id": oid, "offset": offset},
                            timeout=60,
                            oob=view[offset:offset + n],
                        )
                    else:
                        spilled = self._spilled.get(oid)
                        if spilled is None:
                            # restored or freed mid-transfer: the spill
                            # file is gone — fail THIS push cleanly; the
                            # outer handler turns it into {"ok": False}
                            raise RuntimeError(
                                f"source for {oid.hex()[:12]} vanished "
                                "mid-push (spilled copy restored or freed)"
                            )
                        # one copy (page cache -> buf), then raw send; the
                        # window bounds memory to 8 chunks
                        buf = bytearray(n)

                        def _read(path=spilled[0], off=offset, b=buf):
                            with open(path, "rb") as f:
                                f.seek(off)
                                if f.readinto(b) != len(b):
                                    raise RuntimeError(
                                        "spill file truncated mid-push"
                                    )

                        await loop.run_in_executor(None, _read)
                        r = await peer.call(
                            "ReceiveChunk",
                            {"object_id": oid, "offset": offset},
                            timeout=60,
                            oob=buf,
                        )
                return bool(r.get("ok"))

            oks = await asyncio.gather(
                *(send_one(off) for off in range(0, size, chunk))
            )
            if not all(oks):
                _fr.record("obj.push", oid, "target aborted")
                return {"ok": False, "error": "target aborted"}
            r = await peer.call("ReceiveEnd", {"object_id": oid}, timeout=30)
            _fr.record("obj.push", oid, "ok" if r.get("ok") else "end failed")
            return {"ok": bool(r.get("ok"))}
        except Exception as e:
            _fr.record("rpc.error", oid, f"PushObject: {type(e).__name__}")
            return {"ok": False, "error": str(e)}
        finally:
            if view is not None:
                view.release()
                self.plasma.release(oid)

    # ------------------------------------------------- push path (inbound)

    def _abort_recv(self, oid: bytes):
        rec = self._recv.pop(oid, None)
        if rec is None:
            return
        with self._recv_lock:
            if rec.get("landing", 0) > 0:
                # a chunk is streaming into the buffer right now (oob sink,
                # possibly on a reactor shard thread) — defer the plasma
                # abort until the last lander finishes so the store can't
                # hand this memory to a new object mid-write
                rec["abort_pending"] = True
                return
        self._finish_abort_recv(oid, rec)

    def _finish_abort_recv(self, oid: bytes, rec: dict):
        try:
            rec["view"].release()
        except Exception:
            pass
        try:
            self.plasma.abort(oid)
        except Exception:
            pass

    def _receive_chunk_sink(self, payload, nbytes: int):
        """RpcServer oob sink: hand back the pre-created plasma buffer slice
        at the chunk's offset so the raw payload streams from the socket
        straight into shared memory — no intermediate chunk buffer."""
        rec = self._recv.get(payload.get("object_id"))
        if rec is None:
            return None
        off = payload.get("offset")
        if not isinstance(off, int) or off < 0 or off + nbytes > rec["size"]:
            return None
        with self._recv_lock:
            rec["landing"] = rec.get("landing", 0) + 1
        rec["t"] = time.time()

        def done(ok, oid=payload["object_id"], rec=rec):
            with self._recv_lock:
                rec["landing"] -= 1
                rec["t"] = time.time()
                finish = rec.get("abort_pending") and rec["landing"] <= 0
            if finish:
                self._finish_abort_recv(oid, rec)

        return rec["view"][off:off + nbytes], done

    async def handle_ReceiveBegin(self, req):
        oid = req["object_id"]
        if self.plasma.contains(oid):
            return {"ok": True, "already": True}
        if oid in self._pulls:
            # a pull is mid-transfer for the same object; "already" would be
            # a lie (the copy isn't here yet) — pushers retry or move on
            return {"ok": False, "error": "pull already in progress"}
        rec = self._recv.get(oid)
        if rec is not None:
            # A dead pusher must not wedge this object forever: reclaim the
            # unsealed buffer once the transfer has gone idle, otherwise
            # report busy (NOT success — the object is not here yet).
            if time.time() - rec["t"] > 60:
                self._abort_recv(oid)
            else:
                return {"ok": False, "error": "push already in progress"}
        try:
            dest = await self._plasma_create_with_room(oid, req["size"])
        except FileExistsError:
            # an unsealed buffer we don't own (e.g. a pull that registered
            # after our check): sealed means done, unsealed means busy
            if self.plasma.contains(oid):
                return {"ok": True, "already": True}
            return {"ok": False, "error": "object mid-transfer"}
        except PlasmaOOM:
            return {"ok": False, "error": "no plasma room"}
        self._recv[oid] = {
            "view": dest, "size": req["size"],
            "owner_addr": req.get("owner_addr"), "t": time.time(),
        }
        return {"ok": True}

    async def handle_ReceiveChunk(self, req):
        rec = self._recv.get(req["object_id"])
        if rec is None:
            return {"ok": False}
        oob = req.get("_oob")
        if isinstance(oob, int):
            # the oob sink already streamed the chunk into the plasma
            # buffer at its offset — nothing left to copy
            return {"ok": True}
        data = oob if oob is not None else req.get("data")
        if data is None:
            return {"ok": False, "error": "no chunk payload"}
        off = req["offset"]
        if off < 0 or off + len(data) > rec["size"]:
            return {"ok": False, "error": "chunk out of bounds"}
        rec["view"][off:off + len(data)] = data
        rec["t"] = time.time()
        return {"ok": True}

    async def handle_ReceiveEnd(self, req):
        oid = req["object_id"]
        rec = self._recv.pop(oid, None)
        if rec is None:
            return {"ok": False}
        rec["view"].release()
        self.plasma.seal(oid)
        owner_addr = rec.get("owner_addr")
        if owner_addr:
            try:
                owner = await self.pool.get(owner_addr[0], owner_addr[1])
                await owner.notify(
                    "AddObjectLocation",
                    {"object_id": oid, "node_id": self.node_id.binary()},
                )
            except Exception:
                pass
        return {"ok": True}

    async def handle_PullObject(self, req):
        """Make the object local; replies once it is sealed in local plasma.

        Pull-based like the reference's PullManager (reference:
        object_manager/pull_manager.h:92); chunked fetch from one holder.
        """
        oid = req["object_id"]
        if self.plasma.contains(oid):
            return {"ok": True}
        inflight = self._pulls.get(oid)
        if inflight is not None:
            await inflight.wait()
            return {"ok": self.plasma.contains(oid)}
        event = asyncio.Event()
        self._pulls[oid] = event
        try:
            if oid in self._spilled:
                # Spilled on this node: restore from disk, deduplicated by
                # the same in-flight event as remote pulls so concurrent
                # getters never observe a half-restored (unsealed) object.
                ok = await self._restore_spilled(oid)
            else:
                ok = await self._do_pull(oid, req.get("owner_addr"))
            _fr.record("obj.pull", oid, "ok" if ok else "fail")
            return {"ok": ok}
        finally:
            event.set()
            self._pulls.pop(oid, None)

    async def _do_pull(self, oid: bytes, owner_addr) -> bool:
        # 1. locations from the owner (owner-based directory, reference:
        #    ownership_based_object_directory.h)
        locations: List[bytes] = []
        if owner_addr:
            try:
                owner = await self.pool.get(owner_addr[0], owner_addr[1])
                status = await owner.call(
                    "GetObjectStatus", {"object_id": oid, "wait": True}, timeout=30
                )
                locations = list(status.get("plasma", {}).get("locations", []))
                if not locations:
                    logger.warning(
                        "pull %s: owner reports no plasma locations (status=%s)",
                        oid.hex()[:12], status.get("status"),
                    )
            except Exception as e:
                logger.warning("pull %s: owner unreachable: %s", oid.hex()[:12], e)
                return False
        # Broadcast-friendly source selection: shuffle so concurrent pullers
        # of a hot object spread over ALL registered holders instead of all
        # hammering the primary (new copies register with the owner as they
        # complete, so the source set grows as a broadcast fans out —
        # reference: push_manager.h + ownership_based_object_directory.h).
        import random as _random

        locations = [l for l in locations if l != self.node_id.binary()]
        _random.shuffle(locations)
        peers = []
        size = None
        for loc in locations:
            info = self.cluster_view.get(loc)
            if info is None:
                continue
            try:
                peer = await self.pool.get(info["ip"], info["raylet_port"])
                meta = await peer.call(
                    "FetchObjectInfo", {"object_id": oid}, timeout=30
                )
                if meta.get("found"):
                    size = meta["size"]
                    peers.append(peer)
                    if len(peers) >= 4:
                        break
            except Exception as e:
                logger.warning(
                    "pull %s: holder %s unusable: %s",
                    oid.hex()[:12], loc.hex()[:12], e,
                )
        if not peers:
            return False
        try:
            dest = await self._plasma_create_with_room(oid, size)
        except FileExistsError:
            # A buffer already exists: a SEALED copy is success, but an
            # inbound push mid-transfer is not — wait for it to seal
            # instead of handing the caller a half-written object.
            deadline = time.time() + 120
            while time.time() < deadline:
                if self.plasma.contains(oid):
                    return True
                if oid not in self._recv:
                    # transfer vanished (aborted): one shot at a clean redo
                    try:
                        dest = await self._plasma_create_with_room(oid, size)
                        break
                    except FileExistsError:
                        return self.plasma.contains(oid)
                    except PlasmaOOM:
                        return False
                await asyncio.sleep(0.1)
            else:
                return False
        except PlasmaOOM:
            logger.warning("pull %s: no room even after spilling", oid.hex()[:12])
            return False
        # Chunks fetch CONCURRENTLY, striped across every viable holder
        # (reference: object_buffer_pool chunked transfer) — a large object
        # rides multiple source NICs instead of one.
        chunk = RTPU_CONFIG.object_manager_chunk_size
        offsets = list(range(0, size, chunk))
        sem = asyncio.Semaphore(8)

        async def fetch_one(i, off):
            n = min(chunk, size - off)
            order = peers[i % len(peers):] + peers[:i % len(peers)]
            async with sem:
                for peer in order:
                    try:
                        # oob_dest: the holder's out-of-band response frame
                        # streams from the socket straight into OUR plasma
                        # buffer at this chunk's offset — no staging buffer.
                        # (A timed-out call unregisters the dest; a response
                        # landing from a retried peer writes the same bytes.)
                        r = await peer.call(
                            "FetchChunk",
                            {"object_id": oid, "offset": off, "size": n},
                            timeout=60,
                            oob_dest=dest[off:off + n],
                        )
                    except Exception:
                        continue
                    if r.get("found"):
                        oob = r.get("_oob")
                        if oob == n:
                            return True  # landed in place
                        data = oob if oob is not None else r.get("data")
                        if data is None or len(data) != n:
                            continue
                        dest[off:off + n] = data
                        return True
                return False

        results = await asyncio.gather(
            *(fetch_one(i, off) for i, off in enumerate(offsets))
        )
        if not all(results):
            dest.release()
            self.plasma.abort(oid)
            return False
        dest.release()
        self.plasma.seal(oid)
        # register the new copy with the owner
        if owner_addr:
            try:
                owner = await self.pool.get(owner_addr[0], owner_addr[1])
                await owner.notify(
                    "AddObjectLocation",
                    {"object_id": oid, "node_id": self.node_id.binary()},
                )
            except Exception:
                pass
        return True

    async def handle_GetLocalObjectInfo(self, req):
        """State-API source: this node's plasma + spilled objects."""
        objects = []
        seen = set()
        for oid in self.plasma.list_object_ids():
            b = oid.binary()
            seen.add(b)
            size = None
            view = self.plasma.get(b)
            if view is not None:
                size = view.nbytes
                view.release()
                self.plasma.release(b)
            objects.append(
                {
                    "object_id": b,
                    "size": size,
                    "pinned": b in self._pinned,
                    "spilled": b in self._spilled,
                }
            )
        for oid, (path, size) in self._spilled.items():
            if oid not in seen:
                objects.append(
                    {"object_id": oid, "size": size, "pinned": False, "spilled": True}
                )
        return {"objects": objects}

    async def handle_GetLocalWorkerInfo(self, req):
        """State-API source: live worker processes on this node."""
        workers = []
        for h in self.worker_pool.workers.values():
            workers.append(
                {
                    "worker_id": h.worker_id,
                    "pid": h.pid,
                    "job_id": h.job_id,
                    "leased": h.leased,
                    "actor_id": self._actor_workers.get(h.worker_id, b""),
                    "alive": h.alive,
                }
            )
        return {"workers": workers}

    async def handle_ProfileWorker(self, req):
        """Proxy an on-demand profile request to one of this node's
        workers, addressed by worker_id or pid (reference: dashboard
        reporter agent routing, reporter_agent.py:314)."""
        target = None
        for h in self.worker_pool.workers.values():
            if (req.get("worker_id") and h.worker_id == req["worker_id"]) or (
                req.get("pid") and h.pid == req["pid"]
            ):
                target = h
                break
        if target is None or not target.addr[1]:
            return {"error": "no such worker on this node"}
        client = await self.pool.get(*target.addr)
        r = await client.call(
            "Profile",
            {"duration": req.get("duration", 2.0), "hz": req.get("hz", 100.0)},
            timeout=float(req.get("duration", 2.0)) + 30,
        )
        return r

    async def handle_StartProfile(self, req):
        """Profiling-plane fan-out: start a synchronized capture window in
        this raylet AND (include_workers, default True) every live local
        worker. CollectProfile fans the sample sets back in — together the
        pair gives the driver one RPC round per node for a cluster-wide
        profile."""
        from ray_tpu._private import sampling_profiler as _sp

        duration = req.get("duration", 2.0)
        hz = req.get("hz", 99.0)
        started = 0
        try:
            _sp.start_profile(duration, hz, role="raylet")
            started += 1
        except RuntimeError:
            pass  # a capture is already running here; collect returns it
        errors = []
        if req.get("include_workers", True):
            async def _one(h):
                try:
                    client = await self.pool.get(*h.addr)
                    r = await client.call(
                        "StartProfile", {"duration": duration, "hz": hz},
                        timeout=10)
                    return r.get("error")
                except Exception as e:
                    return str(e)

            live = [h for h in self.worker_pool.workers.values()
                    if h.alive and h.addr[1]]
            replies = await asyncio.gather(*(_one(h) for h in live))
            for h, err in zip(live, replies):
                if err:
                    errors.append(f"pid {h.pid}: {err}")
                else:
                    started += 1
        return {"ok": True, "started": started, "errors": errors}

    async def handle_CollectProfile(self, req):
        """Fan-in half: joins this raylet's capture (off-loop) and every
        live worker's, returning one profile list for the node."""
        from ray_tpu._private import sampling_profiler as _sp

        loop = asyncio.get_running_loop()
        profiles = []

        async def _collect_self():
            p = await loop.run_in_executor(None, _sp.collect_profile)
            if p is not None:
                return p
            return None

        async def _one(h):
            try:
                client = await self.pool.get(*h.addr)
                r = await client.call("CollectProfile", {}, timeout=150)
                return r.get("profile")
            except Exception:
                return None

        live = [h for h in self.worker_pool.workers.values()
                if h.alive and h.addr[1]]
        results = await asyncio.gather(
            _collect_self(), *(_one(h) for h in live))
        for p in results:
            if p:
                profiles.append(p)
        return {"node_id": self.node_id.binary(), "profiles": profiles}

    async def handle_DumpFlightRecorder(self, req):
        """Forensics fan-in: this raylet's ring plus every live local
        worker's ring in one reply (`ray-tpu debug dump` calls this once
        per node)."""
        limit = req.get("limit") or 0
        out = {
            "node_id": self.node_id.binary(),
            "pid": os.getpid(),
            "events": _fr.dump(limit),
            "workers": [],
        }
        if req.get("include_workers", True):
            async def _one(h):
                try:
                    client = await self.pool.get(*h.addr)
                    return await client.call(
                        "DumpFlightRecorder", {"limit": limit}, timeout=5)
                except Exception:
                    return None

            live = [h for h in self.worker_pool.workers.values()
                    if h.alive and h.addr[1]]
            replies = await asyncio.gather(*(_one(h) for h in live))
            out["workers"] = [r for r in replies if r]
        return out

    async def handle_Ping(self, req):
        return {"ok": True}

    async def shutdown(self):
        # Worker deaths during teardown are expected, never incidents.
        self._draining = True
        _fr.flush_now()
        for t in self._bg:
            t.cancel()
        proc = getattr(self, "_agent_proc", None)
        if proc is not None:
            self._agent_proc = None
            try:
                proc.kill()
            except Exception:
                pass
            try:
                await asyncio.wait_for(self._deregister_agent(), timeout=5)
            except Exception:
                pass
        self.worker_pool.shutdown()
        await self.server.stop()
        self.plasma.close()
        PlasmaClient.unlink(self.plasma_name)


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--node-id", default="")
    parser.add_argument("--resources", default="{}")
    parser.add_argument("--labels", default="{}")
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--is-head", action="store_true")
    parser.add_argument("--object-store-memory", type=int, default=0)
    parser.add_argument("--port-file", default="")
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO, stream=sys.stderr)
    from ray_tpu._private.proc_profile import maybe_enable_process_profile
    maybe_enable_process_profile("raylet")

    import json

    node_id = NodeID.from_hex(args.node_id) if args.node_id else NodeID.from_random()
    resources = json.loads(args.resources)
    labels = json.loads(args.labels)
    if "CPU" not in resources:
        resources["CPU"] = float(os.cpu_count() or 1)
    auto_res, auto_labels = accelerators.node_resources_and_labels()
    for k, v in auto_res.items():
        resources.setdefault(k, v)
    for k, v in auto_labels.items():
        labels.setdefault(k, v)

    async def run():
        nm = NodeManager(
            node_id, args.host, args.gcs_address, resources, labels,
            args.session_dir, is_head=args.is_head,
            object_store_memory=args.object_store_memory or None,
        )
        port = await nm.start(args.port)
        if args.port_file:
            tmp = args.port_file + ".tmp"
            with open(tmp, "w") as f:
                f.write(str(port))
            os.replace(tmp, args.port_file)
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":
    main()
