"""Stall watchdog: turn silent hangs into GCS incidents with evidence.

The dominant failure mode on TPU pods is not a crash but a *hang*: one
mismatched collective or dead host blocks every worker in the mesh and the
operator sees nothing but a stuck progress bar (arxiv 2011.03641 §5,
arxiv 2412.14374 — straggler/hang diagnosis is the hard operational
problem at scale). This watchdog runs beside the driver (this module) and
beside every raylet (NodeManager._watchdog_loop) and fires when:

  - a submitted task has not resolved for ``RTPU_watchdog_task_timeout_s``
    (driver side) / a lease has been held that long (raylet side);
  - work is pending but the completion counter has not moved for the same
    window (actor queue growing without completions);
  - train-step telemetry (train/_telemetry.StepRecorder) recorded steps
    and then went silent for ``RTPU_watchdog_step_timeout_s``;
  - the StepRecorder flagged a slow step (``slow_step``) or a post-warmup
    recompilation storm (``jit_cache_miss_storm``,
    ``RTPU_perf_compile_storm_k`` compiles inside
    ``RTPU_perf_compile_storm_window_s``).

On trigger it captures evidence while the hang is still live — its own
stacks via profiling.sample_stacks, the stuck task's executing worker via
profiling.profile_via_raylets, and a flight-recorder ring snapshot — and
publishes an **incident** record to the GCS (``ReportIncident``), where
``ray-tpu status`` counts it and ``ray-tpu debug incidents`` / ``debug
dump`` retrieve it. Each condition fires once per subject (task id / lease
id / recorder) — a stuck mesh must not turn into an incident storm.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from ray_tpu._private import flight_recorder as _fr
from ray_tpu._private.config import RTPU_CONFIG

_RING_SNAPSHOT_LIMIT = 200
_STACK_SAMPLE_S = 0.2


def capture_local_stacks(label: str) -> dict:
    """Sample THIS process's threads into a folded-stack section."""
    from ray_tpu._private import profiling

    counts = profiling.sample_stacks(_STACK_SAMPLE_S, hz=50.0,
                                     include_idle=True)
    return {"target": label, "folded": profiling.folded_text(counts)}


def build_incident(kind: str, source: str, detail: str, *,
                   node_id: str = "", worker_id: str = "",
                   task_id: str = "", task_name: str = "",
                   stacks: Optional[list] = None) -> dict:
    return {
        "kind": kind,
        "source": source,
        "detail": detail,
        "node_id": node_id,
        "worker_id": worker_id,
        "task_id": task_id,
        "task_name": task_name,
        "time": time.time(),
        "status": "open",
        "stacks": stacks or [],
        "ring": _fr.dump(limit=_RING_SNAPSHOT_LIMIT),
    }


def capture_incident_profile(core, reason: str) -> Optional[str]:
    """Automatic evidence capture for the profiling plane: one short
    cluster-wide sampling window (profiling.capture_cluster_profile),
    merged with the current task/span timeline and any registered device
    traces into a Perfetto-loadable JSON under
    ``<session>/logs/profiles/``. Returns the file path (registered in the
    GCS capture registry so `ray-tpu debug dump` and the dashboard find
    it), or None when capture failed — incident publishing must never
    depend on it."""
    import json

    from ray_tpu._private import profiling
    from ray_tpu._private import timeline as _tl

    try:
        nodes = core.gcs.get_all_node_info()
        bundle = profiling.capture_cluster_profile(
            nodes, core.gcs,
            duration=RTPU_CONFIG.profile_trigger_duration_s,
            hz=RTPU_CONFIG.profile_trigger_hz,
        )
        try:
            task_events = core.gcs.call(
                "GetTaskEvents", {"limit": 20_000}, timeout=10)["events"]
        except Exception:
            task_events = []
        device = profiling.list_registered(core.gcs, "device_trace")
        trace = _tl.merged_profile_trace(bundle, task_events, device)
        base = core.session_dir
        if not base:
            try:
                base = core.gcs.call(
                    "GetInternalConfig", {}, timeout=5).get("session_dir", "")
            except Exception:
                base = ""
        if base:
            out_dir = os.path.join(base, "logs", "profiles")
        else:
            import tempfile

            out_dir = os.path.join(tempfile.gettempdir(), "ray_tpu_profiles")
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(
            out_dir, f"profile_{reason}_{int(time.time() * 1000)}.json")
        with open(path, "w") as f:
            json.dump(trace, f)
        profiling.register_capture(core.gcs, path, reason=reason)
        _record_capture_metric(reason)
        return path
    except Exception:
        return None


_capture_counter = None
_storm_counter = None


def _record_storm_metric():
    global _storm_counter
    try:
        from ray_tpu.util.metrics import Counter

        if _storm_counter is None:
            _storm_counter = Counter(
                "ray_tpu_perf_compile_storms_total",
                "jit_cache_miss_storm incidents raised by the watchdog")
        _storm_counter.inc()
    except Exception:
        pass


def _record_capture_metric(reason: str):
    global _capture_counter
    try:
        from ray_tpu.util.metrics import Counter

        if _capture_counter is None:
            _capture_counter = Counter(
                "ray_tpu_profile_captures_total",
                "automatic cluster-profile captures", tag_keys=("trigger",))
        _capture_counter.inc(tags={"trigger": reason})
    except Exception:
        pass


class StallWatchdog:
    """Per-CoreWorker watchdog thread (drivers AND workers: the driver
    watches its submitted tasks; a train worker carries the step-stall
    check because the StepRecorder lives in its process)."""

    def __init__(self, core):
        self.core = core
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._fired: set = set()  # dedupe keys, one incident per subject
        self._progress = (0, time.time())  # (tasks_completed, t of change)
        # Slow steps and compile storms recur by nature, so they rate-limit
        # on a cooldown instead of the once-per-subject set.
        self._last_slow_capture = 0.0
        self._last_storm_fire = 0.0

    def start(self):
        self._thread = threading.Thread(
            # name ends in "-watchdog": profiling.sample_stacks skips it
            target=self._loop, name="rtpu-stall-watchdog", daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()

    def _loop(self):
        interval = RTPU_CONFIG.watchdog_interval_s
        while not self._stop.wait(interval):
            if self.core.is_shutdown:
                return
            try:
                self.check()
            except Exception:
                pass

    # ------------------------------------------------------------- checks

    def check(self):
        core = self.core
        now = time.time()
        task_timeout = RTPU_CONFIG.watchdog_task_timeout_s

        completed = core.tasks_completed
        if completed != self._progress[0]:
            self._progress = (completed, now)

        # 1. a specific submitted task stuck past the threshold
        stuck_id, stuck_rec = None, None
        for task_id, rec in list(core._pending_tasks.items()):
            t0 = rec.get("t_submit")
            if t0 and now - t0 > task_timeout:
                stuck_id, stuck_rec = task_id, rec
                break
        if stuck_id is not None and ("task", stuck_id) not in self._fired:
            self._fired.add(("task", stuck_id))
            self._fire_stuck_task(stuck_id, stuck_rec, now)
        # 2. generic no-progress: work outstanding, counter frozen
        elif (core._pending_tasks
              and now - self._progress[1] > task_timeout
              and ("progress", self._progress[0]) not in self._fired):
            self._fired.add(("progress", self._progress[0]))
            self._fire(
                "no_progress",
                f"{len(core._pending_tasks)} tasks outstanding and no "
                f"completion for {now - self._progress[1]:.0f}s",
            )

        # 3. train-step telemetry went silent
        step_timeout = RTPU_CONFIG.watchdog_step_timeout_s
        try:
            from ray_tpu.train import _telemetry

            rec = _telemetry.current_recorder()
        except Exception:
            rec = None
        if rec is not None and step_timeout > 0:
            age = rec.seconds_since_last_step()
            if (age is not None and age > step_timeout
                    and ("train", id(rec)) not in self._fired):
                self._fired.add(("train", id(rec)))
                self._fire(
                    "train_stall",
                    f"train-step telemetry silent for {age:.0f}s "
                    f"after {rec.steps} recorded steps",
                )

        # 4. a train step blew past the trailing median: capture a cluster
        #    profile while the cause (input stall, straggler host, noisy
        #    neighbor) is still warm and publish it as a slow_step incident
        if rec is not None and hasattr(rec, "pop_slow_step"):
            slow = rec.pop_slow_step()
            cooldown = RTPU_CONFIG.profile_slow_step_cooldown_s
            if (slow is not None
                    and now - self._last_slow_capture >= cooldown):
                self._last_slow_capture = now
                self._fire_slow_step(slow)

        # 5. jit-cache-miss storm: the StepRecorder counts post-warmup
        #    recompilations (previously detected, logged, and dropped) —
        #    many inside one window means throughput is being eaten by XLA
        #    retracing (unstable shapes/dtypes), which deserves an incident
        #    with an attached capture, not a log line nobody reads.
        if rec is not None and hasattr(rec, "pop_compile_storm"):
            storm = rec.pop_compile_storm()
            cooldown = RTPU_CONFIG.profile_slow_step_cooldown_s
            if (storm is not None
                    and now - self._last_storm_fire >= cooldown):
                self._last_storm_fire = now
                self._fire_compile_storm(storm)

    # -------------------------------------------------------------- firing

    def _fire_stuck_task(self, task_id: bytes, rec: dict, now: float):
        spec = rec.get("spec", {})
        lease = rec.get("lease")
        stacks = self._gather_stacks(
            lease["worker_id"] if lease else None)
        self._publish(build_incident(
            "stuck_task", self.core.mode,
            f"task {spec.get('name', '?')} submitted "
            f"{now - rec.get('t_submit', now):.0f}s ago and never resolved",
            node_id=self.core.node_id.hex() if self.core.node_id else "",
            worker_id=self.core.worker_id.hex(),
            task_id=task_id.hex(),
            task_name=spec.get("name", ""),
            stacks=stacks,
        ), task_id)

    def _fire(self, kind: str, detail: str):
        stacks = self._gather_stacks(None)
        self._publish(build_incident(
            kind, self.core.mode, detail,
            node_id=self.core.node_id.hex() if self.core.node_id else "",
            worker_id=self.core.worker_id.hex(),
            stacks=stacks,
        ), b"")

    def _fire_slow_step(self, slow: dict):
        incident = build_incident(
            "slow_step", self.core.mode,
            f"train step {int(slow.get('step', 0))} took "
            f"{slow.get('duration_s', 0):.3f}s — "
            f"{slow.get('ratio', 0):.1f}x the trailing median "
            f"({slow.get('median_s', 0):.3f}s)",
            node_id=self.core.node_id.hex() if self.core.node_id else "",
            worker_id=self.core.worker_id.hex(),
        )
        incident["slow_step"] = {
            k: float(v) for k, v in slow.items()}
        path = capture_incident_profile(self.core, "slow_step")
        if path:
            incident["profile_path"] = path
        self._publish(incident, b"")

    def _fire_compile_storm(self, storm: dict):
        incident = build_incident(
            "jit_cache_miss_storm", self.core.mode,
            f"{int(storm.get('compiles', 0))} jit compiles within "
            f"{storm.get('window_s', 0):.0f}s after warmup (at step "
            f"{int(storm.get('step', 0))}, {storm.get('compile_s', 0):.1f}s "
            "cumulative compile time) — the step fn is being retraced",
            node_id=self.core.node_id.hex() if self.core.node_id else "",
            worker_id=self.core.worker_id.hex(),
        )
        incident["compile_storm"] = {
            k: float(v) for k, v in storm.items()}
        _record_storm_metric()
        self._publish(incident, b"")

    def _gather_stacks(self, exec_worker_id) -> list:
        stacks = []
        try:
            stacks.append(capture_local_stacks(
                f"{self.core.mode}:{os.getpid()}"))
        except Exception:
            pass
        if exec_worker_id:
            # The stuck task's executing worker: the existing profiling
            # fan-out resolves it across raylets and samples its stacks.
            try:
                from ray_tpu._private import profiling

                nodes = self.core.gcs.get_all_node_info()
                status, payload = profiling.profile_via_raylets(
                    nodes, worker_id=exec_worker_id, duration=0.5)
                if status == 200:
                    stacks.append({
                        "target": f"worker:{exec_worker_id.hex()[:12]}",
                        "folded": payload.get("folded", ""),
                    })
                else:
                    stacks.append({
                        "target": f"worker:{exec_worker_id.hex()[:12]}",
                        "folded": "",
                        "error": str(payload.get("error", status)),
                    })
            except Exception:
                pass
        return stacks

    def _publish(self, incident: dict, subject: bytes):
        _fr.record("watchdog.fire", subject, incident["kind"])
        if ("profile_path" not in incident
                and RTPU_CONFIG.profile_on_incident):
            # Evidence while the hang is live: a short cluster profile
            # rides every incident this watchdog opens
            # (RTPU_profile_on_incident=0 disables).
            path = capture_incident_profile(self.core, incident["kind"])
            if path:
                incident["profile_path"] = path
        if incident.get("profile_path"):
            # Auto-analysis: read the capture back and record the "why"
            # (top stacks, compile share, scheduling delay) inside the
            # incident itself — the record must stay useful even when the
            # capture file's host is gone by the time someone looks.
            try:
                from ray_tpu._private import perf_analysis

                perf_analysis.attach_analysis(incident)
            except Exception:
                pass
        try:
            self.core.gcs.call(
                "ReportIncident", {"incident": incident}, timeout=10)
        except Exception:
            pass
