"""Fork server: preimports the runtime once, then forks worker processes.

The reference hides worker-startup latency by prestarting pooled workers
(reference: src/ray/raylet/worker_pool.h:359 PrestartWorkers). We go further:
the raylet keeps one fork-server child per node that has already paid the
Python import cost; each worker is an os.fork() of it (~tens of ms instead of
~2 s of interpreter+import startup). The child process then builds its own
CoreWorker and IO loop from scratch, so no event-loop/thread state crosses the
fork — only module imports do.

Protocol (line-delimited JSON):
  stdin:  {"spawn": {"token": int, "job_id": hex, "env": {..}, "log_prefix": path}}
          {"kill": pid}
  stdout: {"ready": true}
          {"spawned": token, "pid": pid}
          {"dead": pid, "rc": int}
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading


def _reaper(out_lock):
    while True:
        try:
            pid, status = os.waitpid(-1, 0)
        except ChildProcessError:
            # no children right now; wait for SIGCHLD via sleep
            import time

            time.sleep(0.2)
            continue
        except InterruptedError:
            continue
        rc = os.waitstatus_to_exitcode(status)
        with out_lock:
            print(json.dumps({"dead": pid, "rc": rc}), flush=True)


def _child_main(args, spawn):
    _mark("start")
    os.setsid()
    for k, v in (spawn.get("env") or {}).items():
        os.environ[k] = str(v)
    # runtime_env working_dir: run user code from the materialized directory
    # with it importable (reference: runtime_env working_dir semantics —
    # cwd + sys.path entry).
    wd = os.environ.get("RTPU_WORKING_DIR")
    if wd:
        try:
            os.chdir(wd)
            sys.path.insert(0, wd)
        except OSError:
            print(f"runtime_env: cannot enter working_dir {wd!r}", file=sys.stderr)
    # runtime_env pip venvs + py_modules: the raylet materialized them and
    # hands their import roots here; forked workers adopt them by sys.path
    # (the venv shares this interpreter via --system-site-packages, so
    # path adoption IS "running inside the venv" for import purposes).
    pypath = os.environ.get("RTPU_PYPATH_PREPEND")
    if pypath:
        import importlib

        for p in reversed(pypath.split(os.pathsep)):
            if p and p not in sys.path:
                sys.path.insert(0, p)
        importlib.invalidate_caches()
    # If jax was preimported (by us or a plugin), its platform config may
    # have been baked at import time — some platform plugins even force
    # their own value, ignoring the env. Re-sync from the (inherited +
    # overridden) environment before any backend initializes, so workers
    # honor JAX_PLATFORMS/XLA_FLAGS exactly like a fresh process would.
    if "jax" in sys.modules:
        try:
            import jax

            jax.config.update(
                "jax_platforms", os.environ.get("JAX_PLATFORMS") or None
            )
        except Exception:
            pass
    log_prefix = spawn.get("log_prefix", "")
    if log_prefix:
        out = open(log_prefix + ".out", "ab", buffering=0)
        err = open(log_prefix + ".err", "ab", buffering=0)
        os.dup2(out.fileno(), 1)
        os.dup2(err.fileno(), 2)
    devnull = os.open(os.devnull, os.O_RDONLY)
    os.dup2(devnull, 0)

    from ray_tpu._private.ids import JobID
    from ray_tpu._private.worker import MODE_WORKER, CoreWorker, set_global_worker

    profile_dir = os.environ.get("RTPU_PROFILE_WORKER_BOOT")
    prof = None
    if profile_dir:
        import cProfile

        prof = cProfile.Profile()
        prof.enable()
    actor = spawn.get("actor")
    pre_register = None
    if actor:
        # Actor-in-spawn fast path: the lease carried the creation spec, so
        # the actor initializes during boot — before RegisterWorker — and
        # the result rides the registration request. No separate GCS->worker
        # connection, CreateActor round-trip, or ActorCreated report.
        import base64

        import msgpack

        spec = msgpack.unpackb(
            base64.b64decode(actor["spec_b64"]), raw=False, strict_map_key=False
        )
        fn_blob = actor.get("fn_blob_b64")

        async def pre_register(worker):
            try:
                if fn_blob:
                    # inside the try: an unpicklable class blob must surface
                    # as a creation error, not crash the child pre-register
                    worker.functions.seed(
                        spec["fn_key"], base64.b64decode(fn_blob)
                    )
                return await worker.executor.create_actor(spec, spec["actor_id"])
            except Exception as e:
                return {"ok": False, "error": repr(e)}

    _mark("pre_core")
    worker = CoreWorker(
        mode=MODE_WORKER,
        gcs_address=args.gcs_address,
        raylet_addr=(args.raylet_host, args.raylet_port),
        job_id=JobID.from_hex(spawn["job_id"]),
        startup_token=spawn["token"],
        session_dir=args.session_dir,
        host=args.raylet_host,
        driver_sys_path=spawn.get("sys_path"),
        node_id_hex=spawn.get("node_id", ""),
        plasma_name=spawn.get("plasma_name", ""),
        pre_register=pre_register,
    )
    _mark("core_done")
    set_global_worker(worker)
    secs = os.environ.get("RTPU_PROFILE_WORKER_SECS")
    if secs and os.environ.get("RTPU_PROFILE_WORKER_BOOT"):
        import cProfile as _cp

        def _steady():
            import time as _time

            p = _cp.Profile()
            p.enable()
            _time.sleep(float(secs))
            p.disable()
            try:
                p.dump_stats(os.path.join(
                    os.environ["RTPU_PROFILE_WORKER_BOOT"],
                    f"steady-{os.getpid()}.prof"))
            except Exception:
                pass

        threading.Thread(target=_steady, daemon=True).start()
    if prof is not None:
        prof.disable()
        try:
            os.makedirs(profile_dir, exist_ok=True)
            prof.dump_stats(os.path.join(profile_dir, f"boot-{os.getpid()}.prof"))
        except Exception:
            pass  # diagnostics must never kill the worker
    if os.environ.get("RTPU_BOOT_CPU_LOG"):
        import resource

        ru = resource.getrusage(resource.RUSAGE_SELF)
        marks = " ".join(f"{k}={v * 1000:.1f}" for k, v in _BOOT_MARKS)
        print(f"BOOT_CPU pid={os.getpid()} "
              f"user={ru.ru_utime * 1000:.1f}ms sys={ru.ru_stime * 1000:.1f}ms "
              f"minflt={ru.ru_minflt} marks[{marks}]",
              file=sys.stderr, flush=True)
    threading.Event().wait()


_BOOT_MARKS: list = []


def _mark(label: str):
    if os.environ.get("RTPU_BOOT_CPU_LOG"):
        import time as _time

        _BOOT_MARKS.append((label, _time.process_time()))


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--raylet-host", required=True)
    parser.add_argument("--raylet-port", type=int, required=True)
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--session-dir", default="")
    args = parser.parse_args(argv)

    # Pay the import bill once, before any fork. This matters double on
    # hosts with PYTHONDONTWRITEBYTECODE=1 (this image): a module imported
    # lazily in the CHILD recompiles from source in EVERY child — ~80 ms a
    # pop — because nothing ever writes a .pyc. Everything a worker touches
    # during boot or its first task must be in sys.modules before fork.
    import base64  # noqa: F401
    import concurrent.futures  # noqa: F401

    import msgpack  # noqa: F401
    import numpy  # noqa: F401

    import ray_tpu._private.direct_channel  # noqa: F401
    import ray_tpu._private.executor  # noqa: F401
    import ray_tpu._private.profiling  # noqa: F401
    import ray_tpu._private.schema  # noqa: F401
    import ray_tpu._private.worker  # noqa: F401
    import ray_tpu.util.tracing  # noqa: F401

    # dlopen the plasma client library once pre-fork — children inherit the
    # mapping (the module memoizes in a global), saving ~1 ms per spawn.
    try:
        from ray_tpu._native import plasma as _plasma

        _plasma._load()
    except Exception:
        pass

    out_lock = threading.Lock()
    signal.signal(signal.SIGCHLD, signal.SIG_DFL)
    threading.Thread(target=_reaper, args=(out_lock,), daemon=True).start()
    with out_lock:
        print(json.dumps({"ready": True}), flush=True)

    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        try:
            req = json.loads(line)
        except json.JSONDecodeError:
            continue
        if "spawn" in req:
            spawn = req["spawn"]
            pid = os.fork()
            if pid == 0:
                try:
                    _child_main(args, spawn)
                except Exception:
                    import traceback

                    traceback.print_exc()
                finally:
                    os._exit(1)
            with out_lock:
                print(json.dumps({"spawned": spawn["token"], "pid": pid}), flush=True)
        elif "kill" in req:
            try:
                os.killpg(os.getpgid(req["kill"]), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                try:
                    os.kill(req["kill"], signal.SIGKILL)
                except ProcessLookupError:
                    pass


if __name__ == "__main__":
    main()
