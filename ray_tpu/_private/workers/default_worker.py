"""Worker process entrypoint, spawned by the raylet's WorkerPool.

Counterpart of the reference's default_worker.py
(reference: python/ray/_private/workers/default_worker.py, main loop
worker.py:877). The process hosts a CoreWorker whose RPC server receives
PushTask/CreateActor/PushActorTask; there is no polling loop — execution is
entirely push-driven, so the main thread just parks.
"""

from __future__ import annotations

import argparse
import logging
import sys
import threading


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--raylet-host", required=True)
    parser.add_argument("--raylet-port", type=int, required=True)
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--plasma-name", required=True)
    parser.add_argument("--job-id", required=True)
    parser.add_argument("--startup-token", type=int, required=True)
    parser.add_argument("--session-dir", default="")
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO, stream=sys.stderr)

    # runtime_env adoption, mirroring the fork-server child
    # (workers/fork_server.py _child_main): working_dir + pypath prepends
    # arrive via env vars because this entrypoint also runs under foreign
    # interpreters (conda envs) and inside containers.
    import os

    wd = os.environ.get("RTPU_WORKING_DIR")
    if wd:
        try:
            os.chdir(wd)
            sys.path.insert(0, wd)
        except OSError:
            print(f"runtime_env: cannot enter working_dir {wd!r}",
                  file=sys.stderr)
    pypath = os.environ.get("RTPU_PYPATH_PREPEND")
    if pypath:
        import importlib

        for p in reversed(pypath.split(os.pathsep)):
            if p and p not in sys.path:
                sys.path.insert(0, p)
        importlib.invalidate_caches()

    from ray_tpu._private.ids import JobID
    from ray_tpu._private.worker import MODE_WORKER, CoreWorker, set_global_worker

    worker = CoreWorker(
        mode=MODE_WORKER,
        gcs_address=args.gcs_address,
        raylet_addr=(args.raylet_host, args.raylet_port),
        job_id=JobID.from_hex(args.job_id),
        startup_token=args.startup_token,
        session_dir=args.session_dir,
        host=args.raylet_host,
        node_id_hex=args.node_id,
        plasma_name=args.plasma_name,
    )
    set_global_worker(worker)
    threading.Event().wait()


if __name__ == "__main__":
    main()
