"""Typed wire contracts for the msgpack RPC surface
(reference: the src/ray/protobuf/ *.proto files — gcs_service.proto,
node_manager.proto:381, core_worker.proto:439. The framework's RPC carries
msgpack maps instead of protobuf messages; this module is the schema: one
declarative spec per method, a protocol version, and a validator that the
RPC server runs on every request when RTPU_VALIDATE_RPC=1 (tests set it) —
so contract drift fails loudly at the boundary instead of as a KeyError
deep inside a handler).

Field spec syntax:
    "field": type            required field of that type
    "field?": type           optional field
    type may be a tuple of accepted types; `object` accepts anything.
Unknown fields are allowed (forward compatibility, like proto3 unknowns).

Out-of-band payloads: methods whose bulk bytes ride raw after the frame
header (rpc.py MSG_REQUEST_OOB / MSG_RESPONSE_OOB) see the landed payload
as an "_oob" field injected by the transport — an int byte count when it
streamed straight into its destination buffer, else a bytearray. Schemas
list the legacy inline field ("data") as optional for those methods.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Tuple, Union

PROTOCOL_VERSION = 2  # v2: out-of-band bulk frames (ReceiveChunk/FetchChunk)

TypeSpec = Union[type, Tuple[type, ...]]

_num = (int, float)
_addr = list  # [host, port]


class SchemaError(Exception):
    pass


GCS_SCHEMAS: Dict[str, Dict[str, TypeSpec]] = {
    "RegisterNode": {"node_id": bytes, "ip": str, "raylet_port": int,
                     "resources?": dict, "labels?": dict, "is_head?": bool,
                     "object_manager_port?": int, "plasma_name?": str,
                     "metrics_port?": int},
    "UnregisterNode": {"node_id": bytes},
    "GetAutoscalerActive": {},
    "Heartbeat": {"node_id": bytes},
    "ReportResources": {"node_id": bytes, "available": dict, "total": dict,
                        "pending_demands?": list, "num_leases?": int,
                        "num_workers?": int},
    "GetAllNodeInfo": {"limit?": int},
    "GetClusterResources": {},
    "GetInternalConfig": {},
    "GetClusterLoad": {},
    "KVPut": {"ns": (bytes, str), "key": (bytes, str),
              "value": (bytes, str), "overwrite?": bool},
    "KVGet": {"ns": (bytes, str), "key": (bytes, str)},
    "KVDel": {"ns": (bytes, str), "key": (bytes, str)},
    "KVKeys": {"ns": (bytes, str), "prefix?": (bytes, str)},
    "KVExists": {"ns": (bytes, str), "key": (bytes, str)},
    "Subscribe": {"sub_id": bytes, "channel": str},
    "SubscribeMany": {"sub_id": bytes, "channels": list},
    "RegisterActors": {"items": list},
    "Unsubscribe": {"sub_id": bytes, "channel?": str},
    "PubsubPoll": {"sub_id": bytes, "timeout?": _num},
    "Publish": {"channel": str, "message": object},
    "AddJob": {"job_id": bytes, "driver_addr?": _addr, "entrypoint?": str,
               "driver_sys_path?": list, "metadata?": dict},
    "GetJob": {"job_id": bytes},
    "MarkJobFinished": {"job_id": bytes},
    "GetAllJobInfo": {"limit?": int},
    "RegisterActor": {"actor_id": bytes, "creation_spec": dict,
                      "name?": str, "namespace?": str, "max_restarts?": int,
                      "detached?": bool},
    "ReportWorkerDeath": {"worker_id?": bytes, "node_id?": bytes,
                          "actor_id?": (bytes, type(None)), "reason?": str},
    "GetActorInfo": {"actor_id": bytes},
    "GetActorByName": {"name": str, "namespace?": (str, type(None))},
    "ListActors": {"limit?": int},
    "KillActor": {"actor_id": bytes, "no_restart?": bool},
    "CreatePlacementGroup": {"pg_id": bytes, "bundles": list,
                             "strategy?": str, "name?": str,
                             "job_id?": bytes,
                             "owner_worker_id?": (bytes, type(None))},
    "GetPlacementGroup": {"pg_id": bytes},
    "ListPlacementGroups": {"limit?": int},
    "WaitPlacementGroupReady": {"pg_id": bytes, "timeout?": _num},
    "RemovePlacementGroup": {"pg_id": bytes},
    "AddTaskEvents": {"events": list},
    # job_id accepts the stored hex-string form too (events materialize ids
    # to hex at flush); trace_id narrows to one trace's SPAN events.
    "GetTaskEvents": {"job_id?": (bytes, str, type(None)), "limit?": int,
                      "trace_id?": (str, type(None))},
    "ListTasks": {"job_id?": (bytes, type(None)), "limit?": int,
                  "detail?": bool},
    "GetWorkerFailures": {"limit?": int},
    "ReportIncident": {"incident": dict},
    "ListIncidents": {"limit?": int, "detail?": bool},
    "DumpFlightRecorder": {"limit?": int},
    "ReportUserMetrics": {"records?": list},
    "GetUserMetrics": {"prefix?": str},
    "StartProfile": {"duration?": _num, "hz?": _num},
    "CollectProfile": {},
    "Ping": {},
}

RAYLET_SCHEMAS: Dict[str, Dict[str, TypeSpec]] = {
    "RegisterWorker": {"worker_id": bytes, "port": int,
                       "startup_token?": int,
                       "actor_result?": dict},
    "RequestWorkerLease": {"job_id": bytes, "resources?": dict,
                           "strategy?": dict,
                           "runtime_env?": (dict, type(None))},
    "ReturnWorker": {"lease_id": bytes, "kill?": bool},
    "GetNodeInfo": {},
    "LeaseWorkerForActor": {"actor_id": bytes, "job_id": bytes,
                            "resources": dict, "strategy?": dict,
                            "runtime_env?": (dict, type(None)),
                            "spec?": dict},
    "LeaseWorkersForActors": {"items": list},
    "KillWorker": {"worker_id": bytes, "reason?": str},
    "JobFinished": {"job_id": bytes},
    "PrepareBundle": {"pg_id": bytes, "bundle_index": int,
                      "resources": dict},
    "CommitBundle": {"pg_id": bytes, "bundle_index": int},
    "PrepareBundles": {"items": list, "commit?": bool},
    "CommitBundles": {"items": list},
    "CancelBundle": {"pg_id?": bytes, "bundle_index?": int},
    "ReturnBundle": {"pg_id?": bytes, "bundle_index?": int},
    "SpillObjects": {"bytes": int},
    # meta: ownership attribution (job/actor/task/callsite/size) kept for
    # the leak detector and OOM forensics — see raylet _pin_meta handling
    "PinObject": {"object_id": bytes, "owner_addr?": _addr, "meta?": dict},
    "FreeObjects": {"ids": list},
    "PushObject": {"object_id": bytes, "target": bytes,
                   "owner_addr?": (_addr, type(None))},
    "ReceiveBegin": {"object_id": bytes, "size": int,
                     "owner_addr?": (_addr, type(None))},
    # chunk bytes normally arrive out-of-band ("_oob"); inline "data" is the
    # fallback for senders without a raw buffer at hand
    "ReceiveChunk": {"object_id": bytes, "offset": int,
                     "data?": (bytes, bytearray)},
    "ReceiveEnd": {"object_id": bytes},
    "FetchObjectInfo": {"object_id": bytes},
    "FetchChunk": {"object_id": bytes, "offset": int, "size": int},
    "PullObject": {"object_id": bytes, "owner_addr?": _addr},
    "GetLocalObjectInfo": {},
    "GetLocalWorkerInfo": {},
    "ProfileWorker": {"worker_id?": bytes, "pid?": int,
                      "duration?": _num, "hz?": _num},
    "StartProfile": {"duration?": _num, "hz?": _num,
                     "include_workers?": bool},
    "CollectProfile": {},
    "DumpFlightRecorder": {"limit?": int, "include_workers?": bool},
    # sweep=True forces a leak sweep before replying (CLI --leaks path)
    "GetMemoryReport": {"include_workers?": bool, "limit?": int,
                        "sweep?": bool},
    # plasma-backed submit ring (_private/submit_ring.py): attach/detach a
    # shared-memory spec mailbox; the doorbell is the only hot-path RPC
    "AttachSubmitRing": {"object_id": bytes, "reply_addr": _addr,
                         "job_id": bytes},
    "DetachSubmitRing": {"object_id": bytes},
    "SubmitRingDoorbell": {"object_id?": (bytes, type(None))},
    "Ping": {},
}

WORKER_SCHEMAS: Dict[str, Dict[str, TypeSpec]] = {
    "PushTask": {"spec": dict},
    "PushTasks": {"specs": list},
    "CreateActor": {"spec": dict, "actor_id": bytes},
    "PushActorTask": {"spec": dict},
    "PushActorTasks": {"specs": list, "reply_addr": _addr},
    "ActorTaskReplies": {"replies": list},
    # batched replies for ring-submitted specs (raylet -> submitter)
    "SubmitRingReplies": {"replies": list},
    "GetObjectStatus": {"object_id": bytes, "wait?": bool,
                        "timeout?": (_num, type(None))},
    "AddBorrowerRef": {"object_id": bytes, "borrower": _addr},
    "RemoveBorrowerRef": {"object_id": bytes, "borrower": _addr},
    "AddObjectLocation": {"object_id": bytes, "node_id": bytes},
    "RemoveObjectLocation": {"object_id": bytes, "node_id": bytes},
    "CancelTask": {"task_id": bytes, "force?": bool},
    "Profile": {"duration?": _num, "hz?": _num},
    "StartProfile": {"duration?": _num, "hz?": _num},
    "CollectProfile": {},
    "DumpFlightRecorder": {"limit?": int},
    "KillActor": {"no_restart?": bool},
    "Exit": {},
    "Ping": {},
    "GetCoreWorkerStats": {},
    "GetMemoryReport": {"limit?": int},
    "CheckRefs": {"ids": list},
}


def _check_type(method: str, key: str, value: Any, spec: TypeSpec):
    if spec is object:
        return
    if isinstance(spec, tuple):
        if not isinstance(value, spec):
            raise SchemaError(
                f"{method}.{key}: expected one of "
                f"{[t.__name__ for t in spec]}, got {type(value).__name__}"
            )
        return
    if spec is float:
        spec = _num  # ints are acceptable floats on the wire
    if not isinstance(value, spec):
        raise SchemaError(
            f"{method}.{key}: expected "
            f"{getattr(spec, '__name__', spec)}, got {type(value).__name__}"
        )


def validate(schemas: Dict[str, Dict[str, TypeSpec]], method: str,
             payload: Any) -> None:
    """Raise SchemaError if payload doesn't satisfy the method's schema.
    Methods without a schema pass (extension surface)."""
    schema = schemas.get(method)
    if schema is None:
        return
    if payload is None:
        payload = {}
    if not isinstance(payload, dict):
        raise SchemaError(f"{method}: payload must be a map, got "
                          f"{type(payload).__name__}")
    for key, spec in schema.items():
        optional = key.endswith("?")
        name = key[:-1] if optional else key
        if name not in payload:
            if optional:
                continue
            raise SchemaError(f"{method}: missing required field {name!r}")
        value = payload[name]
        if optional and value is None:
            continue
        _check_type(method, name, value, spec)


def validation_enabled() -> bool:
    return os.environ.get("RTPU_VALIDATE_RPC", "") not in ("", "0", "false")


def make_validator(schemas: Dict[str, Dict[str, TypeSpec]]):
    """Validator hook for RpcServer.set_validator; None when disabled."""
    if not validation_enabled():
        return None

    def _validate(method: str, payload: Any):
        validate(schemas, method, payload)

    return _validate
