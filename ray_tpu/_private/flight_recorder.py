"""Per-process flight recorder: a fixed-size ring of cheap structured events.

The telemetry layer (PR 1) explains *healthy* jobs; this module exists for
the unhealthy ones — the mismatched collective or dead host that silently
hangs every worker in a TPU mesh. Every runtime process (driver, worker,
raylet, GCS) appends structured events to a bounded in-memory ring on its
hot paths; nothing is formatted or serialized until someone asks for a dump
(reference analogues: the reference's task-event buffer + the "flight
recorder" pattern from MLPerf-scale TPU ops, arxiv 2011.03641 §5 straggler
diagnosis). The ring answers "what were the last things this process did
before it stalled/died", which Prometheus gauges cannot.

Hot-path discipline: ``record()`` is ONE ``deque.append`` of a small tuple
(seq, ts, event, a, b) — no dict build, no hex/str conversion, no lock
(deque.append is atomic under the GIL; the seq counter is an atomic
``itertools.count``). Formatting happens only in ``dump()`` /
``flush_to_file()``. The tier-1 smoke in tests/test_flight_recorder.py
bounds the per-event cost so the always-on recorder stays <2% of
small-task throughput.

Surfacing (see ray_tpu/scripts.py ``ray-tpu debug``):

  - ``DumpFlightRecorder`` RPC on raylets (fans out to live workers) and
    workers;
  - workers append new events to ``<session>/logs/flight_worker-<pid>.jsonl``
    on the task-event flush cadence and on exit, so the raylet can attach a
    SIGKILLed worker's last events to its death report (→ ActorDiedError);
  - the stall watchdog (_private/watchdog.py) snapshots the ring into every
    incident it publishes to the GCS.

EVENT-NAME STABILITY CONTRACT
-----------------------------
Like the metric names in ``ray_tpu/util/metrics.py``, the event names below
are a public debugging surface: ``ray-tpu debug`` archives, the
``flight_*.jsonl`` session files, and incident records all carry them, and
operators grep for them. Renaming or repurposing one is a breaking change —
add new names instead. ``a``/``b`` hold the event's subject (ids as raw
bytes, hex-encoded at dump time) and a short detail string/number.

  task.pending / task.submitted / task.running / task.finished /
  task.failed / task.retry       task state transitions (mirrors the GCS
                                 task-event states, lowercased)
  obj.put                        plasma/inline store of an owned object
  obj.spill / obj.restore        raylet spill-to-disk and restore, one
                                 event per object: (oid, bytes) — the
                                 timeline renders these as instants on
                                 the owning node's lane
  obj.leak                       the leak detector confirmed a primary
                                 with no live owner reference (oid, bytes)
  obj.pull / obj.push            node-to-node object transfer attempts
  rpc.error                      a transport-level RPC failure at a
                                 recorded call site (lease push, reply
                                 flush, transfer)
  lease.grant / lease.return     raylet worker-lease lifecycle
  worker.spawn / worker.death    raylet worker-pool lifecycle
  worker.oom_kill                memory-monitor kill
  actor.state                    actor lifecycle transition (GCS + owner)
  node.dead                      GCS marked a node dead
  chan.up / chan.down            direct call channel lifecycle
  collective.enter / collective.exit   gloo-style CPU collective ops
  train.step                     one (multi-)step dispatch recorded by the
                                 train telemetry layer
  serve.request                  one replica-side serve request finished
  llm.admit / llm.preempt / llm.finish   serve/llm engine sequence
                                 lifecycle (admit carries the prompt
                                 length + prefix-hit token count)
  llm.prefix_hit                 a prefix-cache hit at admission:
                                 "<seq> hit=<tokens>/<context>"
  llm.spec_verify                one speculative verify round:
                                 "batch=<B> k=<proposed> accepted=<n>"
  chaos.inject                   the chaos plane fired a fault:
                                 "<site> <action> rule=<i> <attrs>" —
                                 tests join these against the incident
                                 table to assert exactly-one attributed
                                 incident per induced fault
  serve.failover                 a serve.llm stream resubmitted its
                                 remaining generation to a surviving
                                 replica after its pinned replica died:
                                 "<app> <old>-><new> tokens=<n>
                                 attempt=<k>"
  incident.open                  the GCS accepted an incident record
  watchdog.fire                  a stall watchdog tripped locally
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import List, Optional

__all__ = [
    "FlightRecorder", "enabled", "get_recorder", "record", "dump",
    "set_dump_path", "flush_to_file", "install_exit_dump",
]


def _fmt(v):
    """Dump-time formatting of a recorded arg: bytes ids become hex."""
    if isinstance(v, (bytes, bytearray)):
        return bytes(v).hex()
    if isinstance(v, float):
        return round(v, 6)
    return v


class FlightRecorder:
    """Bounded ring of (seq, ts, event, a, b) tuples.

    ``record`` is safe from any thread; overflow silently drops the oldest
    events (that is the point of a flight recorder — the tail survives).
    """

    def __init__(self, size: int = 4096):
        self._ring: deque = deque(maxlen=max(16, int(size)))
        self._seq = itertools.count(1)
        self._next = self._seq.__next__
        self._flush_cursor = 0  # last seq written by flush_to_file
        self._flush_lock = threading.Lock()
        self.dump_path: Optional[str] = None

    # ------------------------------------------------------------ hot path

    def record(self, event: str, a=b"", b=""):
        self._ring.append((self._next(), time.time(), event, a, b))

    # ------------------------------------------------------------ readouts

    def snapshot(self) -> list:
        """Raw tuples, oldest first (cheap; no formatting)."""
        return list(self._ring)

    def dump(self, limit: int = 0) -> List[dict]:
        """Formatted events, oldest first. ``limit`` > 0 keeps the tail."""
        events = self.snapshot()
        if limit and len(events) > limit:
            events = events[-limit:]
        return [
            {"seq": seq, "ts": round(ts, 6), "event": ev,
             "a": _fmt(a), "b": _fmt(b)}
            for seq, ts, ev, a, b in events
        ]

    # ----------------------------------------------------------- file sink

    def flush_to_file(self, path: Optional[str] = None) -> int:
        """Append events recorded since the last flush to ``path`` (JSONL).

        Incremental and idempotent, so the periodic call from the worker's
        flush loop keeps the on-disk tail current — which is what makes the
        forensics work even for SIGKILLed workers (no exit handler runs,
        but the file already holds everything up to the last cadence).
        Returns the number of events written.
        """
        path = path or self.dump_path
        if not path:
            return 0
        with self._flush_lock:
            fresh = [t for t in self.snapshot() if t[0] > self._flush_cursor]
            if not fresh:
                return 0
            try:
                with open(path, "a") as f:
                    for seq, ts, ev, a, b in fresh:
                        f.write(json.dumps(
                            {"seq": seq, "ts": round(ts, 6), "event": ev,
                             "a": _fmt(a), "b": _fmt(b)}) + "\n")
            except OSError:
                return 0
            self._flush_cursor = fresh[-1][0]
            return len(fresh)


class _NullRecorder:
    """RTPU_flight_recorder=0: every entry point is a no-op."""

    dump_path = None

    def record(self, event, a=b"", b=""):
        pass

    def snapshot(self):
        return []

    def dump(self, limit=0):
        return []

    def flush_to_file(self, path=None):
        return 0


_recorder = None
_rec_lock = threading.Lock()


def enabled() -> bool:
    from ray_tpu._private.config import RTPU_CONFIG

    return bool(RTPU_CONFIG.flight_recorder)


def get_recorder() -> FlightRecorder:
    """Process-global recorder (lazy; config read once at creation)."""
    global _recorder
    rec = _recorder
    if rec is None:
        with _rec_lock:
            rec = _recorder
            if rec is None:
                from ray_tpu._private.config import RTPU_CONFIG

                if RTPU_CONFIG.flight_recorder:
                    rec = FlightRecorder(RTPU_CONFIG.flight_recorder_size)
                else:
                    rec = _NullRecorder()
                _recorder = rec
    return rec


def record(event: str, a=b"", b=""):
    """Module-level hot-path entry: one attribute walk + deque append."""
    get_recorder().record(event, a, b)


def dump(limit: int = 0) -> List[dict]:
    return get_recorder().dump(limit)


def set_dump_path(path: str):
    get_recorder().dump_path = path


def flush_to_file(path: Optional[str] = None) -> int:
    return get_recorder().flush_to_file(path)


def flush_now():
    """Best-effort final flush for os._exit paths (Exit/KillActor RPCs,
    raylet-death suicide) where atexit never runs."""
    try:
        get_recorder().flush_to_file()
    except Exception:
        pass


def install_exit_dump(path: str):
    """Arrange for the ring to reach ``path`` on normal exit and SIGTERM.

    SIGKILL cannot be caught — the periodic flush_to_file cadence is the
    real safety net; this just tightens the tail for graceful deaths.
    """
    import atexit
    import signal

    set_dump_path(path)
    atexit.register(flush_now)
    try:
        prev = signal.getsignal(signal.SIGTERM)

        def _on_term(signum, frame):
            flush_now()
            if callable(prev) and prev not in (signal.SIG_IGN, signal.SIG_DFL):
                prev(signum, frame)
            else:
                os._exit(143)

        signal.signal(signal.SIGTERM, _on_term)
    except (ValueError, OSError):
        pass  # not the main thread / restricted env: atexit still covers us


def read_tail_file(path: str, limit: int = 8) -> List[dict]:
    """Read the last ``limit`` events of a flight JSONL file (raylet side:
    attach a dead worker's final events to its death report)."""
    try:
        with open(path, "rb") as f:
            try:
                f.seek(-64 * 1024, os.SEEK_END)
            except OSError:
                pass
            lines = f.read().decode("utf-8", "replace").splitlines()
    except OSError:
        return []
    out = []
    for line in lines[-limit:]:
        try:
            out.append(json.loads(line))
        except (json.JSONDecodeError, ValueError):
            continue
    return out


def format_tail(events: List[dict]) -> str:
    """One-line-per-event rendering for error messages."""
    return "\n".join(
        f"  [{e.get('ts', 0):.3f}] {e.get('event', '?')}"
        f" {e.get('a', '')} {e.get('b', '')}".rstrip()
        for e in events
    )
