"""Distributed reference counting, owner side and borrower side.

Follows the reference's ownership protocol in spirit
(reference: src/ray/core_worker/reference_count.h:61) with a simplified
borrowing rule: every process that materializes an ObjectRef it does not own
registers itself with the owner (AddBorrowerRef) and deregisters when its last
local reference drops (RemoveBorrowerRef). The owner frees the object when

    local_ref_count == 0  and  submitted_task_count == 0  and  no borrowers.

This is chattier than the reference's batched borrower-merging protocol but
has the same lifetime semantics; the hot path (refs that never leave the
owner) involves no RPCs at all.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set, Tuple

from ray_tpu._private.ids import ObjectID


@dataclass
class OwnedRef:
    local_refs: int = 0
    # Refs held by tasks we submitted that haven't finished yet.
    submitted_task_refs: int = 0
    # (host, port) of borrower worker rpc servers.
    borrowers: Set[Tuple[str, int]] = field(default_factory=set)
    # Lineage: spec of the task that can recreate this object (for reconstruction).
    lineage_task_id: Optional[bytes] = None
    freed: bool = False
    # --- ownership-ledger metadata (memory observability plane) ----------
    # Populated at add_owned / note_size time and ONLY read back by the
    # pull-only GetMemoryReport path — nothing on the hot path consults it.
    size: int = 0
    created: float = 0.0
    callsite: str = ""
    task_id: Optional[bytes] = None  # task whose return this is (if any)
    plasma: bool = False  # primary copy lives in the shared object store


class ReferenceCounter:
    """Thread-safe: touched from user threads (__del__) and the IO loop."""

    def __init__(self, on_zero: Callable[[ObjectID], None]):
        self._lock = threading.RLock()
        self._owned: Dict[ObjectID, OwnedRef] = {}
        # Objects this process borrows: id -> (owner_addr, local_count)
        self._borrowed: Dict[ObjectID, list] = {}
        self._on_zero = on_zero
        # Called with (object_id, owner_addr, delta) when a borrowed ref's local
        # count transitions 0->1 (+1) or 1->0 (-1); wired to RPC by the worker.
        self.on_borrow_change: Optional[Callable] = None

    # ---- owner side -------------------------------------------------------

    def add_owned(self, object_id: ObjectID, lineage_task_id=None, *,
                  size: int = 0, callsite: str = "", task_id=None):
        with self._lock:
            ref = self._owned.setdefault(object_id, OwnedRef())
            ref.lineage_task_id = lineage_task_id
            ref.created = time.time()
            if size:
                ref.size = size
            if callsite:
                ref.callsite = callsite
            if task_id is not None:
                ref.task_id = task_id

    def owns(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._owned

    def add_local_ref(self, object_id: ObjectID):
        with self._lock:
            ref = self._owned.get(object_id)
            if ref is not None:
                ref.local_refs += 1

    def remove_local_ref(self, object_id: ObjectID):
        self._change_owned(object_id, d_local=-1)

    def add_submitted_task_ref(self, object_id: ObjectID):
        with self._lock:
            ref = self._owned.get(object_id)
            if ref is not None:
                ref.submitted_task_refs += 1

    def remove_submitted_task_ref(self, object_id: ObjectID):
        self._change_owned(object_id, d_task=-1)

    def add_borrower(self, object_id: ObjectID, borrower: Tuple[str, int]):
        with self._lock:
            ref = self._owned.get(object_id)
            if ref is not None:
                ref.borrowers.add(tuple(borrower))

    def remove_borrower(self, object_id: ObjectID, borrower: Tuple[str, int]):
        with self._lock:
            ref = self._owned.get(object_id)
            if ref is None:
                return
            ref.borrowers.discard(tuple(borrower))
            self._maybe_free_locked(object_id, ref)

    def _change_owned(self, object_id: ObjectID, d_local=0, d_task=0):
        with self._lock:
            ref = self._owned.get(object_id)
            if ref is None:
                return
            ref.local_refs += d_local
            ref.submitted_task_refs += d_task
            self._maybe_free_locked(object_id, ref)

    def _maybe_free_locked(self, object_id: ObjectID, ref: OwnedRef):
        if (
            ref.local_refs <= 0
            and ref.submitted_task_refs <= 0
            and not ref.borrowers
            and not ref.freed
        ):
            ref.freed = True
            del self._owned[object_id]
            self._on_zero(object_id)

    def num_owned(self) -> int:
        with self._lock:
            return len(self._owned)

    def get_lineage(self, object_id: ObjectID):
        with self._lock:
            ref = self._owned.get(object_id)
            return ref.lineage_task_id if ref else None

    # ---- borrower side ----------------------------------------------------

    def add_borrowed_ref(self, object_id: ObjectID, owner_addr) -> bool:
        """Returns True if this is the first local ref (caller must notify owner)."""
        with self._lock:
            entry = self._borrowed.get(object_id)
            if entry is None:
                self._borrowed[object_id] = [tuple(owner_addr) if owner_addr else None, 1]
                return owner_addr is not None
            entry[1] += 1
            return False

    def remove_borrowed_ref(self, object_id: ObjectID) -> Optional[Tuple[str, int]]:
        """Returns owner_addr if this was the last local ref (caller notifies owner)."""
        with self._lock:
            entry = self._borrowed.get(object_id)
            if entry is None:
                return None
            entry[1] -= 1
            if entry[1] <= 0:
                del self._borrowed[object_id]
                return entry[0]
            return None

    def stats(self):
        with self._lock:
            return {"owned": len(self._owned), "borrowed": len(self._borrowed)}

    # ---- ownership ledger (pull-only; memory observability plane) ---------

    def note_size(self, object_id: ObjectID, size: int, plasma: bool = False):
        """Record an owned ref's byte size once it becomes known (reply
        landing, plasma registration). No-op for refs we no longer own."""
        with self._lock:
            ref = self._owned.get(object_id)
            if ref is not None:
                ref.size = size
                if plasma:
                    ref.plasma = True

    def owns_many(self, ids) -> List[bool]:
        """Batch ownership probe for the leak detector's CheckRefs RPC."""
        with self._lock:
            return [oid in self._owned for oid in ids]

    def ledger(self, limit: int = 0) -> List[dict]:
        """Snapshot of every owned ref's metadata, largest first.

        This IS the per-worker object ownership ledger: per ref — size,
        owning task, creation callsite, pin/plasma state, age, refcounts.
        Built entirely on demand (the hot path only ever wrote the cheap
        fields); ``limit`` > 0 keeps the top holders by size.
        """
        now = time.time()
        with self._lock:
            rows = [
                {
                    "object_id": oid.binary(),
                    "size": ref.size,
                    "age_s": round(now - ref.created, 3) if ref.created else 0.0,
                    "callsite": ref.callsite,
                    "task_id": ref.task_id or b"",
                    "plasma": ref.plasma,
                    "local_refs": ref.local_refs,
                    "submitted_task_refs": ref.submitted_task_refs,
                    "borrowers": len(ref.borrowers),
                }
                for oid, ref in self._owned.items()
            ]
        rows.sort(key=lambda r: -r["size"])
        if limit and len(rows) > limit:
            del rows[limit:]
        return rows

    def owned_bytes(self) -> Tuple[int, int]:
        """(total owned bytes, of which plasma-resident) — cheap totals for
        snapshots and rollups."""
        with self._lock:
            total = plasma = 0
            for ref in self._owned.values():
                total += ref.size
                if ref.plasma:
                    plasma += ref.size
            return total, plasma
