"""ray_tpu.util.collective — host-side collective communication groups.

API parity with the reference's ray.util.collective (collective.py:
init_collective_group :120, create_collective_group :151, allreduce :258,
barrier :298, broadcast :373, allgather :423, reducescatter :472, send :531,
recv :594). Two planes, per SURVEY.md §2.4:

- **Device plane (TPU)**: collectives inside jit-compiled code lower to XLA
  ICI collectives via shardings — you don't call this module for those; use
  a mesh + pjit/shard_map (ray_tpu.parallel). This is the NCCL replacement.
- **Host plane (this module)**: numpy/CPU tensors between actors/tasks over a
  TCP ring with GCS-KV rendezvous — the Gloo replacement (reference
  gloo_collective_group.py:184 rendezvoused via the Ray internal KV :66).

The ring implementation: rank r listens on an ephemeral port, publishes its
address in the GCS KV under the group name, and lazily opens one socket per
peer pair (lower rank dials, higher rank accepts). allreduce is the classic
ring: world-1 reduce-scatter steps + world-1 all-gather steps, so bandwidth
is 2·(w-1)/w · payload regardless of world size.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

_KV_NS = "collective"
_CONNECT_TIMEOUT = 60.0


class ReduceOp:
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"


_REDUCERS = {
    ReduceOp.SUM: lambda a, b: np.add(a, b, out=a),
    ReduceOp.PRODUCT: lambda a, b: np.multiply(a, b, out=a),
    ReduceOp.MIN: lambda a, b: np.minimum(a, b, out=a),
    ReduceOp.MAX: lambda a, b: np.maximum(a, b, out=a),
}


def _kv():
    from ray_tpu._private.worker import get_global_worker

    return get_global_worker().gcs


def _send_msg(sock: socket.socket, payload: bytes):
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def _recv_msg(sock: socket.socket) -> bytes:
    hdr = _recv_exact(sock, 8)
    (n,) = struct.unpack("<Q", hdr)
    return _recv_exact(sock, n)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("collective peer closed connection")
        got += r
    return bytes(buf)


@dataclass
class _Group:
    name: str
    rank: int
    world_size: int
    listener: Optional[socket.socket] = None
    port: int = 0

    def __post_init__(self):
        self._conns: Dict[int, socket.socket] = {}
        self._incoming: Dict[int, socket.socket] = {}
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # Serializes connection establishment so concurrent _conn(peer) calls
        # (e.g. world_size==2, where the send and recv neighbor are the same
        # peer) cannot both miss the cache and dial twice. Safe to hold while
        # waiting: a dial never blocks on the remote peer's establish lock,
        # only on its listener (created before KV registration).
        self._estab_lock = threading.Lock()
        if self.world_size > 1:
            self.listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self.listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self.listener.bind(("", 0))
            self.listener.listen(self.world_size)
            self.port = self.listener.getsockname()[1]
            t = threading.Thread(target=self._accept_loop, daemon=True)
            t.start()

    # ------------------------------------------------------------ plumbing

    def _accept_loop(self):
        while True:
            try:
                conn, _ = self.listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            peer_rank = struct.unpack("<I", _recv_exact(conn, 4))[0]
            with self._cv:
                self._incoming[peer_rank] = conn
                self._cv.notify_all()

    def _conn(self, peer: int) -> socket.socket:
        """One socket per pair: the lower rank dials, the higher accepts."""
        # Fast path outside _estab_lock: a cached-peer send must not stall
        # behind another thread's in-progress (up to 60 s) establishment.
        with self._lock:
            if peer in self._conns:
                return self._conns[peer]
        with self._estab_lock:
            with self._lock:
                if peer in self._conns:
                    return self._conns[peer]
            if self.rank < peer:
                addr = _wait_for_addr(self.name, peer)
                s = socket.create_connection(addr, timeout=_CONNECT_TIMEOUT)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                s.settimeout(None)
                s.sendall(struct.pack("<I", self.rank))
            else:
                deadline = time.time() + _CONNECT_TIMEOUT
                with self._cv:
                    while peer not in self._incoming:
                        left = deadline - time.time()
                        if left <= 0:
                            raise TimeoutError(
                                f"rank {self.rank}: no connection from rank "
                                f"{peer}"
                            )
                        self._cv.wait(left)
                    s = self._incoming[peer]
            with self._lock:
                self._conns[peer] = s
            return s

    def send_bytes(self, peer: int, payload: bytes):
        _send_msg(self._conn(peer), payload)

    def recv_bytes(self, peer: int) -> bytes:
        return _recv_msg(self._conn(peer))

    def close(self):
        if self.listener is not None:
            try:
                self.listener.close()
            except OSError:
                pass
        for s in list(self._conns.values()):
            try:
                s.close()
            except OSError:
                pass


_groups: Dict[str, _Group] = {}


def _wait_for_addr(group_name: str, rank: int):
    kv = _kv()
    key = f"{group_name}/{rank}".encode()
    deadline = time.time() + _CONNECT_TIMEOUT
    while time.time() < deadline:
        v = kv.kv_get(_KV_NS, key)
        if v:
            host, port = v.decode().rsplit(":", 1)
            return host, int(port)
        time.sleep(0.02)
    raise TimeoutError(f"rank {rank} of group '{group_name}' never registered")


# ============================================================== public API


def init_collective_group(
    world_size: int,
    rank: int,
    backend: str = "ring",
    group_name: str = "default",
):
    """Call on every participant (reference: collective.py:120)."""
    if backend not in ("ring", "gloo", "nccl"):
        raise ValueError(f"unknown backend {backend!r}")
    if group_name in _groups:
        raise RuntimeError(f"group '{group_name}' already initialized")
    if not (0 <= rank < world_size):
        raise ValueError(f"rank {rank} out of range for world {world_size}")
    g = _Group(group_name, rank, world_size)
    if world_size > 1:
        ip = socket.gethostbyname(socket.gethostname())
        _kv().kv_put(_KV_NS, f"{group_name}/{rank}".encode(),
                     f"{ip}:{g.port}".encode())
    _groups[group_name] = g
    return g


def create_collective_group(
    actors: List,
    world_size: int,
    ranks: List[int],
    backend: str = "ring",
    group_name: str = "default",
):
    """Declarative setup from the driver (reference: collective.py:151):
    remotely initializes the group on every actor, in parallel."""
    import ray_tpu

    refs = [
        actor.__ray_call__.remote(
            lambda self, *, _w=world_size, _r=rank, _b=backend, _g=group_name:
            init_collective_group(_w, _r, _b, _g) and None
        )
        for actor, rank in zip(actors, ranks)
    ]
    ray_tpu.get(refs)


def destroy_collective_group(group_name: str = "default"):
    g = _groups.pop(group_name, None)
    if g is not None:
        try:
            _kv().kv_del(_KV_NS, f"{group_name}/{g.rank}".encode())
        except Exception:
            pass
        g.close()


def is_group_initialized(group_name: str = "default") -> bool:
    return group_name in _groups


def get_rank(group_name: str = "default") -> int:
    return _get(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _get(group_name).world_size


def _get(group_name: str) -> _Group:
    g = _groups.get(group_name)
    if g is None:
        raise RuntimeError(
            f"collective group '{group_name}' is not initialized; call "
            "init_collective_group/create_collective_group first"
        )
    return g


def _sendrecv(g: _Group, right: int, left: int, out: bytes) -> bytes:
    """Send to the right neighbor while receiving from the left."""
    box = {}

    def _tx():
        g.send_bytes(right, out)

    t = threading.Thread(target=_tx, daemon=True)
    t.start()
    box["rx"] = g.recv_bytes(left)
    t.join()
    return box["rx"]


def allreduce(tensor, group_name: str = "default", op: str = ReduceOp.SUM):
    """In-place ring allreduce; also returns the reduced array."""
    from ray_tpu._private import flight_recorder as _fr

    g = _get(group_name)
    a = np.ascontiguousarray(tensor)
    if not a.flags.writeable:
        a = a.copy()  # zero-copy object-store views are read-only
    if g.world_size == 1:
        return a
    # enter/exit bracket: a rank stuck INSIDE the collective (the classic
    # mismatched-collective hang) shows an unmatched collective.enter in
    # its flight-recorder tail — the single most valuable hang breadcrumb
    _fr.record("collective.enter",
               f"{group_name}:r{g.rank}".encode(), f"allreduce {a.nbytes}B")
    try:
        return _allreduce_ring(g, a, tensor, op)
    finally:
        _fr.record("collective.exit",
                   f"{group_name}:r{g.rank}".encode(), "allreduce")


def _allreduce_ring(g, a, tensor, op):
    w, r = g.world_size, g.rank
    right, left = (r + 1) % w, (r - 1) % w
    flat = a.reshape(-1)
    chunks = np.array_split(flat, w)
    offsets = np.cumsum([0] + [c.size for c in chunks])
    reduce_fn = _REDUCERS[op]
    # reduce-scatter
    for step in range(w - 1):
        send_idx = (r - step) % w
        recv_idx = (r - step - 1) % w
        rx = _sendrecv(g, right, left, chunks[send_idx].tobytes())
        incoming = np.frombuffer(rx, dtype=a.dtype)
        seg = flat[offsets[recv_idx]:offsets[recv_idx + 1]]
        reduce_fn(seg, incoming)
    # all-gather
    for step in range(w - 1):
        send_idx = (r - step + 1) % w
        recv_idx = (r - step) % w
        rx = _sendrecv(g, right, left, chunks[send_idx].tobytes())
        flat[offsets[recv_idx]:offsets[recv_idx + 1]] = np.frombuffer(
            rx, dtype=a.dtype
        )
    if (isinstance(tensor, np.ndarray) and tensor is not a
            and tensor.flags.writeable):
        tensor[...] = a.reshape(tensor.shape)
    return a.reshape(np.shape(tensor))


def barrier(group_name: str = "default"):
    g = _get(group_name)
    if g.world_size == 1:
        return
    allreduce(np.zeros(1, np.int8), group_name)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    """Ring pipeline broadcast from src_rank; in-place on non-src ranks."""
    g = _get(group_name)
    a = np.ascontiguousarray(tensor)
    if g.world_size == 1:
        return a
    w, r = g.world_size, g.rank
    right, left = (r + 1) % w, (r - 1) % w
    if r == src_rank:
        g.send_bytes(right, a.tobytes())
    else:
        data = g.recv_bytes(left)
        a = np.frombuffer(data, dtype=a.dtype).reshape(np.shape(tensor)).copy()
        if right != src_rank:
            g.send_bytes(right, data)
        if isinstance(tensor, np.ndarray) and tensor.flags.writeable:
            tensor[...] = a
    return a


def allgather(tensor, group_name: str = "default") -> List[np.ndarray]:
    """Returns [rank0_tensor, ..., rankN-1_tensor] (functional form; the
    reference fills a tensor_list in place — same data)."""
    g = _get(group_name)
    a = np.ascontiguousarray(tensor)
    w, r = g.world_size, g.rank
    out: List[Optional[np.ndarray]] = [None] * w
    out[r] = a.copy()
    if w == 1:
        return [out[0]]
    right, left = (r + 1) % w, (r - 1) % w
    for step in range(w - 1):
        send_idx = (r - step) % w
        recv_idx = (r - step - 1) % w
        rx = _sendrecv(g, right, left, out[send_idx].tobytes())
        out[recv_idx] = np.frombuffer(rx, dtype=a.dtype).reshape(a.shape).copy()
    return out  # type: ignore[return-value]


def reducescatter(tensor, group_name: str = "default",
                  op: str = ReduceOp.SUM) -> np.ndarray:
    """Reduce across ranks, return this rank's 1/world shard (reference
    :472 takes a tensor list; here the input is the full array)."""
    g = _get(group_name)
    a = np.ascontiguousarray(tensor).copy()
    w, r = g.world_size, g.rank
    flat = a.reshape(-1)
    chunks = np.array_split(flat, w)
    offsets = np.cumsum([0] + [c.size for c in chunks])
    if w == 1:
        return flat
    right, left = (r + 1) % w, (r - 1) % w
    reduce_fn = _REDUCERS[op]
    for step in range(w - 1):
        send_idx = (r - step) % w
        recv_idx = (r - step - 1) % w
        rx = _sendrecv(g, right, left, chunks[send_idx].tobytes())
        seg = flat[offsets[recv_idx]:offsets[recv_idx + 1]]
        reduce_fn(seg, np.frombuffer(rx, dtype=a.dtype))
    mine = (r + 1) % w
    return flat[offsets[mine]:offsets[mine + 1]].copy()


def send(tensor, dst_rank: int, group_name: str = "default"):
    import json

    g = _get(group_name)
    a = np.ascontiguousarray(tensor)
    head = json.dumps({"dtype": a.dtype.str, "shape": list(a.shape)}).encode()
    g.send_bytes(dst_rank, head + b"\x00" + a.tobytes())


def recv(src_rank: int, group_name: str = "default") -> np.ndarray:
    import json

    g = _get(group_name)
    payload = g.recv_bytes(src_rank)
    head, _, body = payload.partition(b"\x00")
    meta = json.loads(head.decode())
    a = np.frombuffer(body, dtype=np.dtype(meta["dtype"])).copy()
    return a.reshape(meta["shape"])
