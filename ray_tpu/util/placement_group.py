"""Placement groups: gang reservation of resource bundles across nodes
(reference: python/ray/util/placement_group.py:41,:145; GCS-side 2PC in
gcs_placement_group_scheduler.h). On a TPU cluster the canonical use is
reserving whole ICI slices: one bundle per slice host, or one
``TPU-<type>-head`` bundle to gang-schedule a slice."""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ray_tpu._private.ids import PlacementGroupID
from ray_tpu._private.worker import get_global_worker

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: bytes, bundles: List[Dict[str, float]],
                 created: bool = False):
        self.id = pg_id
        self._bundles = bundles
        # CreatePlacementGroup's reply carries the state when the GCS
        # reserved the group inline; ready()/wait() then skip their RPC.
        self._created = created

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        return list(self._bundles)

    @property
    def bundle_count(self) -> int:
        return len(self._bundles)

    def ready(self, timeout: float = 600.0):
        """Block until the group is reserved; returns self (the reference
        returns an ObjectRef — here waiting is direct and synchronous)."""
        if not self.wait(timeout):
            raise TimeoutError("placement group not ready within timeout")
        return self

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        if self._created:
            return True
        worker = get_global_worker()
        reply = worker.gcs.call(
            "WaitPlacementGroupReady",
            {"pg_id": self.id, "timeout": timeout_seconds},
            timeout=timeout_seconds + 5,
        )
        self._created = bool(reply.get("ready"))
        return self._created

    def __reduce__(self):
        return (PlacementGroup, (self.id, self._bundles))


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: str = "",
    lifetime: Optional[str] = None,
) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}")
    if not bundles:
        raise ValueError("bundles must be non-empty")
    for b in bundles:
        if not b or any(v < 0 for v in b.values()):
            raise ValueError(f"invalid bundle {b}")
    worker = get_global_worker()
    pg_id = PlacementGroupID.from_random().binary()
    reply = worker.gcs.call(
        "CreatePlacementGroup",
        {
            "pg_id": pg_id,
            "bundles": bundles,
            "strategy": strategy,
            "name": name,
            "job_id": worker.job_id.binary(),
            # Fate-sharing (reference: PGs are owned by their creating
            # worker/job and reclaimed when it dies) unless detached.
            "owner_worker_id": (
                None if lifetime == "detached"
                else worker.worker_id.binary()
            ),
        },
    )
    return PlacementGroup(pg_id, bundles,
                          created=reply.get("state") == "CREATED")


def remove_placement_group(pg: PlacementGroup):
    worker = get_global_worker()
    worker.gcs.call("RemovePlacementGroup", {"pg_id": pg.id})


def get_placement_group(name: str) -> PlacementGroup:
    worker = get_global_worker()
    reply = worker.gcs.call("ListPlacementGroups", {})
    for rec in reply["pgs"]:
        if rec.get("name") == name and rec["state"] != "REMOVED":
            return PlacementGroup(rec["pg_id"], [b["resources"] for b in rec["bundles"]])
    raise ValueError(f"no placement group named '{name}'")


def placement_group_table() -> dict:
    worker = get_global_worker()
    reply = worker.gcs.call("ListPlacementGroups", {})
    out = {}
    for rec in reply["pgs"]:
        out[rec["pg_id"].hex()] = {
            "name": rec.get("name", ""),
            "strategy": rec["strategy"],
            "state": rec["state"],
            "bundles": {
                b["index"]: b["resources"] for b in rec["bundles"]
            },
            "bundles_to_node_id": {
                b["index"]: (b["node_id"].hex() if b.get("node_id") else None)
                for b in rec["bundles"]
            },
        }
    return out
