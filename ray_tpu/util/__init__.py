"""ray_tpu.util — placement groups, scheduling strategies, collectives,
actor pool, queue, state API."""

from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from ray_tpu.util import collective  # noqa: F401


def __getattr__(name):
    if name == "collective":
        from ray_tpu.util import collective

        return collective
    if name == "placement_group":
        from ray_tpu.util import placement_group

        return placement_group
    if name == "ActorPool":
        from ray_tpu.util.actor_pool import ActorPool

        return ActorPool
    if name == "queue":
        from ray_tpu.util import queue

        return queue
    if name == "state":
        from ray_tpu.util import state

        return state
    raise AttributeError(f"module 'ray_tpu.util' has no attribute '{name}'")
