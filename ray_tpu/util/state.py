"""State API: live introspection of cluster entities.

Counterpart of ``ray.util.state``
(reference: python/ray/util/state/api.py — list_actors :781, list_nodes
:873, list_tasks :1008, summarize_tasks :1365; aggregation
dashboard/state_aggregator.py:138). The GCS is the source of truth for
actors/nodes/jobs/placement groups/tasks (task events); object listings are
aggregated live from every raylet's plasma + spill tables.

All functions accept an optional ``address`` ("host:port" of the GCS);
default is the connected driver's cluster.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu._private.gcs.client import GcsClient


def _gcs(address: Optional[str]) -> GcsClient:
    if address:
        return GcsClient.from_address(address)
    from ray_tpu._private import worker as worker_mod

    if worker_mod.global_worker is None:
        raise RuntimeError("ray_tpu is not initialized and no address given")
    return worker_mod.global_worker.gcs


def _hex(b) -> str:
    return b.hex() if isinstance(b, (bytes, bytearray)) else str(b)


def _server_limit(filters, limit: int) -> dict:
    """Limit applied GCS-side — but only when no client-side filters will
    run afterwards (limiting before filtering would change results)."""
    return {} if filters else {"limit": limit}


def list_nodes(address: Optional[str] = None, *, filters=None, limit: int = 10_000) -> List[dict]:
    nodes = _gcs(address).call(
        "GetAllNodeInfo", _server_limit(filters, limit))["nodes"]
    out = [
        {
            "node_id": _hex(n["node_id"]),
            "state": n["state"],
            "node_ip": n["ip"],
            "raylet_port": n["raylet_port"],
            "metrics_port": n.get("metrics_port", 0),
            "is_head_node": bool(n.get("is_head")),
            "resources_total": n.get("resources_total", {}),
            "resources_available": n.get("resources_available", {}),
            "labels": n.get("labels", {}),
            "start_time": n.get("start_time"),
            "end_time": n.get("end_time"),
        }
        for n in nodes
    ]
    return _filtered(out, filters)[:limit]


def list_actors(address: Optional[str] = None, *, filters=None, limit: int = 10_000) -> List[dict]:
    actors = _gcs(address).call(
        "ListActors", _server_limit(filters, limit))["actors"]
    out = [
        {
            "actor_id": _hex(a["actor_id"]),
            "state": a["state"],
            "name": a.get("name", ""),
            "ray_namespace": a.get("namespace", ""),
            "job_id": _hex(a.get("job_id", b"")),
            "node_id": _hex(a["node_id"]) if a.get("node_id") else None,
            "pid": None,
            "class_name": a.get("class_name", ""),
            "num_restarts": a.get("num_restarts", 0),
            "death_cause": a.get("death_cause", ""),
            "start_time": a.get("start_time"),
        }
        for a in actors
    ]
    return _filtered(out, filters)[:limit]


def list_jobs(address: Optional[str] = None, *, filters=None, limit: int = 10_000) -> List[dict]:
    jobs = _gcs(address).call(
        "GetAllJobInfo", _server_limit(filters, limit))["jobs"]
    out = [
        {
            "job_id": _hex(j["job_id"]),
            "status": j.get("state", ""),
            "entrypoint": j.get("entrypoint", ""),
            "start_time": j.get("start_time"),
            "end_time": j.get("end_time"),
            "metadata": j.get("metadata", {}),
        }
        for j in jobs
    ]
    return _filtered(out, filters)[:limit]


def list_placement_groups(
    address: Optional[str] = None, *, filters=None, limit: int = 10_000
) -> List[dict]:
    pgs = _gcs(address).call(
        "ListPlacementGroups", _server_limit(filters, limit))["pgs"]
    out = [
        {
            "placement_group_id": _hex(p["pg_id"]),
            "name": p.get("name", ""),
            "state": p["state"],
            "strategy": p.get("strategy", ""),
            "bundles": [
                {
                    "bundle_index": b["index"],
                    "resources": b["resources"],
                    "node_id": _hex(b["node_id"]) if b.get("node_id") else None,
                }
                for b in p.get("bundles", [])
            ],
        }
        for p in pgs
    ]
    return _filtered(out, filters)[:limit]


def list_tasks(
    address: Optional[str] = None, *, filters=None, limit: int = 10_000,
    detail: bool = True,
) -> List[dict]:
    """Latest known state per task, folded GCS-side (``ListTasks``): the
    server folds its task-event log into one row per task and applies
    ``limit`` before anything crosses the wire — the old path shipped the
    whole 100k-event log and sliced client-side. ``detail=False`` is the
    fast path for count/state polling: rows carry only identity + state
    (no error messages / node / worker attribution)."""
    req = {"detail": detail}
    if not filters:
        req["limit"] = limit
    rows = _gcs(address).call("ListTasks", req)["tasks"]
    out = [
        {
            "task_id": t["task_id"],
            "name": t.get("name", ""),
            "state": t["state"],
            "job_id": t.get("job_id", ""),
            "creation_time": t.get("creation_time"),
            "last_update_time": t.get("last_update_time"),
            **(
                {
                    "actor_id": t.get("actor_id", "") or None,
                    "node_id": t.get("node_id", ""),
                    "worker_id": t.get("worker_id", ""),
                    "error_message": t.get("error_message", ""),
                }
                if detail
                else {}
            ),
        }
        for t in rows
    ]
    return _filtered(out, filters)[:limit]


def summarize_tasks(address: Optional[str] = None) -> dict:
    """Counts by (name, state) — reference: util/state/api.py:1365."""
    tasks = list_tasks(address)
    summary: Dict[str, Dict[str, int]] = {}
    for t in tasks:
        by_state = summary.setdefault(t["name"], {})
        by_state[t["state"]] = by_state.get(t["state"], 0) + 1
    return {
        "total_tasks": len(tasks),
        "summary": summary,
    }


def list_incidents(
    address: Optional[str] = None, *, limit: int = 100, detail: bool = False
) -> List[dict]:
    """Stall-watchdog incident records from the GCS (newest last).
    ``detail=True`` includes captured stacks and flight-recorder rings."""
    return _gcs(address).call(
        "ListIncidents", {"limit": limit, "detail": detail}
    )["incidents"]


def count_open_incidents(address: Optional[str] = None) -> int:
    return _gcs(address).call("ListIncidents", {"limit": 1}).get("open", 0)


def _fanout_raylets(address: Optional[str], method: str, timeout: float = 10.0,
                    payload: Optional[dict] = None):
    """Call every alive raylet concurrently; yields (node, reply) pairs."""
    import asyncio

    from ray_tpu._private.rpc import IoThread, RpcClient

    nodes = [
        n
        for n in _gcs(address).call("GetAllNodeInfo", {})["nodes"]
        if n["state"] == "ALIVE"
    ]

    async def _one(n):
        client = RpcClient(n["ip"], n["raylet_port"])
        try:
            await client.connect()
            return n, await client.call(method, payload or {}, timeout=timeout)
        finally:
            await client.close()

    async def _all():
        return await asyncio.gather(
            *(_one(n) for n in nodes), return_exceptions=True
        )

    results = IoThread.current().run(_all(), timeout=timeout + 10)
    return [r for r in results if not isinstance(r, BaseException)]


def list_objects(
    address: Optional[str] = None, *, filters=None, limit: int = 10_000
) -> List[dict]:
    """Aggregate plasma + spilled objects from every alive raylet."""
    out: List[dict] = []
    for n, r in _fanout_raylets(address, "GetLocalObjectInfo"):
        for o in r.get("objects", []):
            out.append(
                {
                    "object_id": _hex(o["object_id"]),
                    "node_id": _hex(n["node_id"]),
                    "size_bytes": o.get("size"),
                    "pinned": o.get("pinned", False),
                    "spilled": o.get("spilled", False),
                }
            )
    return _filtered(out, filters)[:limit]


def list_workers(
    address: Optional[str] = None, *, filters=None, limit: int = 10_000
) -> List[dict]:
    """Live worker processes (from every raylet) + recent worker failures."""
    out: List[dict] = []
    for n, r in _fanout_raylets(address, "GetLocalWorkerInfo"):
        for w in r.get("workers", []):
            out.append(
                {
                    "worker_id": _hex(w.get("worker_id", b"")),
                    "node_id": _hex(n["node_id"]),
                    "pid": w.get("pid"),
                    "job_id": _hex(w.get("job_id", b"")),
                    "is_alive": bool(w.get("alive", True)),
                    "leased": bool(w.get("leased")),
                    "actor_id": _hex(w["actor_id"]) if w.get("actor_id") else None,
                    "exit_detail": "",
                    "end_time": None,
                }
            )
    failures = _gcs(address).call("GetWorkerFailures", {"limit": limit})["failures"]
    out.extend(
        {
            "worker_id": _hex(f.get("worker_id", b"")),
            "node_id": _hex(f.get("node_id", b"")),
            "pid": None,
            "job_id": "",
            "is_alive": False,
            "leased": False,
            "actor_id": None,
            "exit_detail": f.get("reason", ""),
            "end_time": f.get("time"),
        }
        for f in failures
    )
    return _filtered(out, filters)[:limit]


# ------------------------------------------------- memory observability


def _hexify_worker_report(w: dict) -> dict:
    out = dict(w)
    for k in ("worker_id", "actor_id", "job_id"):
        out[k] = _hex(out.get(k, b"") or b"")
    out["ledger"] = [
        {**row,
         "object_id": _hex(row.get("object_id", b"")),
         "task_id": _hex(row.get("task_id", b"") or b"")}
        for row in (w.get("ledger") or [])
    ]
    return out


def _driver_memory_reports(address: Optional[str], limit: int) -> List[dict]:
    """Drivers own most long-lived refs but live in no raylet's worker
    pool — ask each RUNNING job's driver directly (same pattern as the
    profiling plane's driver fan-out)."""
    import asyncio

    from ray_tpu._private.rpc import IoThread, RpcClient

    addrs = []
    try:
        for j in _gcs(address).call("GetAllJobInfo", {}, timeout=10)["jobs"]:
            addr = j.get("driver_addr")
            if j.get("state") == "RUNNING" and addr and addr[1]:
                addrs.append((addr[0], int(addr[1])))
    except Exception:
        return []

    async def _one(a):
        client = RpcClient(*a)
        try:
            await client.connect()
            r = await client.call("GetMemoryReport", {"limit": limit},
                                  timeout=10)
            return r.get("report")
        finally:
            await client.close()

    async def _all():
        return await asyncio.gather(*(_one(a) for a in addrs),
                                    return_exceptions=True)

    results = IoThread.current().run(_all(), timeout=30)
    return [_hexify_worker_report(r) for r in results
            if r and not isinstance(r, BaseException)]


def memory_report(
    address: Optional[str] = None, *, include_objects: bool = True,
    include_drivers: bool = True, sweep: bool = False,
    limit: int = 0,
) -> dict:
    """Cluster-wide memory report: every raylet's plasma/spill/pin tables
    joined with its workers' object ownership ledgers (``GetMemoryReport``
    fan-in), running jobs' driver ledgers, and the per-device HBM gauges
    the train telemetry already exports — one structure that answers "who
    is holding this memory". ``sweep=True`` forces a leak sweep on every
    node first."""
    import time as _time

    from ray_tpu._private.config import RTPU_CONFIG

    limit = limit or RTPU_CONFIG.memory_report_top_n
    payload = {"include_workers": True, "limit": limit}
    if sweep:
        payload["sweep"] = True
    nodes_out = []
    for n, r in _fanout_raylets(
        address, "GetMemoryReport", timeout=60, payload=payload
    ):
        node = {
            "node_id": _hex(r.get("node_id", n["node_id"])),
            "node_ip": n["ip"],
            "plasma": r.get("plasma", {}),
            "pinned_count": r.get("pinned_count", 0),
            "pinned_bytes": r.get("pinned_bytes", 0),
            "spilled_count": r.get("spilled_count", 0),
            "spilled_bytes": r.get("spilled_bytes", 0),
            "raylet_rss": r.get("raylet_rss", 0),
            "agent_rss": r.get("agent_rss", 0),
            "leaks": r.get("leaks", []),
            "leak_candidates": r.get("leak_candidates", 0),
            "workers": [_hexify_worker_report(w)
                        for w in r.get("workers", [])],
        }
        if include_objects:
            node["objects"] = [
                {**o,
                 "object_id": _hex(o.get("object_id", b"")),
                 "job_id": _hex(o.get("job_id", b"") or b""),
                 "actor_id": _hex(o.get("actor_id", b"") or b""),
                 "task_id": _hex(o.get("task_id", b"") or b"")}
                for o in r.get("objects", [])
            ]
        else:
            node["objects"] = []
        nodes_out.append(node)
    drivers = (_driver_memory_reports(address, limit)
               if include_drivers else [])
    try:
        hbm = _gcs(address).call(
            "GetUserMetrics",
            {"prefix": "ray_tpu_train_hbm_bytes_in_use"})["records"]
    except Exception:
        hbm = []
    return {"time": _time.time(), "nodes": nodes_out, "drivers": drivers,
            "hbm": hbm}


def memory_rollup(report: dict, group_by: str = "job") -> Dict[str, dict]:
    """Fold a ``memory_report`` into per-job / per-actor / per-node rows
    unifying plasma residency (raylet tables, pin-meta attribution), worker
    RSS + owned-ledger bytes, per-device HBM, and leaked bytes."""
    if group_by not in ("job", "actor", "node"):
        raise ValueError(f"group_by must be job|actor|node, not {group_by!r}")
    rows: Dict[str, dict] = {}

    def row(key: str) -> dict:
        return rows.setdefault(key or "?", {
            "plasma_bytes": 0, "objects": 0, "spilled_bytes": 0,
            "rss_bytes": 0, "owned_bytes": 0, "hbm_bytes": 0,
            "leaked_bytes": 0, "workers": 0,
        })

    # WorkerId metric labels are 12-hex prefixes (worker.py stamps them)
    wid_map: Dict[str, str] = {}
    # object_id -> (job, actor) from every owner ledger: attributes copies
    # that carry no pin meta (e.g. secondaries pulled to another node)
    oid_attr: Dict[str, tuple] = {}
    for node in report.get("nodes", []):
        for w in node.get("workers", []):
            wid = (w.get("worker_id") or "")[:12]
            if group_by == "node":
                wid_map[wid] = node["node_id"]
            elif group_by == "actor":
                wid_map[wid] = w.get("actor_id") or "-"
            else:
                wid_map[wid] = w.get("job_id") or "?"
            for entry in w.get("ledger") or []:
                oid_attr[entry.get("object_id", "")] = (
                    w.get("job_id") or "", w.get("actor_id") or "")
    for w in report.get("drivers", []):
        wid_map[(w.get("worker_id") or "")[:12]] = (
            "(driver)" if group_by in ("node", "actor")
            else w.get("job_id") or "?")
        for entry in w.get("ledger") or []:
            oid_attr[entry.get("object_id", "")] = (
                w.get("job_id") or "", w.get("actor_id") or "")

    def _obj_key(node: dict, o: dict) -> str:
        if group_by == "node":
            return node["node_id"]
        attr = oid_attr.get(o.get("object_id", ""), ("", ""))
        if group_by == "actor":
            return o.get("actor_id") or attr[1] or "-"
        return o.get("job_id") or attr[0] or "?"

    for node in report.get("nodes", []):
        for o in node.get("objects", []):
            r = row(_obj_key(node, o))
            r["objects"] += 1
            size = o.get("size") or 0
            if o.get("spilled") and not o.get("pinned"):
                r["spilled_bytes"] += size
            else:
                r["plasma_bytes"] += size
        for leak in node.get("leaks", []):
            r = row(_obj_key(node, leak))
            r["leaked_bytes"] += leak.get("size") or 0
        for w in node.get("workers", []):
            if group_by == "node":
                key = node["node_id"]
            elif group_by == "actor":
                key = w.get("actor_id") or "-"
            else:
                key = w.get("job_id") or "?"
            r = row(key)
            r["rss_bytes"] += w.get("rss_bytes") or 0
            r["owned_bytes"] += w.get("owned_bytes") or 0
            r["workers"] += 1
    for w in report.get("drivers", []):
        if group_by == "job":
            key = w.get("job_id") or "?"
        else:
            key = "(driver)"
        r = row(key)
        r["rss_bytes"] += w.get("rss_bytes") or 0
        r["owned_bytes"] += w.get("owned_bytes") or 0
        r["workers"] += 1
    for rec in report.get("hbm", []):
        labels = rec.get("labels", {})
        if group_by == "job":
            key = labels.get("JobId") or "?"
        else:
            key = wid_map.get(labels.get("WorkerId", ""), "?")
        row(key)["hbm_bytes"] += rec.get("value") or 0
    return rows


def find_memory_leaks(
    address: Optional[str] = None, *, sweep: bool = True,
    confirm_pause_s: float = 1.0,
) -> List[dict]:
    """Leaked plasma primaries across the cluster, with attribution.

    With ``sweep=True`` every raylet runs a leak sweep on demand — twice,
    ``confirm_pause_s`` apart, because confirmation needs two sweeps (the
    in-flight-handoff guard). Without it, returns whatever the background
    cadence last confirmed."""
    payload = {"include_workers": False}
    if sweep:
        payload["sweep"] = True
        import time as _time

        _fanout_raylets(address, "GetMemoryReport", timeout=60,
                        payload=payload)
        _time.sleep(max(0.0, confirm_pause_s))
    leaks: List[dict] = []
    for _n, r in _fanout_raylets(
        address, "GetMemoryReport", timeout=60, payload=payload
    ):
        leaks.extend(r.get("leaks", []))
    leaks.sort(key=lambda l: -(l.get("size") or 0))
    return leaks


def _filtered(rows: List[dict], filters) -> List[dict]:
    """filters: iterable of (key, predicate '=' or '!=', value) tuples."""
    if not filters:
        return rows
    for key, op, value in filters:
        if op == "=":
            rows = [r for r in rows if r.get(key) == value]
        elif op == "!=":
            rows = [r for r in rows if r.get(key) != value]
        else:
            raise ValueError(f"unsupported filter predicate {op!r}")
    return rows
