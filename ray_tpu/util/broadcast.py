"""Broadcast a plasma object to many nodes with tree fan-out pushes.

Reference: the object-store broadcast scalability envelope
(release/benchmarks/README.md:19 — 1 GiB to 50+ nodes) is served by the
object manager's push path (object_manager/object_manager.cc:339,
push_manager.h). Here the owner orchestrates a binary fan-out: every round,
every node that already holds a copy pushes to one node that doesn't, so a
broadcast to N nodes takes ceil(log2 N) rounds and the transfer load
spreads across holders instead of N serial pulls from the primary.

Each push rides the zero-copy transfer path: the holding raylet slices its
plasma view directly into out-of-band RPC frames (rpc.py MSG_REQUEST_OOB)
and the receiver streams the chunks from the socket straight into its
pre-created plasma buffer — no Python bytes materialization of the object
anywhere in the fan-out (raylet handle_PushObject / _receive_chunk_sink).
"""

from __future__ import annotations

import asyncio
from typing import List, Optional, Tuple

from ray_tpu._private.object_ref import ObjectRef


def broadcast_object(
    ref: ObjectRef,
    node_ids: Optional[List[bytes]] = None,
    timeout: float = 300.0,
) -> dict:
    """Replicate `ref`'s plasma object onto `node_ids` (default: every
    alive node). Returns {"rounds", "transfers": [(src_node, dst_node)...],
    "nodes": final holder set}. The object must be plasma-resident (large
    object); inline objects don't need broadcasting.
    """
    from ray_tpu._private.worker import get_global_worker

    worker = get_global_worker()
    return worker.io.run(
        _broadcast(worker, ref, node_ids, timeout), timeout=timeout + 30
    )


async def _broadcast(worker, ref, node_ids, timeout):
    oid = ref.object_id()
    # holder set + owner address from the owner's directory
    owner_addr = (
        list(ref.owner_address) if ref.owner_address else list(worker.address)
    )
    if tuple(owner_addr) == worker.address:
        entry = worker.memory_store.get_if_exists(oid)
        locations = set(getattr(entry, "locations", set()))
        locations |= worker._object_locations.get(oid.binary(), set())
    else:
        owner = await worker.pool.get(owner_addr[0], owner_addr[1])
        status = await owner.call(
            "GetObjectStatus", {"object_id": oid.binary(), "wait": True},
            timeout=30,
        )
        locations = set(status.get("plasma", {}).get("locations", []))
    if not locations:
        raise ValueError(
            f"object {oid.hex()[:12]} has no plasma copies — only "
            "plasma-resident (large) objects can be broadcast"
        )

    nodes = await worker.gcs_aio.get_all_node_info()
    alive = {n["node_id"]: n for n in nodes if n.get("state", "ALIVE") == "ALIVE"}
    targets = [
        n for n in (node_ids if node_ids is not None else list(alive))
        if n in alive and n not in locations
    ]

    sources = [loc for loc in locations if loc in alive]
    if not sources:
        raise ValueError("no alive holder for the object")
    transfers: List[Tuple[bytes, bytes]] = []
    rounds = 0
    pending = list(targets)
    while pending:
        rounds += 1
        wave = []
        # every current holder feeds one new target this round
        for src in list(sources):
            if not pending:
                break
            dst = pending.pop(0)
            wave.append((src, dst))

        async def push(src, dst):
            info = alive[src]
            client = await worker.pool.get(info["ip"], info["raylet_port"])
            for attempt in range(4):
                r = await client.call(
                    "PushObject",
                    {"object_id": oid.binary(), "target": dst,
                     "owner_addr": owner_addr},
                    timeout=timeout,
                )
                if r.get("ok"):
                    return dst
                # a concurrent pull/push for the same object on the target
                # is transient — let it finish and re-check
                if "progress" in str(r.get("error", "")) or "transfer" in str(
                    r.get("error", "")
                ):
                    await asyncio.sleep(0.5 * (attempt + 1))
                    continue
                break
            raise RuntimeError(
                f"push {src.hex()[:8]}->{dst.hex()[:8]} failed: "
                f"{r.get('error')}"
            )

        done = await asyncio.gather(*(push(s, d) for s, d in wave))
        transfers.extend(wave)
        sources.extend(done)
    return {
        "rounds": rounds,
        "transfers": [(s, d) for s, d in transfers],
        "nodes": sorted(set(sources)),
    }
