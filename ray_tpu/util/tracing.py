"""Distributed tracing: spans that follow tasks/actors across processes.

Counterpart of the reference's opt-in tracing
(reference: python/ray/util/tracing/tracing_helper.py — remote calls are
wrapped to open spans and the context travels in an injected
``_ray_trace_ctx`` kwarg). Here tracing is runtime-native: when enabled,
task specs carry a ``trace_ctx`` field, the executor restores it before
user code runs, and spans are buffered with the task events and flushed to
the GCS — so ``ray_tpu.timeline()`` renders user spans and task spans in
one Chrome trace, correlated by trace id. No OpenTelemetry dependency; the
span model (trace_id / span_id / parent) is wire-compatible with it.

Usage:
    from ray_tpu.util import tracing
    tracing.enable()              # on the driver, before submitting work
    with tracing.span("preprocess", {"rows": 100}):
        ...
"""

from __future__ import annotations

import contextlib
import contextvars
import time
import uuid
from typing import Dict, Optional

_ENABLED_KV_KEY = b"__tracing_enabled__"

# Current trace context in this thread/task: {"trace_id", "span_id"}.
_current: contextvars.ContextVar[Optional[Dict[str, str]]] = contextvars.ContextVar(
    "rtpu_trace_ctx", default=None
)
_local_enabled: Optional[bool] = None  # cached flag; re-read after TTL
_checked_at: float = 0.0
_CACHE_TTL_S = 5.0


def _worker():
    from ray_tpu._private import worker as worker_mod

    return worker_mod.global_worker


def enable():
    """Turn tracing on cluster-wide (flag in the GCS KV; every process
    re-reads it within the cache TTL)."""
    global _local_enabled, _checked_at
    w = _worker()
    if w is None:
        raise RuntimeError("ray_tpu is not initialized")
    w.gcs.kv_put("", _ENABLED_KV_KEY, b"1")
    _local_enabled, _checked_at = True, time.time()


def disable():
    global _local_enabled, _checked_at
    w = _worker()
    if w is not None:
        w.gcs.kv_del("", _ENABLED_KV_KEY)
    _local_enabled, _checked_at = False, time.time()


def is_enabled() -> bool:
    """TTL-cached KV read: both enable() AND disable() propagate to every
    process within ~_CACHE_TTL_S, not just until the first cache fill."""
    global _local_enabled, _checked_at
    now = time.time()
    if _local_enabled is not None and now - _checked_at < _CACHE_TTL_S:
        return _local_enabled
    w = _worker()
    if w is None:
        return False
    try:
        _local_enabled = bool(w.gcs.kv_exists("", _ENABLED_KV_KEY))
        _checked_at = now
    except Exception:
        return bool(_local_enabled)
    return _local_enabled


def _mark_enabled():
    """Executor-side fast path: a spec carrying trace_ctx (its ``enabled``
    bit) proves tracing was on at submission — adopt that immediately
    instead of waiting out the KV cache TTL, so a fresh worker's first
    task records its spans from the first instruction."""
    global _local_enabled, _checked_at
    _local_enabled, _checked_at = True, time.time()


def current_context() -> Optional[Dict[str, str]]:
    return _current.get()

def set_context(ctx: Optional[Dict[str, str]]):
    _current.set(ctx)


def new_context() -> Dict[str, str]:
    return {"trace_id": uuid.uuid4().hex, "span_id": uuid.uuid4().hex[:16]}


def context_for_spec() -> Optional[Dict[str, str]]:
    """Called at task submission: the ctx to embed in the spec (the current
    span becomes the remote task's parent). A submission with no open span
    roots a fresh one-off trace — it is NOT installed as the caller's
    context, so unrelated submissions don't collapse into one giant trace
    hanging off a never-recorded synthetic parent.

    The ctx carries an explicit ``enabled`` bit: the executing worker
    treats a spec-borne context as proof tracing is on and marks its local
    cache (``_mark_enabled``) instead of waiting out the GCS-KV cache TTL —
    without it, a freshly started worker (or one holding a stale
    disabled-cache) silently dropped the task's early spans for up to
    ``_CACHE_TTL_S`` seconds."""
    if not is_enabled():
        return None
    ctx = _current.get()
    if ctx is None:
        ctx = new_context()
    return {**ctx, "enabled": True}


@contextlib.contextmanager
def span(name: str, attributes: Optional[dict] = None):
    """Record a named span; nests under the current task/span context."""
    if not is_enabled():
        yield None
        return
    parent = _current.get() or new_context()
    ctx = {
        "trace_id": parent["trace_id"],
        "span_id": uuid.uuid4().hex[:16],
        "parent_span_id": parent.get("span_id"),
    }
    token = _current.set(ctx)
    start = time.time()
    error = ""
    try:
        yield ctx
    except BaseException as e:
        error = repr(e)[:200]
        raise
    finally:
        end = time.time()
        _current.reset(token)
        w = _worker()
        if w is not None:
            w.task_events.record_span(
                name, start, end, ctx, attributes or {}, error
            )
        _export_span(name, start, end, ctx, attributes or {}, error)


# ------------------------------------------------------------ span export
# Pluggable exporter seam (reference: util/tracing/tracing_helper.py wires
# OpenTelemetry when installed). The runtime-native sink (task events →
# timeline) always records; an exporter additionally receives each
# finished span as a dict — set_span_exporter(fn) for custom sinks, or
# enable_otel_export() to bridge into an installed opentelemetry SDK.

_exporter = None


def set_span_exporter(fn) -> None:
    """fn({name, start, end, trace_id, span_id, parent_span_id,
    attributes, error}) called per finished span in-process."""
    global _exporter
    _exporter = fn


def _export_span(name, start, end, ctx, attributes, error):
    if _exporter is None:
        return
    try:
        _exporter({
            "name": name, "start": start, "end": end,
            "trace_id": ctx.get("trace_id"),
            "span_id": ctx.get("span_id"),
            "parent_span_id": ctx.get("parent_span_id"),
            "attributes": attributes, "error": error,
        })
    except Exception:
        pass  # an exporter bug must never fail user code


def enable_otel_export(tracer_name: str = "ray_tpu") -> bool:
    """Bridge spans into an installed OpenTelemetry SDK (no-op False when
    opentelemetry is absent — the framework carries no hard dependency)."""
    try:
        from opentelemetry import trace as otel_trace
    except ImportError:
        return False
    tracer = otel_trace.get_tracer(tracer_name)

    def export(span_dict):
        otel_span = tracer.start_span(
            span_dict["name"],
            start_time=int(span_dict["start"] * 1e9),
            attributes={
                **{str(k): str(v)
                   for k, v in span_dict["attributes"].items()},
                "rtpu.trace_id": span_dict["trace_id"] or "",
                "rtpu.parent_span_id": span_dict["parent_span_id"] or "",
            },
        )
        if span_dict["error"]:
            otel_span.set_attribute("error", span_dict["error"])
        otel_span.end(end_time=int(span_dict["end"] * 1e9))

    set_span_exporter(export)
    return True
