"""User-defined application metrics: Counter, Gauge, Histogram.

Counterpart of ``ray.util.metrics`` (reference: python/ray/util/metrics.py:19).
Metric updates are recorded in-process and pushed to the GCS with the
periodic task-event flush; the GCS aggregates them (summing counters,
last-write gauges, bucket-merging histograms) and exports everything on its
Prometheus /metrics endpoint.

Metric-name stability contract
------------------------------
The framework's own workload series are a public interface: dashboards,
alerts and the ``/api/train`` / ``/api/serve`` summaries key on these exact
names and label keys, so renaming or re-labeling any of them is a breaking
change (add new series instead). The stable set:

  training (train/_telemetry.py, labels: run, +WorkerId/JobId at flush)
    ray_tpu_train_step_seconds         histogram, wall time per step
    ray_tpu_train_steps_total          counter
    ray_tpu_train_tokens_per_second    gauge
    ray_tpu_train_examples_per_second  gauge
    ray_tpu_train_mfu_ratio            gauge, 0-1
    ray_tpu_train_goodput_ratio        gauge, 0-1
    ray_tpu_train_compile_seconds      gauge, cumulative
    ray_tpu_train_last_step_seconds    gauge (driver-side re-publish)
    ray_tpu_train_hbm_bytes_in_use     gauge, labels +device (TPU only)

  serving (serve/_replica.py + serve/_handle.py, labels: deployment
  [, replica])
    ray_tpu_serve_requests_total                 counter
    ray_tpu_serve_request_errors_total           counter
    ray_tpu_serve_inflight_requests              gauge
    ray_tpu_serve_queue_depth                    gauge
    ray_tpu_serve_request_latency_seconds        histogram (replica-side)
    ray_tpu_serve_handle_latency_seconds         histogram (caller-side)
    ray_tpu_serve_handle_requests_total          counter

  llm serving (serve/llm/engine.py, labels: deployment, replica)
    ray_tpu_llm_tokens_per_s           gauge, generated tokens/s (EMA
                                       over engine steps)
    ray_tpu_llm_kv_utilization         gauge, 0-1 fraction of paged KV
                                       blocks in use
    ray_tpu_llm_batch_size             gauge, sequences in the last
                                       engine step
    ray_tpu_llm_preemptions_total      counter, sequences requeued on KV
                                       exhaustion
    ray_tpu_llm_prefix_hit_rate        gauge, 0-1 cumulative fraction of
                                       looked-up prompt tokens served
                                       from the shared-prefix KV index
                                       (only published with
                                       RTPU_llm_prefix_cache on)
    ray_tpu_llm_spec_acceptance        gauge, 0-1 cumulative fraction of
                                       proposed draft tokens the target
                                       model accepted (only published
                                       when a draft model is loaded)

  profiling plane (_private/watchdog.py, labels: trigger — the incident
  kind or trigger that caused the capture: slow_step, stuck_task, ...)
    ray_tpu_profile_captures_total               counter, automatic
                                                 cluster-profile captures

  perf regression plane (_private/perf_gate.py + _private/watchdog.py)
    ray_tpu_perf_regressions_total     counter, labels: metric — gate
                                       comparisons landing beyond the
                                       noise band (perf check/compare)
    ray_tpu_perf_gate_ratio            gauge, labels: metric — latest
                                       current/baseline ratio per metric
    ray_tpu_perf_compile_storms_total  counter — jit_cache_miss_storm
                                       incidents raised by the watchdog

  chaos / robustness plane (_private/chaos.py + serve failover paths)
    ray_tpu_chaos_injections_total     counter, labels: site, action —
                                       faults fired by the chaos plane
                                       (zero unless RTPU_chaos_plan is
                                       armed)
    ray_tpu_serve_failovers_total      counter, labels: deployment —
                                       mid-stream llm failovers (the
                                       remaining generation resubmitted
                                       to a surviving replica) plus
                                       ActorDiedError retries of
                                       idempotent DeploymentHandle calls

  memory observability plane (raylet _collect_metrics, labels: node)
    ray_tpu_object_store_pinned_bytes  gauge — bytes held by pinned
                                       primary copies in this node's
                                       plasma store
    ray_tpu_object_store_leaked_bytes  gauge — bytes in primaries the
                                       leak detector confirmed have no
                                       live owner reference (two-sweep
                                       cross-check)
    ray_tpu_memory_rss_bytes           gauge, labels +role
                                       (raylet|worker|agent) — resident
                                       set size per process role on the
                                       node (worker = sum over workers)

  node system series (raylet _collect_metrics, labels: node unless noted
  — the Grafana cluster panels and `ray-tpu status` key on these)
    ray_tpu_node_resource_total        gauge, labels +resource
    ray_tpu_node_resource_available    gauge, labels +resource
    ray_tpu_node_workers               gauge, labels +state (idle|leased)
    ray_tpu_node_leases                gauge, outstanding worker leases
    ray_tpu_node_pg_bundles            gauge, placed placement-group
                                       bundles
    ray_tpu_node_cpu_percent           gauge
    ray_tpu_node_mem_used_bytes        gauge
    ray_tpu_node_mem_total_bytes       gauge
    ray_tpu_object_store_used_bytes    gauge
    ray_tpu_object_store_capacity_bytes  gauge
    ray_tpu_object_store_num_objects   gauge
    ray_tpu_object_store_evicted_bytes gauge, cumulative
    ray_tpu_spilled_objects            gauge, objects currently on disk
    ray_tpu_spilled_bytes              gauge, bytes currently on disk
    ray_tpu_pulls_in_flight            gauge
    ray_tpu_worker_rss_bytes           gauge, labels +pid

  GCS system series (gcs/server.py _collect_metrics)
    ray_tpu_gcs_nodes                  gauge, labels: state
    ray_tpu_gcs_actors                 gauge, labels: state
    ray_tpu_gcs_placement_groups       gauge, labels: state
    ray_tpu_gcs_jobs                   gauge, labels: state
    ray_tpu_gcs_task_events_buffered   gauge
    ray_tpu_gcs_incidents_open         gauge
    ray_tpu_gcs_uptime_seconds         gauge

  dashboard-agent host series (dashboard/agent.py, labels: node)
    ray_tpu_agent_cpu_percent          gauge
    ray_tpu_agent_mem_used_bytes       gauge
    ray_tpu_agent_mem_total_bytes      gauge
    ray_tpu_agent_uptime_seconds       gauge
    ray_tpu_agent_disk_used_bytes      gauge
    ray_tpu_agent_worker_rss_bytes     gauge, labels +pid

The RTPU_profile_* / RTPU_device_trace_steps / RTPU_perf_* /
RTPU_memory_* / RTPU_llm_* / RTPU_chaos_* / RTPU_serve_failover_* config
flags are likewise a stability contract — see the profiling-plane,
perf-regression-plane, memory-observability-plane, serve.llm and
chaos-plane sections of ``ray_tpu/_private/config.py``.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

_lock = threading.Lock()
# (name, frozenset(label items)) -> record dict
_records: Dict[Tuple[str, frozenset], dict] = {}


def _record(kind: str, name: str, help_: str, labels: Dict[str, str], **kw):
    key = (name, frozenset(labels.items()))
    with _lock:
        rec = _records.get(key)
        if rec is None:
            rec = {
                "kind": kind,
                "name": name,
                "help": help_,
                "labels": dict(labels),
                "value": 0.0,
                "buckets": {},  # boundary -> count (histogram)
                "count": 0,
                "sum": 0.0,
            }
            _records[key] = rec
        return rec


def drain_records() -> List[dict]:
    """Called by the worker's flush loop; returns a snapshot (counters and
    histograms are cumulative deltas since the last drain)."""
    with _lock:
        out = []
        for rec in _records.values():
            snap = {k: (dict(v) if isinstance(v, dict) else v) for k, v in rec.items()}
            out.append(snap)
            if rec["kind"] in ("counter", "histogram"):
                rec["value"] = 0.0
                rec["buckets"] = {}
                rec["count"] = 0
                rec["sum"] = 0.0
        return [s for s in out if s["kind"] == "gauge" or s["count"] or s["value"]]


def restore_records(records: List[dict]) -> None:
    """Re-merge drained deltas after a failed flush so counter/histogram
    increments survive a GCS outage instead of being silently lost."""
    with _lock:
        for snap in records:
            # The flush stamps WorkerId/JobId; strip them to match local keys.
            labels = {
                k: v
                for k, v in snap.get("labels", {}).items()
                if k not in ("WorkerId", "JobId")
            }
            key = (snap["name"], frozenset(labels.items()))
            rec = _records.get(key)
            if rec is None or rec["kind"] != snap["kind"]:
                continue
            if snap["kind"] in ("counter", "histogram"):
                rec["value"] += snap.get("value", 0.0)
                for b, c in snap.get("buckets", {}).items():
                    rec["buckets"][b] = rec["buckets"].get(b, 0) + c
                rec["count"] += snap.get("count", 0)
                rec["sum"] += snap.get("sum", 0.0)


class _Metric:
    def __init__(self, name: str, description: str = "", tag_keys: Sequence[str] = ()):
        if not name:
            raise ValueError("metric name is required")
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _tags(self, tags: Optional[Dict[str, str]]) -> Dict[str, str]:
        merged = dict(self._default_tags)
        if tags:
            merged.update(tags)
        extra = set(merged) - set(self._tag_keys)
        if extra:
            raise ValueError(
                f"tag(s) {sorted(extra)} not declared in tag_keys={self._tag_keys}"
            )
        return merged


class Counter(_Metric):
    """Monotonically increasing value (reference: util/metrics.py Counter)."""

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        if value < 0:
            raise ValueError("Counter.inc() requires value >= 0")
        rec = _record("counter", self._name, self._description, self._tags(tags))
        with _lock:
            rec["value"] += value
            rec["count"] += 1


class Gauge(_Metric):
    """Last-set value."""

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        rec = _record("gauge", self._name, self._description, self._tags(tags))
        with _lock:
            rec["value"] = float(value)
            rec["count"] += 1


class Histogram(_Metric):
    """Bucketed observations."""

    def __init__(
        self,
        name: str,
        description: str = "",
        boundaries: Sequence[float] = (),
        tag_keys: Sequence[str] = (),
    ):
        super().__init__(name, description, tag_keys)
        if not boundaries:
            raise ValueError("Histogram requires bucket boundaries")
        self._boundaries = sorted(float(b) for b in boundaries)

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        rec = _record("histogram", self._name, self._description, self._tags(tags))
        with _lock:
            rec.setdefault("boundaries", self._boundaries)
            for b in self._boundaries:
                if value <= b:
                    key = str(b)
                    break
            else:
                key = "+Inf"  # above the largest boundary
            rec["buckets"][key] = rec["buckets"].get(key, 0) + 1
            rec["count"] += 1
            rec["sum"] += float(value)
