"""Open-loop arrival load generator for the chaos/scenario suite.

Closed-loop load tests (fire the next request when the previous one
returns) suffer **coordinated omission**: a stalled server pauses the
generator too, so the stall never shows up in the latency histogram.
This generator is open-loop — the arrival schedule is computed up front
(fixed-rate or seeded-Poisson) and every request fires at its scheduled
wall-clock time on its own thread, whether or not earlier requests have
completed. Latency is measured from the SCHEDULED arrival, so queueing
delay during a stall or a replica-kill storm lands in the tail where it
belongs (see Tene, "How NOT to Measure Latency").

    from ray_tpu.util.loadgen import OpenLoopLoadGen

    gen = OpenLoopLoadGen(lambda i: client.call("app", i),
                          rate_hz=50, duration_s=10, arrival="poisson",
                          seed=7)
    report = gen.run()
    assert report["p99_s"] < 0.5 and not report["errors"]

The schedule is deterministic given (rate_hz, duration_s, arrival, seed),
so a chaos scenario replayed with the same seed sees the same offered
load.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

__all__ = ["OpenLoopLoadGen"]


class OpenLoopLoadGen:
    """Fire ``fn(i)`` at precomputed arrival times; collect per-request
    records ``{i, scheduled, start, end, ok, error, result}``."""

    def __init__(self, fn: Callable[[int], Any], *, rate_hz: float,
                 duration_s: float, arrival: str = "uniform", seed: int = 0,
                 max_outstanding: int = 512):
        if rate_hz <= 0 or duration_s <= 0:
            raise ValueError("rate_hz and duration_s must be positive")
        self.fn = fn
        self.records: List[dict] = []
        self._lock = threading.Lock()
        self._offsets = self._schedule(rate_hz, duration_s, arrival, seed)
        # backstop against unbounded thread growth when the system under
        # test stops answering entirely; hitting it is itself recorded
        # (shed=True) so the report can't silently under-count load
        self._max_outstanding = max_outstanding
        self.shed = 0

    @staticmethod
    def _schedule(rate_hz: float, duration_s: float, arrival: str,
                  seed: int) -> List[float]:
        if arrival == "uniform":
            n = int(rate_hz * duration_s)
            return [i / rate_hz for i in range(n)]
        if arrival == "poisson":
            import random

            rng = random.Random(seed)
            offsets, t = [], 0.0
            while True:
                t += rng.expovariate(rate_hz)
                if t >= duration_s:
                    return offsets
                offsets.append(t)
        raise ValueError(f"unknown arrival process {arrival!r}")

    def _fire(self, i: int, scheduled_abs: float):
        start = time.perf_counter()
        ok, err, result = True, "", None
        try:
            result = self.fn(i)
        except Exception as e:  # noqa: BLE001 — recorded, not raised
            ok, err = False, f"{type(e).__name__}: {e}"
        end = time.perf_counter()
        with self._lock:
            self.records.append({
                "i": i, "scheduled": scheduled_abs, "start": start,
                "end": end, "ok": ok, "error": err, "result": result,
            })

    def run(self, join_timeout_s: float = 120.0) -> Dict[str, Any]:
        """Blocking: plays the whole schedule, joins the stragglers, and
        returns :meth:`report`."""
        threads: List[threading.Thread] = []
        t0 = time.perf_counter()
        for i, off in enumerate(self._offsets):
            now = time.perf_counter()
            if t0 + off > now:
                time.sleep(t0 + off - now)
            live = sum(1 for t in threads if t.is_alive())
            if live >= self._max_outstanding:
                self.shed += 1
                continue
            th = threading.Thread(target=self._fire, args=(i, t0 + off),
                                  daemon=True)
            th.start()
            threads.append(th)
        deadline = time.perf_counter() + join_timeout_s
        for th in threads:
            th.join(timeout=max(0.0, deadline - time.perf_counter()))
        return self.report()

    @staticmethod
    def _pct(sorted_vals: List[float], q: float) -> Optional[float]:
        if not sorted_vals:
            return None
        return sorted_vals[min(len(sorted_vals) - 1,
                               int(q * len(sorted_vals)))]

    def report(self) -> Dict[str, Any]:
        with self._lock:
            recs = list(self.records)
        # latency from the SCHEDULED arrival (not the thread's start):
        # this is where coordinated omission would otherwise hide
        lats = sorted(r["end"] - r["scheduled"] for r in recs if r["ok"])
        errors: Dict[str, int] = {}
        for r in recs:
            if not r["ok"]:
                key = r["error"].split(":", 1)[0]
                errors[key] = errors.get(key, 0) + 1
        return {
            "offered": len(self._offsets),
            "completed": len(lats),
            "failed": len(recs) - len(lats),
            "shed": self.shed,
            "errors": errors,
            "p50_s": self._pct(lats, 0.50),
            "p95_s": self._pct(lats, 0.95),
            "p99_s": self._pct(lats, 0.99),
            "max_s": lats[-1] if lats else None,
        }
