"""Pluggable search algorithms (reference: tune/search/ — Searcher API,
searcher.py suggest/on_trial_complete, basic_variant.py, and the
hyperopt/optuna integrations' role).

The integrations themselves wrap third-party libraries; here the framework
SHAPE is the point: `Searcher` is the plugin seam (suggest pulls the next
config when a trial slot frees; completions feed back), with three
built-ins — BasicVariantGenerator (grid/random, the default),
TPESearcher (a native tree-structured Parzen estimator over the Domain
space — the hyperopt algorithm, reimplemented), and ConcurrencyLimiter.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional

from ray_tpu.tune.search_space import (
    Categorical,
    Domain,
    GridSearch,
    LogUniform,
    Randint,
    Uniform,
    generate_variants,
)


class Searcher:
    """Suggestion algorithm plugin. The controller calls suggest(trial_id)
    when it can start a trial (None = nothing to suggest right now; the
    search ends when nothing is running and suggest stays None), and
    feeds results back through on_trial_result/on_trial_complete."""

    def __init__(self, metric: Optional[str] = None, mode: str = "max"):
        self.metric = metric
        self.mode = mode

    def set_search_properties(self, metric: Optional[str], mode: str,
                              param_space: Dict[str, Any]) -> None:
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode
        self.param_space = param_space

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]) -> None:
        pass

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        pass


class BasicVariantGenerator(Searcher):
    """Grid/random expansion, served lazily (reference:
    tune/search/basic_variant.py)."""

    def __init__(self, seed: int = 0):
        super().__init__()
        self._seed = seed
        self._variants: Optional[List[dict]] = None
        self._i = 0
        self.num_samples = 1
        # grid expansion can exceed num_samples (num_samples x |grid|);
        # the controller raises its trial cap to this once known
        self.total_variants = 0

    def set_search_properties(self, metric, mode, param_space):
        super().set_search_properties(metric, mode, param_space)
        self._variants = None

    def suggest(self, trial_id):
        if self._variants is None:
            self._variants = generate_variants(
                self.param_space, self.num_samples, seed=self._seed
            )
            self.total_variants = len(self._variants)
        if self._i >= len(self._variants):
            return None
        cfg = self._variants[self._i]
        self._i += 1
        return cfg


class TPESearcher(Searcher):
    """Native tree-structured Parzen estimator (Bergstra et al. 2011 — the
    algorithm behind hyperopt, reimplemented): completed trials split into
    good (top gamma) and bad sets; numeric dims get per-dim Gaussian
    Parzen densities l(x) (good) and g(x) (bad), categoricals get
    smoothed count distributions; candidates drawn from l, the one
    maximizing l/g wins. Grid dims are unsupported (use
    BasicVariantGenerator for grids)."""

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 n_initial: int = 8, gamma: float = 0.25,
                 n_candidates: int = 24, seed: int = 0):
        super().__init__(metric, mode)
        self.n_initial = n_initial
        self.gamma = gamma
        self.n_candidates = n_candidates
        self._rng = random.Random(seed)
        self._suggested = 0
        self._history: List[tuple] = []  # (config, score)
        self._live: Dict[str, dict] = {}

    def set_search_properties(self, metric, mode, param_space):
        super().set_search_properties(metric, mode, param_space)
        for k, v in param_space.items():
            if isinstance(v, GridSearch):
                raise ValueError(
                    "TPESearcher does not support grid_search dims; "
                    "use BasicVariantGenerator"
                )

    # ------------------------------------------------------------- internals

    def _sample_random(self) -> dict:
        cfg = {}
        for k, v in self.param_space.items():
            cfg[k] = v.sample(self._rng) if isinstance(v, Domain) else v
        return cfg

    @staticmethod
    def _to_unit(dom, x) -> float:
        if isinstance(dom, LogUniform):
            # LogUniform stores log-space bounds (_lo/_hi)
            return (math.log(x) - dom._lo) / max(1e-12, dom._hi - dom._lo)
        if isinstance(dom, (Uniform, Randint)):
            return (x - dom.low) / max(1e-12, dom.high - dom.low)
        raise TypeError(dom)

    @staticmethod
    def _from_unit(dom, u: float):
        u = min(1.0, max(0.0, u))
        if isinstance(dom, LogUniform):
            return math.exp(dom._lo + u * (dom._hi - dom._lo))
        if isinstance(dom, Randint):
            return min(dom.high - 1, int(dom.low + u * (dom.high - dom.low)))
        return dom.low + u * (dom.high - dom.low)

    @staticmethod
    def _parzen(u: float, centers: List[float], bw: float) -> float:
        if not centers:
            return 1.0
        s = sum(
            math.exp(-0.5 * ((u - c) / bw) ** 2) for c in centers
        )
        return s / (len(centers) * bw) + 1e-12

    def _suggest_tpe(self) -> dict:
        scored = sorted(
            self._history, key=lambda t: t[1],
            reverse=(self.mode == "max"),
        )
        n_good = max(1, int(len(scored) * self.gamma))
        good = [c for c, _ in scored[:n_good]]
        bad = [c for c, _ in scored[n_good:]] or good
        bw = max(0.08, 1.0 / max(1, len(good)))

        best_cfg, best_ratio = None, -math.inf
        for _ in range(self.n_candidates):
            cfg = {}
            log_ratio = 0.0
            for k, dom in self.param_space.items():
                if not isinstance(dom, Domain):
                    cfg[k] = dom
                    continue
                if isinstance(dom, Categorical):
                    counts_g = {c: 1.0 for c in dom.categories}
                    counts_b = {c: 1.0 for c in dom.categories}
                    for g in good:
                        counts_g[g[k]] = counts_g.get(g[k], 1.0) + 1.0
                    for b in bad:
                        counts_b[b[k]] = counts_b.get(b[k], 1.0) + 1.0
                    total_g = sum(counts_g.values())
                    cats, weights = zip(*counts_g.items())
                    choice = self._rng.choices(
                        cats, [w / total_g for w in weights]
                    )[0]
                    cfg[k] = choice
                    pg = counts_g[choice] / total_g
                    pb = counts_b[choice] / sum(counts_b.values())
                    log_ratio += math.log(pg / pb)
                else:
                    centers = [self._to_unit(dom, g[k]) for g in good]
                    centers_b = [self._to_unit(dom, b[k]) for b in bad]
                    # draw from l: pick a good center, add bandwidth noise
                    c = self._rng.choice(centers) if centers else self._rng.random()
                    u = c + self._rng.gauss(0.0, bw)
                    cfg[k] = self._from_unit(dom, u)
                    u = self._to_unit(dom, cfg[k])
                    log_ratio += math.log(
                        self._parzen(u, centers, bw)
                        / self._parzen(u, centers_b, bw)
                    )
            if log_ratio > best_ratio:
                best_cfg, best_ratio = cfg, log_ratio
        return best_cfg

    # ------------------------------------------------------------- interface

    def suggest(self, trial_id):
        if self._suggested < self.n_initial or len(self._history) < 2:
            cfg = self._sample_random()
        else:
            cfg = self._suggest_tpe()
        self._suggested += 1
        self._live[trial_id] = cfg
        return cfg

    def on_trial_complete(self, trial_id, result=None, error=False):
        cfg = self._live.pop(trial_id, None)
        if cfg is None or error or not result or self.metric not in result:
            return
        self._history.append((cfg, float(result[self.metric])))


class ConcurrencyLimiter(Searcher):
    """Cap in-flight suggestions (reference: tune/search/
    concurrency_limiter.py): sequential algorithms like TPE degrade to
    random search if every trial launches before any result lands."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        super().__init__(searcher.metric, searcher.mode)
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set = set()

    @property
    def total_variants(self) -> int:
        # grid-expansion totals must see through the wrapper or the
        # controller under-counts the trial cap
        return getattr(self.searcher, "total_variants", 0)

    @property
    def num_samples(self) -> int:
        return getattr(self.searcher, "num_samples", 1)

    @num_samples.setter
    def num_samples(self, v: int):
        if hasattr(self.searcher, "num_samples"):
            self.searcher.num_samples = v

    def set_search_properties(self, metric, mode, param_space):
        super().set_search_properties(metric, mode, param_space)
        self.searcher.set_search_properties(metric, mode, param_space)

    def suggest(self, trial_id):
        if len(self._live) >= self.max_concurrent:
            return None  # wait: slots free on completion
        cfg = self.searcher.suggest(trial_id)
        if cfg is not None:
            self._live.add(trial_id)
        return cfg

    def on_trial_result(self, trial_id, result):
        self.searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id, result=None, error=False):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)
