"""ray_tpu.tune — hyperparameter tuning over trial actors.

API parity with the reference's ray.tune essentials: Tuner/TuneConfig,
search-space primitives, ASHA + PBT schedulers, tune.report/get_checkpoint
(shared with ray_tpu.train's session, as in the reference's unified session).
"""

from ray_tpu.train._session import get_checkpoint, report  # noqa: F401
from ray_tpu.tune.result_grid import ResultGrid, TrialResult  # noqa: F401
from ray_tpu.tune.search import (  # noqa: F401
    BasicVariantGenerator,
    ConcurrencyLimiter,
    Searcher,
    TPESearcher,
)
from ray_tpu.tune.schedulers import (  # noqa: F401
    ASHAScheduler,
    AsyncHyperBandScheduler,
    FIFOScheduler,
    HyperBandScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
)
from ray_tpu.tune.search_space import (  # noqa: F401
    choice,
    grid_search,
    loguniform,
    randint,
    uniform,
)
from ray_tpu.tune.tuner import (  # noqa: F401
    TuneConfig,
    Tuner,
    with_parameters,
    with_resources,
)
