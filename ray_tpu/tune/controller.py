"""Tune controller: the event loop driving trial actors.

Reference call stack (SURVEY.md §3.4): Tuner.fit (tune/tuner.py:44) →
tune.run → TuneController event loop (tune/execution/tune_controller.py:68)
driving trial actors. Here each trial is one `_TrainWorker` actor (the same
actor class Train's WorkerGroup uses — a trial IS a 1-worker group, sharing
the session report/ack protocol), and the loop multiplexes trials with
ray_tpu.wait over their outstanding next_report calls.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train._session import TrainContext
from ray_tpu.train._worker_group import _TrainWorker, _to_actor_options
from ray_tpu.tune import schedulers as sched_mod
from ray_tpu.tune.schedulers import CONTINUE, EXPLOIT, FIFOScheduler, STOP

logger = logging.getLogger("ray_tpu.tune")

PENDING = "PENDING"
RUNNING = "RUNNING"
TERMINATED = "TERMINATED"
ERRORED = "ERRORED"


class Trial:
    def __init__(self, trial_id: str, config: Dict[str, Any], local_dir: str):
        self.id = trial_id
        self.config = config
        self.local_dir = local_dir  # <experiment>/<trial_id>
        self.state = PENDING
        self.actor = None
        self.last_result: Optional[Dict[str, Any]] = None
        self.metrics_history: List[Dict[str, Any]] = []
        self.iteration = 0
        self.latest_checkpoint: Optional[str] = None
        self.error: Optional[str] = None
        self.restore_from: Optional[str] = None
        # PBT handshake
        self.exploit_from: Optional["Trial"] = None
        self.exploit_config: Optional[Dict[str, Any]] = None
        self._ckpt_index = 0

    def snapshot(self) -> dict:
        # Persist only the JSON-safe config entries; record which keys were
        # dropped so restore() can re-inject them (e.g. __trainer__) instead
        # of crashing on a repr string.
        cfg, dropped = {}, []
        for k, v in (self.config or {}).items():
            try:
                json.dumps(v)
                cfg[k] = v
            except (TypeError, ValueError):
                dropped.append(k)
        return {
            "id": self.id,
            "config": cfg,
            "config_dropped_keys": dropped,
            "state": self.state,
            "iteration": self.iteration,
            "latest_checkpoint": self.latest_checkpoint,
            "last_result": _jsonable(self.last_result),
            "error": self.error,
        }


def _jsonable(v):
    try:
        json.dumps(v)
        return v
    except (TypeError, ValueError):
        return repr(v)


class TuneController:
    def __init__(
        self,
        trial_fn: Callable,
        configs: List[Dict[str, Any]],
        experiment_dir: str,
        *,
        scheduler: Optional[FIFOScheduler] = None,
        stop: Optional[Dict[str, Any]] = None,
        resources_per_trial: Optional[Dict[str, float]] = None,
        max_concurrent: int = 0,
        restored_trials: Optional[List[Trial]] = None,
        searcher=None,
        num_samples: int = 0,
    ):
        self.trial_fn = trial_fn
        # searcher mode: trials are suggested lazily as slots free, and
        # completions feed back (reference: tune/search Searcher protocol)
        self.searcher = searcher
        self.num_samples = num_samples
        self.experiment_dir = experiment_dir
        self.scheduler = scheduler or FIFOScheduler()
        self.stop_criteria = stop or {}
        self.resources = resources_per_trial or {"CPU": 1}
        self.max_concurrent = max_concurrent
        if restored_trials is not None:
            self.trials = restored_trials
        else:
            self.trials = [
                Trial(f"trial_{i:05d}", cfg,
                      os.path.join(experiment_dir, f"trial_{i:05d}"))
                for i, cfg in enumerate(configs)
            ]
        self._report_refs: Dict[Any, Trial] = {}

    # --------------------------------------------------------------- helpers

    def live_trials(self) -> List[Trial]:
        return [t for t in self.trials if t.state == RUNNING]

    def _start_trial(self, trial: Trial):
        os.makedirs(trial.local_dir, exist_ok=True)
        actor_cls = ray_tpu.remote(_TrainWorker)
        trial.actor = actor_cls.options(
            **_to_actor_options(dict(self.resources))
        ).remote(0, {})
        ctx = TrainContext(
            world_rank=0, world_size=1, local_rank=0, local_world_size=1,
            node_ip="", experiment_name=trial.id,
        )
        restore = None
        if trial.restore_from:
            restore = Checkpoint(trial.restore_from)
            trial.restore_from = None
        trial.actor.start_run.remote(
            self.trial_fn, trial.config, ctx, restore
        )
        trial.state = RUNNING
        ref = trial.actor.next_report.remote()
        self._report_refs[ref] = trial

    def _requeue(self, trial: Trial):
        """Ack the consumed report and arm the next round."""
        trial.actor.ack_report.remote()
        ref = trial.actor.next_report.remote()
        self._report_refs[ref] = trial

    def _stop_trial(self, trial: Trial, state: str):
        trial.state = state
        # Drop outstanding report refs for the old actor: a killed actor's
        # ref resolves to ActorDiedError, which must not be mistaken for a
        # failure of the restarted trial (PBT exploit path).
        for ref, t in list(self._report_refs.items()):
            if t is trial:
                del self._report_refs[ref]
        if trial.actor is not None:
            try:
                ray_tpu.kill(trial.actor)
            except Exception:
                pass
            trial.actor = None

    def _persist_checkpoint(self, trial: Trial, worker_path: str) -> str:
        from ray_tpu.train._storage import is_remote_uri

        if is_remote_uri(worker_path):
            # already durable in URI storage (the trainer's workers uploaded
            # it); record the URI instead of copying by path
            trial.latest_checkpoint = worker_path
            return worker_path
        dest = os.path.join(trial.local_dir,
                            f"checkpoint_{trial._ckpt_index:06d}")
        trial._ckpt_index += 1
        shutil.copytree(worker_path, dest, dirs_exist_ok=True)
        trial.latest_checkpoint = dest
        return dest

    def _should_stop(self, result: Dict[str, Any]) -> bool:
        for k, v in self.stop_criteria.items():
            if k in result and result[k] >= v:
                return True
        return False

    def _save_state(self):
        state = {
            "timestamp": time.time(),
            "trials": [t.snapshot() for t in self.trials],
        }
        tmp = os.path.join(self.experiment_dir, ".experiment_state.tmp")
        with open(tmp, "w") as f:
            json.dump(state, f, indent=1)
        os.replace(tmp, os.path.join(self.experiment_dir,
                                     "experiment_state.json"))

    # ------------------------------------------------------------------ run

    def run(self) -> List[Trial]:
        os.makedirs(self.experiment_dir, exist_ok=True)
        pending = [t for t in self.trials if t.state == PENDING]
        done_states = (TERMINATED, ERRORED)

        def trial_limit():
            # generators expanding grids can produce more than num_samples
            # variants (num_samples per grid point); honor their total
            return max(
                self.num_samples,
                getattr(self.searcher, "total_variants", 0) or 0,
            )

        def current_cap():
            if self.max_concurrent:
                return self.max_concurrent
            # match the non-searcher path's parallelism: a grid sweep must
            # not serialize just because it came through a searcher
            return trial_limit() if self.searcher else len(self.trials)

        def maybe_launch():
            while pending and len(self.live_trials()) < current_cap():
                self._start_trial(pending.pop(0))
            if self.searcher is None:
                return
            # caps recompute per iteration: grid totals are only known
            # after the generator's first suggest() expands the space
            while (len(self.trials) < trial_limit()
                   and len(self.live_trials()) < current_cap()):
                tid = f"trial_{len(self.trials):05d}"
                cfg = self.searcher.suggest(tid)
                if cfg is None:
                    break  # waiting on results (or exhausted)
                trial = Trial(
                    tid, cfg, os.path.join(self.experiment_dir, tid)
                )
                self.trials.append(trial)
                self._start_trial(trial)

        maybe_launch()
        self._save_state()
        try:
            while True:
                if not self._report_refs:
                    maybe_launch()
                    if not self._report_refs:
                        break
                ready, _ = ray_tpu.wait(
                    list(self._report_refs), num_returns=1, timeout=5.0
                )
                if not ready:
                    continue
                for ref in ready:
                    trial = self._report_refs.pop(ref)
                    if trial.state in done_states:
                        continue
                    try:
                        report = ray_tpu.get(ref)
                    except Exception as e:
                        trial.error = f"trial actor died: {e}"
                        self._stop_trial(trial, ERRORED)
                        if self.searcher is not None:
                            # the searcher must see EVERY terminal outcome
                            # or ConcurrencyLimiter slots leak
                            self.searcher.on_trial_complete(
                                trial.id, error=True
                            )
                        continue
                    self._handle_report(trial, report)
                maybe_launch()
                self._save_state()
        finally:
            # Never leak running trial actors, whatever takes us out.
            for t in self.live_trials():
                self._stop_trial(t, t.state)
            self._save_state()
        return self.trials

    def _handle_report(self, trial: Trial, report: dict):
        kind = report["type"]
        if kind == "finished":
            self._stop_trial(trial, TERMINATED)
            self.scheduler.on_trial_complete(self, trial, trial.last_result)
            if self.searcher is not None:
                self.searcher.on_trial_complete(trial.id, trial.last_result)
            return
        if kind == "error":
            trial.error = report.get("traceback") or report.get("error")
            self._stop_trial(trial, ERRORED)
            if self.searcher is not None:
                self.searcher.on_trial_complete(trial.id, error=True)
            return
        # a live report round
        trial.iteration += 1
        result = dict(report["metrics"])
        result.setdefault("training_iteration", trial.iteration)
        trial.last_result = result
        trial.metrics_history.append(result)
        if "checkpoint_path" in report:
            self._persist_checkpoint(trial, report["checkpoint_path"])
        if self._should_stop(result):
            decision = STOP
        else:
            try:
                decision = self.scheduler.on_trial_result(self, trial, result)
            except Exception:
                # A scheduler bug (or a report missing its metric) must not
                # abort the experiment; let the trial continue.
                logger.exception("scheduler failed on result for %s", trial.id)
                decision = CONTINUE
        if self.searcher is not None:
            self.searcher.on_trial_result(trial.id, result)
        if decision == STOP:
            self._stop_trial(trial, TERMINATED)
            self.scheduler.on_trial_complete(self, trial, result)
            if self.searcher is not None:
                self.searcher.on_trial_complete(trial.id, result)
            return
        if decision == EXPLOIT:
            self._exploit(trial)
            return
        self._requeue(trial)

    def _exploit(self, trial: Trial):
        """PBT: restart this trial from the donor's checkpoint with the
        perturbed config (reference pbt.py _exploit)."""
        donor, new_config = trial.exploit_from, trial.exploit_config
        trial.exploit_from = trial.exploit_config = None
        if donor is None or donor.latest_checkpoint is None:
            self._requeue(trial)
            return
        logger.info("PBT exploit: %s <- %s", trial.id, donor.id)
        self._stop_trial(trial, PENDING)
        trial.config = new_config
        trial.restore_from = donor.latest_checkpoint
        self._start_trial(trial)
