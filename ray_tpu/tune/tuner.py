"""Tuner: the user-facing Tune entry point (reference: tune/tuner.py:44).

`Tuner(fn_or_trainer, param_space=..., tune_config=...).fit()` expands the
search space into trials, runs them through the TuneController over trial
actors, and returns a ResultGrid. A DataParallelTrainer/JaxTrainer is a valid
trainable — its `fit()` is a 1-trial Tune run, exactly like the reference
(train/base_trainer.py:819 wraps the trainer into a Tune Trainable).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

from ray_tpu.tune.controller import (
    ERRORED,
    PENDING,
    RUNNING,
    TERMINATED,
    Trial,
    TuneController,
)
from ray_tpu.tune.result_grid import ResultGrid
from ray_tpu.tune.schedulers import FIFOScheduler
from ray_tpu.tune.search_space import generate_variants


@dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int = 0  # 0 = unbounded
    scheduler: Optional[Any] = None
    # pluggable suggestion algorithm (reference: tune/search Searcher);
    # None = grid/random expansion of param_space
    search_alg: Optional[Any] = None
    seed: int = 0


def with_resources(trainable: Callable, resources: Dict[str, float]):
    """Attach a per-trial resource request (reference: tune.with_resources)."""
    trainable._tune_resources = dict(resources)
    return trainable


def with_parameters(trainable: Callable, **kwargs):
    """Bind large objects to a trainable via the object store (reference:
    tune.with_parameters — datasets/models are put once and fetched
    zero-copy by each trial instead of being pickled into every trial's
    config)."""
    import ray_tpu
    from ray_tpu.train._trainer import DataParallelTrainer

    if isinstance(trainable, DataParallelTrainer):
        # match the reference: trainers carry their own config/datasets —
        # wrapping one would silently bypass the Tuner's trainer path
        raise ValueError(
            "tune.with_parameters() only supports function trainables; "
            "pass datasets/config to the trainer directly"
        )
    refs = {k: ray_tpu.put(v) for k, v in kwargs.items()}

    def wrapped(config):
        resolved = {k: ray_tpu.get(r) for k, r in refs.items()}
        return trainable(config, **resolved)

    wrapped.__name__ = getattr(trainable, "__name__", "trainable")
    if hasattr(trainable, "_tune_resources"):
        wrapped._tune_resources = trainable._tune_resources
    return wrapped


def _trainer_trial_fn(config):
    """Runs a DataParallelTrainer inside a trial actor, forwarding every
    inner report round to the trial's session."""
    import os as _os

    from ray_tpu import train as train_mod

    trainer = config["__trainer__"]
    overrides = {k: v for k, v in config.items() if k != "__trainer__"}
    if overrides:
        base = dict(trainer._train_config or {})
        base.update(overrides)
        trainer._train_config = base
    # Re-root this trial's trainer into a private subdir: concurrent trials
    # of one tuned trainer must not share checkpoint numbering/pruning.
    ctx = train_mod.get_context()
    trainer.experiment_dir = _os.path.join(
        trainer.experiment_dir, f"worker_of_{ctx.get_experiment_name()}"
    )

    def forward(metrics, checkpoint_path):
        ckpt = None
        if checkpoint_path:
            from ray_tpu.train._checkpoint import Checkpoint

            ckpt = Checkpoint(checkpoint_path)
        train_mod.report(metrics, checkpoint=ckpt)

    trainer._fit_direct(report_callback=forward)


class Tuner:
    def __init__(
        self,
        trainable: Any,
        *,
        param_space: Optional[Dict[str, Any]] = None,
        tune_config: Optional[TuneConfig] = None,
        run_config: Optional[Any] = None,
        _restored_trials=None,
    ):
        from ray_tpu.train._config import RunConfig
        from ray_tpu.train._trainer import DataParallelTrainer

        self._tune_config = tune_config or TuneConfig()
        self._run_config = run_config or RunConfig()
        self._is_trainer = isinstance(trainable, DataParallelTrainer)
        self._restored_trials = _restored_trials
        if self._is_trainer:
            self._trial_fn = _trainer_trial_fn
            self._resources = {"CPU": 0}  # inner worker group holds the CPUs
            base_space = dict(param_space or {})
            base_space["__trainer__"] = trainable
            self._param_space = base_space
            if run_config is None and trainable.run_config is not None:
                self._run_config = trainable.run_config
        else:
            self._trial_fn = trainable
            self._resources = getattr(trainable, "_tune_resources",
                                      {"CPU": 1})
            self._param_space = dict(param_space or {})
        name = self._run_config.name or f"tune_{int(time.time())}"
        from ray_tpu.train._storage import is_remote_uri

        storage = self._run_config.storage_path or os.path.join(
            os.path.expanduser("~"), "ray_tpu_results"
        )
        if is_remote_uri(storage):
            # URI storage is for checkpoints (uploaded worker-side by the
            # inner trainer); the tuner's own trial-state bookkeeping is
            # driver-local state and stays on the driver's disk.
            storage = os.path.join(os.path.expanduser("~"),
                                   "ray_tpu_results")
        self.experiment_dir = os.path.join(storage, name)

    # ------------------------------------------------------------------ fit

    def fit(self) -> ResultGrid:
        tc = self._tune_config
        if self._restored_trials is not None:
            controller = TuneController(
                self._trial_fn, [], self.experiment_dir,
                scheduler=tc.scheduler or FIFOScheduler(),
                resources_per_trial=self._resources,
                max_concurrent=tc.max_concurrent_trials,
                restored_trials=self._restored_trials,
            )
        elif tc.search_alg is not None:
            # lazy suggestion mode: the searcher hands out configs as trial
            # slots free and consumes completions (sequential optimization)
            space = {
                k: v for k, v in self._param_space.items()
                if k != "__trainer__"
            }
            tc.search_alg.set_search_properties(tc.metric, tc.mode, space)
            # generators that expand a static variant list need the sample
            # count (BasicVariantGenerator; custom ones may ignore it)
            if hasattr(tc.search_alg, "num_samples"):
                tc.search_alg.num_samples = tc.num_samples
            inner = getattr(tc.search_alg, "searcher", None)
            if inner is not None and hasattr(inner, "num_samples"):
                inner.num_samples = tc.num_samples
            if self._is_trainer:
                base = tc.search_alg

                class _TrainerWrap:
                    def __getattr__(self, n):
                        return getattr(base, n)

                    def suggest(self, tid):
                        cfg = base.suggest(tid)
                        if cfg is not None:
                            cfg = dict(
                                cfg,
                                __trainer__=self_outer._param_space["__trainer__"],
                            )
                        return cfg

                self_outer = self
                searcher = _TrainerWrap()
            else:
                searcher = tc.search_alg
            controller = TuneController(
                self._trial_fn, [], self.experiment_dir,
                scheduler=tc.scheduler or FIFOScheduler(),
                resources_per_trial=self._resources,
                max_concurrent=tc.max_concurrent_trials,
                searcher=searcher,
                num_samples=tc.num_samples,
            )
        else:
            configs = generate_variants(
                self._param_space, tc.num_samples, seed=tc.seed
            )
            controller = TuneController(
                self._trial_fn, configs, self.experiment_dir,
                scheduler=tc.scheduler or FIFOScheduler(),
                resources_per_trial=self._resources,
                max_concurrent=tc.max_concurrent_trials,
            )
        trials = controller.run()
        return ResultGrid(trials, self.experiment_dir)

    # -------------------------------------------------------------- restore

    @classmethod
    def can_restore(cls, path: str) -> bool:
        return os.path.exists(os.path.join(path, "experiment_state.json"))

    @classmethod
    def restore(cls, path: str, trainable: Any,
                tune_config: Optional[TuneConfig] = None) -> "Tuner":
        """Resume an interrupted experiment: finished trials keep their
        results; unfinished ones re-run from their latest checkpoint
        (reference: Tuner.restore, tune/tuner.py)."""
        import json

        from ray_tpu.train._config import RunConfig

        from ray_tpu.train._trainer import DataParallelTrainer

        with open(os.path.join(path, "experiment_state.json")) as f:
            state = json.load(f)
        is_trainer = isinstance(trainable, DataParallelTrainer)
        trials = []
        for snap in state["trials"]:
            config = dict(snap["config"])
            dropped = set(snap.get("config_dropped_keys", []))
            if is_trainer:
                config["__trainer__"] = trainable
                dropped.discard("__trainer__")
            if dropped:
                # Non-JSON config values can't be reconstructed; the trial
                # can only be kept if it already finished.
                if snap["state"] not in (TERMINATED, ERRORED):
                    snap = dict(snap, state=ERRORED)
                    snap["error"] = (
                        f"cannot restore config keys {sorted(dropped)}"
                    )
            t = Trial(snap["id"], config,
                      os.path.join(path, snap["id"]))
            t.iteration = snap.get("iteration", 0)
            t.latest_checkpoint = snap.get("latest_checkpoint")
            t.last_result = snap.get("last_result")
            t.error = snap.get("error")
            if snap["state"] in (TERMINATED, ERRORED):
                t.state = snap["state"]
            else:
                t.state = PENDING
                t.restore_from = t.latest_checkpoint
            trials.append(t)
        run_config = RunConfig(
            name=os.path.basename(path),
            storage_path=os.path.dirname(path),
        )
        return cls(trainable, tune_config=tune_config, run_config=run_config,
                   _restored_trials=trials)
