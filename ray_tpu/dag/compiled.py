"""Compiled actor DAGs: static graphs executed through preallocated
shared-memory channels with persistent per-actor exec loops.

Reference architecture: python/ray/dag/compiled_dag_node.py:391 (CompiledDAG,
do_exec_tasks :84, execute :1408) + shared_memory_channel.py:147. The
TPU-native difference: channels are in-place-mutated plasma objects on the
node segment (one memcpy handoff, no per-step task submission), and values
that are jax/numpy arrays ride the serializer's zero-copy buffer path, so a
same-host pipeline stage handoff never round-trips device data through RPC.

Usage::

    with InputNode() as inp:
        x = a.f.bind(inp)
        y = b.g.bind(x)
    dag = y.experimental_compile()
    for step in range(1000):
        ref = dag.execute(step)        # no task submission per step
        out = ref.get()
    dag.teardown()

Constraints (same as the reference's aDAG v1): every bound method must be an
actor method (plain tasks cannot host a persistent loop), the graph is
static, and all participating actors must live on the driver's node (the
shared-memory plane is node-local; cross-node pipelines shard by stage).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from ray_tpu.dag.node import (
    ClassMethodNode,
    DAGNode,
    InputNode,
    MultiOutputNode,
    _AttrProxy,
)
from ray_tpu.experimental.channel import (
    Channel,
    ChannelClosed,
    _PropagatedError,
)


class _FROM_CHANNEL:
    """Sentinel marking a positional arg fed by a channel read. A class is
    pickled by reference, so identity survives the __ray_call__ hop."""


class CompiledDAGRef:
    """Result handle for one execute(); reads the output channels."""

    def __init__(self, dag: "CompiledDAG", seq: int):
        self._dag = dag
        self._seq = seq
        self._value = None
        self._consumed = False

    def get(self, timeout: Optional[float] = 60.0):
        return self._dag._read_output(self, timeout)


def _exec_loop(self, tasks: List[dict]):
    """Runs inside the actor (shipped via __ray_call__): read inputs, call
    the bound method, write the output — forever, until teardown closes a
    channel. This is the reference's do_exec_tasks."""
    attached: Dict[bytes, Channel] = {}

    def chan(desc, reader_index):
        key = desc["oid"]
        if key not in attached:
            attached[key] = Channel.attach(desc, reader_index)
        return attached[key]

    try:
        while True:
            for t in tasks:
                # One read per channel per task-tick: a method consuming the
                # same upstream twice (f.bind(x, x)) must not double-read.
                # Per-task (not per-tick): each task owns a distinct reader
                # slot and must perform its own read to ack it.
                tick_cache: Dict[bytes, Any] = {}
                args = []
                error = None
                for desc, ridx, unpack in t["reads"]:
                    key = desc["oid"]
                    if key in tick_cache:
                        v = tick_cache[key]
                    else:
                        try:
                            v = chan(desc, ridx).read()
                        except _PropagatedError as e:
                            v = e
                        tick_cache[key] = v
                    if isinstance(v, _PropagatedError):
                        error = v
                        args.append(None)
                    elif unpack is None:
                        args.append(v)
                    else:
                        args.append(v[unpack])
                out_chan = chan(t["write"], None)
                if error is not None:
                    out_chan.write(error.inner, is_error=True)
                    continue
                it = iter(args)
                bound = [next(it) if s is _FROM_CHANNEL else s
                         for s in t["static_args"]]
                try:
                    result = getattr(self, t["method"])(*bound, **t["kwargs"])
                except Exception as e:
                    out_chan.write(e, is_error=True)
                    continue
                out_chan.write(result)
    except ChannelClosed:
        return None


def _start_exec_loop(self, tasks: List[dict]):
    t = threading.Thread(
        target=_exec_loop, args=(self, tasks), daemon=True,
        name="rtpu-dag-exec",
    )
    t.start()
    return True


class CompiledDAG:
    def __init__(self, output_node: DAGNode,
                 buffer_size_bytes: int = 4 * 1024 * 1024):
        self._buffer_size = buffer_size_bytes
        self._torn_down = False
        self._seq = 0
        self._next_read_seq = 1
        self._in_flight: List[CompiledDAGRef] = []
        self._lock = threading.Lock()
        self._compile(output_node)

    # ------------------------------------------------------------- compile

    def _compile(self, output_node: DAGNode):
        if isinstance(output_node, MultiOutputNode):
            outputs = list(output_node._nodes)
        else:
            outputs = [output_node]
        for n in outputs:
            if not isinstance(n, ClassMethodNode):
                raise ValueError(
                    "compiled DAGs support actor-method nodes only "
                    "(reference: compiled_dag_node.py NotImplementedError)"
                )

        # Topological collection (args before consumers).
        order: List[ClassMethodNode] = []
        seen = set()
        self._input_node: Optional[InputNode] = None

        def visit(n):
            if id(n) in seen:
                return
            seen.add(id(n))
            if isinstance(n, InputNode):
                self._input_node = n
                return
            if isinstance(n, _AttrProxy):
                visit(n._base)
                return
            if not isinstance(n, ClassMethodNode):
                if isinstance(n, DAGNode):
                    raise ValueError(
                        f"unsupported node type in compiled DAG: {type(n)}"
                    )
                return
            for a in list(n._bound_args) + list(n._bound_kwargs.values()):
                if isinstance(a, DAGNode):
                    visit(a)
            order.append(n)

        for n in outputs:
            visit(n)
        if not order:
            raise ValueError("empty DAG")

        # Reader bookkeeping: channel per producing node + the input channel.
        # Consumer lists are UNIQUE per node: a method consuming the same
        # upstream twice still occupies one reader slot (the exec loop reads
        # each channel once per tick), and every allocated slot must have a
        # live reader or the writer's all-acked wait never completes.
        consumers: Dict[int, List] = {id(n): [] for n in order}
        input_consumers: List = []
        for n in order:
            seen_bases = set()
            for a in n._bound_args:
                base = a._base if isinstance(a, _AttrProxy) else a
                if id(base) in seen_bases:
                    continue
                seen_bases.add(id(base))
                if isinstance(base, InputNode):
                    input_consumers.append(n)
                elif isinstance(base, ClassMethodNode):
                    consumers[id(base)].append(n)
        out_reader_idx: Dict[int, int] = {}
        for n in outputs:
            consumers[id(n)].append("driver")

        # Allocate channels.
        self._input_channel = (
            Channel.create(max(1, len(input_consumers)), self._buffer_size)
            if input_consumers else None
        )
        node_channel: Dict[int, Channel] = {}
        for n in order:
            node_channel[id(n)] = Channel.create(
                max(1, len(consumers[id(n)])), self._buffer_size
            )

        # Build per-actor task descriptors.
        input_rix: Dict[int, int] = {}
        for i, c in enumerate(input_consumers):
            input_rix.setdefault(id(c), i)
        node_rix: Dict[int, Dict[int, int]] = {}
        for n in order:
            node_rix[id(n)] = {}
            for i, c in enumerate(consumers[id(n)]):
                if c == "driver":
                    out_reader_idx[id(n)] = i
                else:
                    node_rix[id(n)][id(c)] = i

        by_actor: Dict[Any, List[dict]] = {}
        self._actors = []
        for n in order:
            handle = n._class_node._ensure_actor()
            reads = []
            static_args = []
            kwargs = {}
            for a in n._bound_args:
                unpack = None
                base = a
                if isinstance(a, _AttrProxy):
                    unpack = a._key
                    base = a._base
                if isinstance(base, InputNode):
                    reads.append((self._input_channel.descriptor(),
                                  input_rix[id(n)], unpack))
                    static_args.append(_FROM_CHANNEL)
                elif isinstance(base, ClassMethodNode):
                    reads.append((node_channel[id(base)].descriptor(),
                                  node_rix[id(base)][id(n)], unpack))
                    static_args.append(_FROM_CHANNEL)
                else:
                    static_args.append(base)
            for k, v in n._bound_kwargs.items():
                if isinstance(v, DAGNode):
                    raise ValueError("DAG deps must be positional args")
                kwargs[k] = v
            by_actor.setdefault(handle, []).append({
                "method": n._method_name,
                "reads": reads,
                "static_args": static_args,
                "kwargs": kwargs,
                "write": node_channel[id(n)].descriptor(),
            })

        # Same-node constraint: the shared-memory plane is node-local.
        import ray_tpu

        my_node = ray_tpu.get_runtime_context().get_node_id()
        for handle in by_actor:
            actor_node = ray_tpu.get(
                handle.__ray_call__.remote(
                    lambda self: __import__("ray_tpu")
                    .get_runtime_context().get_node_id()
                )
            )
            if actor_node != my_node:
                raise ValueError(
                    "compiled DAG actors must be on the driver's node "
                    f"(actor on {actor_node}, driver on {my_node}); "
                    "shard cross-node pipelines by stage"
                )

        # Launch exec loops.
        started = [
            handle.__ray_call__.remote(_start_exec_loop, tasks)
            for handle, tasks in by_actor.items()
        ]
        ray_tpu.get(started)
        self._actors = list(by_actor)
        self._output_channels = [
            (node_channel[id(n)], out_reader_idx[id(n)]) for n in outputs
        ]
        self._output_readers = [
            Channel(ch._oid, ch._view, ridx, ch._n_readers)
            for ch, ridx in self._output_channels
        ]
        self._all_channels = list(node_channel.values()) + (
            [self._input_channel] if self._input_channel else []
        )
        self._multi_output = isinstance(output_node, MultiOutputNode)

    # ------------------------------------------------------------- execute

    def execute(self, *args, timeout: Optional[float] = 60.0):
        if self._torn_down:
            raise RuntimeError("compiled DAG was torn down")
        with self._lock:
            self._seq += 1
            ref = CompiledDAGRef(self, self._seq)
            self._in_flight.append(ref)
        if self._input_channel is not None:
            value = args[0] if len(args) == 1 else args
            self._input_channel.write(value, timeout=timeout)
        return ref

    def _read_output(self, ref: CompiledDAGRef, timeout: Optional[float]):
        with self._lock:
            if ref._consumed:
                return ref._value
            # Channel reads are strictly ordered; service older refs first.
            for pending in list(self._in_flight):
                if pending._seq > ref._seq:
                    break
                outs = []
                err = None
                for rd in self._output_readers:
                    try:
                        outs.append(rd.read(timeout=timeout))
                    except _PropagatedError as e:
                        err = e.inner
                        outs.append(None)
                pending._consumed = True
                if err is not None:
                    pending._value = err
                    pending._error = True
                else:
                    pending._value = (
                        outs if self._multi_output else outs[0]
                    )
                    pending._error = False
                self._in_flight.remove(pending)
            if getattr(ref, "_error", False):
                raise ref._value
            return ref._value

    def teardown(self):
        if self._torn_down:
            return
        self._torn_down = True
        for ch in self._all_channels:
            try:
                ch.destroy()
            except Exception:
                pass


