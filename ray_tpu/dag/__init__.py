from ray_tpu.dag.node import ClassNode, DAGNode, FunctionNode, InputNode  # noqa: F401
