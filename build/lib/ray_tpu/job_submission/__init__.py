"""ray_tpu.job_submission — submit driver scripts as managed jobs.

Counterpart of ``ray.job_submission`` (reference:
python/ray/dashboard/modules/job/sdk.py:35 JobSubmissionClient). The client
speaks either directly to the GCS (``ray_tpu://host:port`` or a bare
``host:port``) or to a dashboard's REST API (``http://host:port``).
"""

from __future__ import annotations

import json
import urllib.request
from typing import List, Optional

from ray_tpu.job_submission._manager import (
    FAILED,
    PENDING,
    RUNNING,
    STOPPED,
    SUCCEEDED,
    JobManager,
    JobSupervisor,
)


class JobStatus:
    PENDING = PENDING
    RUNNING = RUNNING
    SUCCEEDED = SUCCEEDED
    FAILED = FAILED
    STOPPED = STOPPED


class JobSubmissionClient:
    def __init__(self, address: Optional[str] = None):
        self._http = None
        self._mgr: Optional[JobManager] = None
        if address and address.startswith("http"):
            self._http = address.rstrip("/")
        elif address:
            from ray_tpu._private.gcs.client import GcsClient

            address = address.replace("ray_tpu://", "")
            self._mgr = JobManager(GcsClient.from_address(address))
        else:
            self._mgr = JobManager()

    # ------------------------------------------------------------ REST glue

    def _req(self, method: str, path: str, body: Optional[dict] = None):
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self._http + path,
            data=data,
            method=method,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=60) as r:
            return json.loads(r.read() or b"{}")

    # ----------------------------------------------------------------- API

    def submit_job(
        self,
        *,
        entrypoint: str,
        submission_id: Optional[str] = None,
        runtime_env: Optional[dict] = None,
        metadata: Optional[dict] = None,
    ) -> str:
        if self._http:
            r = self._req(
                "POST",
                "/api/jobs/",
                {
                    "entrypoint": entrypoint,
                    "submission_id": submission_id,
                    "runtime_env": runtime_env,
                    "metadata": metadata,
                },
            )
            return r["submission_id"]
        return self._mgr.submit_job(
            entrypoint=entrypoint,
            submission_id=submission_id,
            runtime_env=runtime_env,
            metadata=metadata,
        )

    def get_job_status(self, submission_id: str) -> str:
        if self._http:
            return self._req("GET", f"/api/jobs/{submission_id}")["status"]
        return self._mgr.get_job_status(submission_id)

    def get_job_info(self, submission_id: str) -> dict:
        if self._http:
            return self._req("GET", f"/api/jobs/{submission_id}")
        return self._mgr.get_job_info(submission_id)

    def get_job_logs(self, submission_id: str, offset: int = 0) -> str:
        if self._http:
            return self._req(
                "GET", f"/api/jobs/{submission_id}/logs?offset={offset}"
            )["logs"]
        return self._mgr.get_job_logs(submission_id, offset)

    def stop_job(self, submission_id: str) -> bool:
        if self._http:
            return self._req("POST", f"/api/jobs/{submission_id}/stop")["stopped"]
        return self._mgr.stop_job(submission_id)

    def list_jobs(self) -> List[dict]:
        if self._http:
            return self._req("GET", "/api/jobs/")["jobs"]
        return self._mgr.list_jobs()

    def tail_job_logs(self, submission_id: str):
        """Yield new log chunks; each poll transfers only unseen bytes."""
        import time

        offset = 0
        while True:
            chunk = self.get_job_logs(submission_id, offset=offset)
            if chunk:
                yield chunk
                offset += len(chunk.encode("utf-8", "replace"))
            status = self.get_job_status(submission_id)
            if status in (JobStatus.SUCCEEDED, JobStatus.FAILED, JobStatus.STOPPED):
                chunk = self.get_job_logs(submission_id, offset=offset)
                if chunk:
                    yield chunk
                return
            time.sleep(0.5)


__all__ = ["JobSubmissionClient", "JobStatus", "JobManager", "JobSupervisor"]
