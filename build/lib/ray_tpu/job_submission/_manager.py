"""Job manager + supervisor: run driver scripts as managed cluster jobs.

Counterpart of the reference's job submission stack
(reference: python/ray/dashboard/modules/job/job_manager.py:57 JobManager,
job_supervisor.py:51 JobSupervisor — a detached supervisor actor per job
runs the entrypoint as a subprocess with the cluster address injected,
captures output, and records status for the REST/SDK/CLI surfaces).
Status lives in the GCS KV (ns "job_submission") so it survives the
submitting client.
"""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import Dict, List, Optional

JOB_KV_NS = "job_submission"

PENDING = "PENDING"
RUNNING = "RUNNING"
SUCCEEDED = "SUCCEEDED"
FAILED = "FAILED"
STOPPED = "STOPPED"

_TERMINAL = (SUCCEEDED, FAILED, STOPPED)


class JobSupervisor:
    """Detached actor that owns one job's subprocess
    (reference: job_supervisor.py:51)."""

    def __init__(self, submission_id: str, entrypoint: str, env_vars: dict,
                 gcs_address: str, log_path: str):
        self.submission_id = submission_id
        self.entrypoint = entrypoint
        self.env_vars = dict(env_vars or {})
        self.gcs_address = gcs_address
        self.log_path = log_path
        self._proc = None
        self._stopped = False

    def _set_status(self, status: str, message: str = ""):
        from ray_tpu._private import worker as worker_mod

        gcs = worker_mod.global_worker.gcs
        key = self.submission_id.encode()
        # Read-modify-write: preserve submit-time fields (metadata, ...).
        try:
            info = json.loads(gcs.kv_get(JOB_KV_NS, key) or b"{}")
        except Exception:
            info = {}
        info.update(
            submission_id=self.submission_id,
            entrypoint=self.entrypoint,
            status=status,
            message=message,
            start_time=getattr(self, "_start_time", None),
            end_time=time.time() if status in _TERMINAL else None,
            log_path=self.log_path,
        )
        gcs.kv_put(JOB_KV_NS, key, json.dumps(info).encode())

    async def start(self) -> bool:
        """Spawn the entrypoint subprocess. The submitter blocks on this so
        the job is provably started before submit_job returns (a
        fire-and-forget run could be lost if the submitting process exits
        immediately, e.g. the CLI)."""
        import asyncio
        import subprocess

        self._start_time = time.time()
        env = dict(os.environ)
        env.update(self.env_vars)
        env["RTPU_ADDRESS"] = self.gcs_address
        os.makedirs(os.path.dirname(self.log_path) or ".", exist_ok=True)
        logf = open(self.log_path, "ab", buffering=0)
        try:
            self._proc = subprocess.Popen(
                self.entrypoint,
                shell=True,
                stdout=logf,
                stderr=subprocess.STDOUT,
                env=env,
                start_new_session=True,
            )
        except Exception as e:
            logf.close()
            self._set_status(FAILED, f"failed to spawn entrypoint: {e}")
            return False
        self._set_status(RUNNING)
        self._wait_task = asyncio.ensure_future(self._wait(logf))
        return True

    async def _wait(self, logf) -> str:
        import asyncio

        loop = asyncio.get_running_loop()
        try:
            rc = await loop.run_in_executor(None, self._proc.wait)
        finally:
            logf.close()
        if self._stopped:
            status, msg = STOPPED, "stopped by user"
        elif rc == 0:
            status, msg = SUCCEEDED, ""
        else:
            status, msg = FAILED, f"entrypoint exited with code {rc}"
        self._set_status(status, msg)
        # Self-terminate after a grace period (reference: the supervisor
        # actor exits with the job) — the log file outlives the actor and
        # queries fall back to it; without this every job leaks a detached
        # actor forever.
        asyncio.get_running_loop().call_later(60.0, self._exit_self)
        return status

    def _exit_self(self):
        import os as _os

        _os._exit(0)

    async def run(self) -> str:
        """Start and block until terminal (in-process convenience)."""
        if not await self.start():
            return FAILED
        return await self._wait_task

    async def stop(self) -> bool:
        import signal

        self._stopped = True
        if self._proc is not None and self._proc.poll() is None:
            try:
                os.killpg(os.getpgid(self._proc.pid), signal.SIGTERM)
            except Exception:
                try:
                    self._proc.terminate()
                except Exception:
                    return False
            return True
        return False

    async def get_logs(self, offset: int = 0) -> str:
        try:
            with open(self.log_path, "rb") as f:
                if offset:
                    f.seek(offset)
                return f.read().decode("utf-8", "replace")
        except OSError:
            return ""

    async def ping(self) -> bool:
        return True


class JobManager:
    """Submits/queries jobs against a connected cluster
    (reference: job_manager.py:57)."""

    def __init__(self, gcs_client=None):
        if gcs_client is None:
            from ray_tpu._private import worker as worker_mod

            if worker_mod.global_worker is None:
                raise RuntimeError("ray_tpu is not initialized")
            gcs_client = worker_mod.global_worker.gcs
        self.gcs = gcs_client

    def _ensure_connected(self):
        """Actor operations (supervisor spawn/lookup) need a driver; CLI
        and SDK callers may not have called ray_tpu.init themselves."""
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init(address=self.gcs.address, log_to_driver=False)

    # ----------------------------------------------------------- submission

    def submit_job(
        self,
        *,
        entrypoint: str,
        submission_id: Optional[str] = None,
        runtime_env: Optional[dict] = None,
        metadata: Optional[dict] = None,
    ) -> str:
        import ray_tpu

        self._ensure_connected()
        submission_id = submission_id or f"raysubmit_{uuid.uuid4().hex[:12]}"
        if self.gcs.kv_exists(JOB_KV_NS, submission_id.encode()):
            raise ValueError(f"job '{submission_id}' already exists")
        env_vars = dict((runtime_env or {}).get("env_vars") or {})
        working_dir = (runtime_env or {}).get("working_dir")
        if working_dir:
            env_vars.setdefault("RTPU_JOB_WORKING_DIR", working_dir)
        session_dir = self._session_dir()
        log_path = os.path.join(session_dir, "logs", f"job-{submission_id}.log")
        info = {
            "submission_id": submission_id,
            "entrypoint": entrypoint,
            "status": PENDING,
            "message": "",
            "metadata": metadata or {},
            "start_time": None,
            "end_time": None,
        }
        self.gcs.kv_put(
            JOB_KV_NS, submission_id.encode(), json.dumps(info).encode()
        )
        supervisor = (
            ray_tpu.remote(JobSupervisor)
            .options(
                name=f"JOB_SUP::{submission_id}",
                lifetime="detached",
                max_concurrency=4,
                num_cpus=0,
            )
            .remote(
                submission_id,
                entrypoint,
                env_vars,
                self.gcs.address,
                log_path,
            )
        )
        # Block until the subprocess is spawned: the submitter may exit
        # right after (CLI one-shots) and a buffered fire-and-forget task
        # would be lost with it.
        ray_tpu.get(supervisor.start.remote(), timeout=120)
        return submission_id

    def _session_dir(self) -> str:
        try:
            r = self.gcs.call("GetInternalConfig", {})
            return r.get("session_dir") or "/tmp/ray_tpu"
        except Exception:
            return "/tmp/ray_tpu"

    # -------------------------------------------------------------- queries

    def get_job_info(self, submission_id: str) -> dict:
        raw = self.gcs.kv_get(JOB_KV_NS, submission_id.encode())
        if raw is None:
            raise ValueError(f"no job '{submission_id}'")
        return json.loads(raw)

    def get_job_status(self, submission_id: str) -> str:
        return self.get_job_info(submission_id)["status"]

    def list_jobs(self) -> List[dict]:
        out = []
        for key in self.gcs.kv_keys(JOB_KV_NS):
            raw = self.gcs.kv_get(JOB_KV_NS, key)
            if raw:
                out.append(json.loads(raw))
        out.sort(key=lambda j: j.get("start_time") or 0)
        return out

    def get_job_logs(self, submission_id: str, offset: int = 0) -> str:
        import ray_tpu

        info = self.get_job_info(submission_id)  # raises on unknown id
        # The log file outlives the (self-terminating) supervisor actor;
        # prefer it when reachable, fall back to the actor for remote logs.
        log_path = info.get("log_path")
        if log_path and os.path.exists(log_path):
            try:
                with open(log_path, "rb") as f:
                    if offset:
                        f.seek(offset)
                    return f.read().decode("utf-8", "replace")
            except OSError:
                pass
        self._ensure_connected()
        try:
            sup = ray_tpu.get_actor(f"JOB_SUP::{submission_id}")
            return ray_tpu.get(sup.get_logs.remote(offset), timeout=30)
        except Exception:
            return ""

    def stop_job(self, submission_id: str) -> bool:
        import ray_tpu

        self._ensure_connected()
        info = self.get_job_info(submission_id)
        if info["status"] in _TERMINAL:
            return False
        try:
            sup = ray_tpu.get_actor(f"JOB_SUP::{submission_id}")
            return ray_tpu.get(sup.stop.remote(), timeout=30)
        except Exception:
            return False

    def wait_until_finished(
        self, submission_id: str, timeout: float = 300.0
    ) -> str:
        deadline = time.time() + timeout
        while time.time() < deadline:
            status = self.get_job_status(submission_id)
            if status in _TERMINAL:
                return status
            time.sleep(0.5)
        raise TimeoutError(f"job '{submission_id}' still {status}")
