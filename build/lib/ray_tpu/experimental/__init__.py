"""ray_tpu.experimental — compiled-DAG channels and other pre-stable APIs."""
