"""@serve.batch — transparent request batching inside a replica.

Counterpart of the reference's batching (reference:
python/ray/serve/batching.py — queue individual calls, run the wrapped
method once per batch of up to max_batch_size after at most
batch_wait_timeout_s, scatter results back).
"""

from __future__ import annotations

import asyncio
import functools
from typing import Any, Callable, List, Optional


class _BatchQueue:
    def __init__(self, fn, max_batch_size: int, batch_wait_timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.batch_wait_timeout_s = batch_wait_timeout_s
        self.queue: List[tuple] = []  # (item, future)
        self._flusher: Optional[asyncio.Task] = None

    async def submit(self, instance, item) -> Any:
        fut = asyncio.get_running_loop().create_future()
        self.queue.append((item, fut))
        if len(self.queue) >= self.max_batch_size:
            await self._flush(instance)
        elif self._flusher is None or self._flusher.done():
            self._flusher = asyncio.ensure_future(self._delayed_flush(instance))
        return await fut

    async def _delayed_flush(self, instance):
        await asyncio.sleep(self.batch_wait_timeout_s)
        await self._flush(instance)

    async def _flush(self, instance):
        if not self.queue:
            return
        batch, self.queue = self.queue, []
        items = [b[0] for b in batch]
        try:
            if instance is not None:
                results = await self.fn(instance, items)
            else:
                results = await self.fn(items)
            if len(results) != len(items):
                raise ValueError(
                    f"@serve.batch function returned {len(results)} results "
                    f"for a batch of {len(items)}"
                )
            for (_, fut), res in zip(batch, results):
                if not fut.done():
                    fut.set_result(res)
        except Exception as e:
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(e)


def batch(
    _fn: Optional[Callable] = None,
    *,
    max_batch_size: int = 8,
    batch_wait_timeout_s: float = 0.01,
):
    """Decorate an async method taking a LIST of items; individual calls
    are queued and executed as batches."""

    def wrap(fn):
        queues = {}  # instance id -> _BatchQueue (per-replica state)

        @functools.wraps(fn)
        async def wrapper(*args):
            if len(args) == 2:
                instance, item = args
            elif len(args) == 1:
                instance, item = None, args[0]
            else:
                raise TypeError("@serve.batch methods take exactly one argument")
            key = id(instance)
            q = queues.get(key)
            if q is None:
                q = queues[key] = _BatchQueue(fn, max_batch_size, batch_wait_timeout_s)
            return await q.submit(instance, item)

        wrapper._is_serve_batch = True
        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
