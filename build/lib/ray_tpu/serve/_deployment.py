"""Deployment / Application: the declarative layer of Serve.

Counterpart of the reference's deployment decorator + bound application
graph (reference: python/ray/serve/deployment.py — Deployment.bind,
serve/api.py:535 serve.run). ``@serve.deployment`` wraps a class or
function; ``.bind(*args)`` produces an Application node whose arguments may
themselves be Applications (model composition — inner apps become their own
deployments and the outer one receives DeploymentHandles).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple


@dataclass
class AutoscalingConfig:
    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 2.0
    downscale_delay_s: float = 10.0

    def to_dict(self):
        return self.__dict__.copy()


class Deployment:
    def __init__(
        self,
        func_or_class,
        name: str,
        num_replicas: int = 1,
        ray_actor_options: Optional[dict] = None,
        max_ongoing_requests: int = 8,
        autoscaling_config: Optional[dict] = None,
        health_check_period_s: float = 2.0,
    ):
        self.func_or_class = func_or_class
        self.name = name
        self.num_replicas = num_replicas
        self.ray_actor_options = dict(ray_actor_options or {})
        self.max_ongoing_requests = max_ongoing_requests
        if isinstance(autoscaling_config, AutoscalingConfig):
            autoscaling_config = autoscaling_config.to_dict()
        self.autoscaling_config = autoscaling_config
        self.health_check_period_s = health_check_period_s

    def options(self, **overrides) -> "Deployment":
        cfg = {
            "name": self.name,
            "num_replicas": self.num_replicas,
            "ray_actor_options": self.ray_actor_options,
            "max_ongoing_requests": self.max_ongoing_requests,
            "autoscaling_config": self.autoscaling_config,
            "health_check_period_s": self.health_check_period_s,
        }
        cfg.update(overrides)
        return Deployment(self.func_or_class, **cfg)

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)

    def __call__(self, *a, **k):
        raise RuntimeError(
            "Deployments are not directly callable; use .bind() + serve.run, "
            "then handle.remote()"
        )


class Application:
    """A bound deployment DAG node."""

    def __init__(self, deployment: Deployment, args: tuple, kwargs: dict):
        self.deployment = deployment
        self.args = args
        self.kwargs = kwargs

    def flatten(self) -> List[Tuple[Deployment, tuple, dict]]:
        """Topological list of (deployment, init_args, init_kwargs) with
        nested Applications replaced by handle placeholders."""
        out: List[Tuple[Deployment, tuple, dict]] = []
        seen: Dict[int, str] = {}

        def visit(app: "Application") -> "_HandleRef":
            if id(app) in seen:
                return _HandleRef(seen[id(app)])
            args = tuple(
                visit(a) if isinstance(a, Application) else a for a in app.args
            )
            kwargs = {
                k: visit(v) if isinstance(v, Application) else v
                for k, v in app.kwargs.items()
            }
            name = app.deployment.name
            suffix = 1
            while any(d.name == name for d, _, _ in out):
                suffix += 1
                name = f"{app.deployment.name}_{suffix}"
            dep = app.deployment.options(name=name) if name != app.deployment.name else app.deployment
            seen[id(app)] = name
            out.append((dep, args, kwargs))
            return _HandleRef(name)

        visit(self)
        return out


@dataclass
class _HandleRef:
    """Placeholder in init args, resolved to a DeploymentHandle at replica
    construction time."""

    deployment_name: str


def deployment(
    _func_or_class=None,
    *,
    name: Optional[str] = None,
    num_replicas: int = 1,
    ray_actor_options: Optional[dict] = None,
    max_ongoing_requests: int = 8,
    autoscaling_config: Optional[dict] = None,
    health_check_period_s: float = 2.0,
):
    """@serve.deployment decorator (reference: serve/api.py deployment)."""

    def wrap(func_or_class):
        return Deployment(
            func_or_class,
            name=name or getattr(func_or_class, "__name__", "deployment"),
            num_replicas=num_replicas,
            ray_actor_options=ray_actor_options,
            max_ongoing_requests=max_ongoing_requests,
            autoscaling_config=autoscaling_config,
            health_check_period_s=health_check_period_s,
        )

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap
