"""Model multiplexing: many models served by few replicas
(reference: python/ray/serve/multiplex.py — @serve.multiplexed LRU model
cache per replica + serve.get_multiplexed_model_id()).

The handle routes a request tagged with ``multiplexed_model_id`` to a
replica with deterministic model→replica affinity (hash-based), so a model's
weights load on one replica instead of all of them; inside the replica a
@multiplexed-decorated loader keeps an LRU cache of at most
``max_num_models_per_replica`` models, evicting the least-recently-used
(calling its ``__del__`` if defined, mirroring the reference's unload hook).
"""

from __future__ import annotations

import asyncio
import contextvars
import inspect
from collections import OrderedDict
from typing import Any, Callable, Optional

_current_model_id: contextvars.ContextVar[str] = contextvars.ContextVar(
    "rtpu_serve_multiplexed_model_id", default=""
)


def get_multiplexed_model_id() -> str:
    """The model id of the request currently being handled
    (reference: serve.get_multiplexed_model_id)."""
    return _current_model_id.get()


def _set_current_model_id(model_id: str):
    _current_model_id.set(model_id)


class _ModelCache:
    def __init__(self, loader: Callable, max_models: int):
        self._loader = loader
        self._max = max_models
        self._models: OrderedDict[str, Any] = OrderedDict()
        self._locks: dict = {}

    async def get(self, owner, model_id: str) -> Any:
        if model_id in self._models:
            self._models.move_to_end(model_id)
            return self._models[model_id]
        lock = self._locks.setdefault(model_id, asyncio.Lock())
        async with lock:
            if model_id in self._models:
                self._models.move_to_end(model_id)
                return self._models[model_id]
            result = self._loader(owner, model_id) if owner is not None \
                else self._loader(model_id)
            if inspect.iscoroutine(result):
                result = await result
            self._models[model_id] = result
            while len(self._models) > self._max:
                _, evicted = self._models.popitem(last=False)
                del_fn = getattr(evicted, "__del__", None)
                if del_fn is not None:
                    try:
                        r = del_fn()
                        if inspect.iscoroutine(r):
                            await r
                    except Exception:
                        pass
            return result

    def loaded_ids(self):
        return list(self._models.keys())


def multiplexed(func: Optional[Callable] = None, *,
                max_num_models_per_replica: int = 3):
    """Decorator for a model-loader method/function:

        @serve.multiplexed(max_num_models_per_replica=2)
        async def get_model(self, model_id: str): ...

    Calls are LRU-cached per replica by model id."""

    def wrap(fn):
        sig = inspect.signature(fn)
        takes_self = list(sig.parameters) and (
            list(sig.parameters)[0] == "self")
        cache = _ModelCache(fn, max_num_models_per_replica)

        if takes_self:
            async def wrapper(self, model_id: str):
                return await cache.get(self, model_id)
        else:
            async def wrapper(model_id: str):
                return await cache.get(None, model_id)

        wrapper._serve_model_cache = cache
        wrapper.__name__ = getattr(fn, "__name__", "multiplexed")
        return wrapper

    if func is not None:
        return wrap(func)
    return wrap
