"""Runtime context (reference: python/ray/runtime_context.py)."""

from __future__ import annotations

from typing import Optional

from ray_tpu._private.worker import get_global_worker


class RuntimeContext:
    def __init__(self, worker):
        self._worker = worker

    def get_job_id(self) -> str:
        return self._worker.job_id.hex()

    def get_node_id(self) -> str:
        return self._worker.node_id.hex()

    def get_worker_id(self) -> str:
        return self._worker.worker_id.hex()

    def get_task_id(self) -> Optional[str]:
        spec = self._worker.current_task_spec()
        return spec["task_id"].hex() if spec else None

    def get_actor_id(self) -> Optional[str]:
        return self._worker.actor_id.hex() if self._worker.actor_id else None

    def get_actor_name(self) -> Optional[str]:
        spec = self._worker._actor_spec
        if spec is None:
            return None
        return spec.get("name", "").split(".")[0] or None

    @property
    def gcs_address(self) -> str:
        return self._worker.gcs.address

    def get_assigned_resources(self) -> dict:
        spec = self._worker.current_task_spec()
        if spec is not None:
            return dict(spec.get("resources", {}))
        if self._worker._actor_spec is not None:
            return dict(self._worker._actor_spec.get("resources", {}))
        return {}

    def get_accelerator_ids(self) -> dict:
        import os

        visible = os.environ.get("TPU_VISIBLE_CHIPS", "")
        chips = [c for c in visible.split(",") if c] if visible else []
        if not chips:
            n = int(self.get_assigned_resources().get("TPU", 0))
            chips = [str(i) for i in range(n)]
        return {"TPU": chips}

    def was_current_actor_reconstructed(self) -> bool:
        return False


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext(get_global_worker())
