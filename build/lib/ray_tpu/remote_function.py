"""@ray_tpu.remote functions (reference: python/ray/remote_function.py:40)."""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

from ray_tpu._private import task_spec as ts
from ray_tpu._private.worker import get_global_worker
from ray_tpu.util.scheduling_strategies import strategy_to_dict


_OPTION_DEFAULTS = dict(
    num_cpus=None,
    num_tpus=None,
    num_gpus=None,  # accepted for API compat; TPU is the accelerator here
    memory=None,
    resources=None,
    num_returns=1,
    max_retries=None,
    retry_exceptions=False,
    scheduling_strategy=None,
    runtime_env=None,
    name=None,
    _metadata=None,
)


def _merge_options(base: Dict[str, Any], overrides: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(base)
    for k, v in overrides.items():
        if k not in _OPTION_DEFAULTS:
            raise ValueError(f"unknown option '{k}' for remote function")
        out[k] = v
    return out


class RemoteFunction:
    def __init__(self, fn, options: Optional[Dict[str, Any]] = None):
        if isinstance(fn, RemoteFunction):
            fn = fn._function
        self._function = fn
        self._options = dict(_OPTION_DEFAULTS)
        if options:
            self._options = _merge_options(self._options, options)
        functools.update_wrapper(self, fn)

    def options(self, **overrides) -> "RemoteFunction":
        rf = RemoteFunction(self._function, None)
        rf._options = _merge_options(self._options, overrides)
        return rf

    def remote(self, *args, **kwargs):
        worker = get_global_worker()
        o = self._options
        if o["num_gpus"]:
            raise ValueError(
                "num_gpus is not supported on a TPU cluster; use num_tpus"
            )
        resources = ts.normalize_resources(
            o["num_cpus"], o["num_tpus"], o["memory"], o["resources"]
        )
        max_retries = o["max_retries"]
        if max_retries is None:
            from ray_tpu._private.config import RTPU_CONFIG

            max_retries = RTPU_CONFIG.task_max_retries_default
        refs = worker.submit_task(
            self._function,
            args,
            kwargs,
            name=o["name"] or self._function.__qualname__,
            num_returns=o["num_returns"],
            resources=resources,
            max_retries=max_retries,
            retry_exceptions=bool(o["retry_exceptions"]),
            scheduling_strategy=strategy_to_dict(o["scheduling_strategy"]),
            runtime_env=o["runtime_env"],
        )
        if o["num_returns"] == 1:
            return refs[0]
        if o["num_returns"] == 0:
            return None
        return refs

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function '{self._function.__qualname__}' cannot be called "
            "directly; use .remote()"
        )

    def bind(self, *args, **kwargs):
        """DAG-building entrypoint (reference: python/ray/dag)."""
        from ray_tpu.dag.node import FunctionNode

        return FunctionNode(self, args, kwargs)
