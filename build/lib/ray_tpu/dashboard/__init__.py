"""ray_tpu.dashboard — HTTP surface over the cluster's state + jobs."""

from ray_tpu.dashboard.head import DashboardHead, start_dashboard

__all__ = ["DashboardHead", "start_dashboard"]
