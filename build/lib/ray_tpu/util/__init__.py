"""ray_tpu.util — placement groups, scheduling strategies, collectives,
actor pool, queue, state API."""

import importlib
from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from ray_tpu.util import collective  # noqa: F401

_LAZY_SUBMODULES = ("check_serialize", "client", "collective", "multiprocessing", "placement_group", "queue", "state")


def __getattr__(name):
    if name in _LAZY_SUBMODULES:
        return importlib.import_module(f"ray_tpu.util.{name}")
    if name == "ActorPool":
        from ray_tpu.util.actor_pool import ActorPool

        return ActorPool
    raise AttributeError(f"module 'ray_tpu.util' has no attribute '{name}'")
