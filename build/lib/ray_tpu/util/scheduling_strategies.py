"""User-facing scheduling strategies
(reference: python/ray/util/scheduling_strategies.py)."""

from __future__ import annotations

from typing import Optional, Union


class PlacementGroupSchedulingStrategy:
    def __init__(
        self,
        placement_group,
        placement_group_bundle_index: int = -1,
        placement_group_capture_child_tasks: bool = False,
    ):
        self.placement_group = placement_group
        self.placement_group_bundle_index = placement_group_bundle_index
        self.placement_group_capture_child_tasks = placement_group_capture_child_tasks


class NodeAffinitySchedulingStrategy:
    def __init__(self, node_id: Union[str, bytes], soft: bool = False):
        self.node_id = node_id
        self.soft = soft


class NodeLabelSchedulingStrategy:
    def __init__(self, hard: Optional[dict] = None, soft: Optional[dict] = None):
        self.hard = hard or {}
        self.soft = soft or {}


SchedulingStrategyT = Union[
    None, str, PlacementGroupSchedulingStrategy, NodeAffinitySchedulingStrategy,
    NodeLabelSchedulingStrategy,
]

# per-PG round-robin cursor for bundle_index=-1 ("any bundle") submissions
_rr_counters: dict = {}


def strategy_to_dict(strategy: SchedulingStrategyT) -> dict:
    if strategy is None or strategy == "DEFAULT":
        return {}
    if strategy == "SPREAD":
        return {"type": "spread"}
    if isinstance(strategy, NodeAffinitySchedulingStrategy):
        node_id = strategy.node_id
        if isinstance(node_id, str):
            node_id = bytes.fromhex(node_id)
        return {"type": "node_affinity", "node_id": node_id, "soft": strategy.soft}
    if isinstance(strategy, PlacementGroupSchedulingStrategy):
        pg = strategy.placement_group
        pg_id = pg.id if isinstance(pg.id, bytes) else pg.id.binary()
        index = strategy.placement_group_bundle_index
        if index < 0:
            # "any bundle": round-robin across the group's bundles per
            # submission so tasks spread instead of pinning to bundle 0
            n = max(pg.bundle_count, 1)
            index = _rr_counters.get(pg_id, 0) % n
            _rr_counters[pg_id] = index + 1
        return {
            "type": "placement_group",
            "pg_id": pg_id,
            "bundle_index": index,
        }
    if isinstance(strategy, NodeLabelSchedulingStrategy):
        return {"type": "node_label", "hard": strategy.hard, "soft": strategy.soft}
    raise ValueError(f"unknown scheduling strategy {strategy!r}")
