"""ActorPool: round-robin work distribution over a fixed set of actors
(reference: python/ray/util/actor_pool.py)."""

from __future__ import annotations

from typing import Any, Callable, Iterable, List


class ActorPool:
    def __init__(self, actors: List):
        self._idle = list(actors)
        self._future_to_actor = {}
        self._index_to_future = {}
        self._next_task_index = 0
        self._next_return_index = 0

    def submit(self, fn: Callable, value):
        """fn(actor, value) -> ObjectRef; runs on the next idle actor."""
        if not self._idle:
            raise RuntimeError("no idle actors; call get_next first")
        actor = self._idle.pop()
        ref = fn(actor, value)
        self._future_to_actor[ref] = (self._next_task_index, actor)
        self._index_to_future[self._next_task_index] = ref
        self._next_task_index += 1

    def has_next(self) -> bool:
        return bool(self._future_to_actor)

    def has_free(self) -> bool:
        return bool(self._idle)

    def get_next(self, timeout=None) -> Any:
        """Next result in submission order."""
        import ray_tpu

        if self._next_return_index not in self._index_to_future:
            raise RuntimeError("no pending result at this index")
        ref = self._index_to_future.pop(self._next_return_index)
        self._next_return_index += 1
        _, actor = self._future_to_actor.pop(ref)
        self._idle.append(actor)
        return ray_tpu.get(ref, timeout=timeout)

    def get_next_unordered(self, timeout=None) -> Any:
        """Next result in completion order."""
        import ray_tpu

        if not self._future_to_actor:
            raise RuntimeError("no pending results")
        ready, _ = ray_tpu.wait(
            list(self._future_to_actor), num_returns=1, timeout=timeout
        )
        if not ready:
            raise TimeoutError("get_next_unordered timed out")
        ref = ready[0]
        idx, actor = self._future_to_actor.pop(ref)
        self._index_to_future.pop(idx, None)
        self._idle.append(actor)
        return ray_tpu.get(ref)

    def map(self, fn: Callable, values: Iterable):
        for v in values:
            if not self._idle:
                yield self.get_next()
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable):
        for v in values:
            if not self._idle:
                yield self.get_next_unordered()
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def push(self, actor):
        self._idle.append(actor)

    def pop_idle(self):
        return self._idle.pop() if self._idle else None
