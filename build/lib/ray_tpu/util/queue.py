"""Distributed FIFO queue backed by an actor (reference:
python/ray/util/queue.py)."""

from __future__ import annotations

import asyncio
from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


@ray_tpu.remote
class _QueueActor:
    def __init__(self, maxsize: int):
        self._q = asyncio.Queue(maxsize)

    async def put(self, item, timeout=None):
        try:
            await asyncio.wait_for(self._q.put(item), timeout)
        except asyncio.TimeoutError:
            raise Full from None

    async def get(self, timeout=None):
        try:
            return await asyncio.wait_for(self._q.get(), timeout)
        except asyncio.TimeoutError:
            raise Empty from None

    def put_nowait(self, item):
        if self._q.full():
            raise Full
        self._q.put_nowait(item)

    def get_nowait(self):
        if self._q.empty():
            raise Empty
        return self._q.get_nowait()

    def qsize(self) -> int:
        return self._q.qsize()

    def empty(self) -> bool:
        return self._q.empty()

    def full(self) -> bool:
        return self._q.full()


class Queue:
    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        opts = actor_options or {}
        self.actor = _QueueActor.options(**opts).remote(maxsize)

    def put(self, item, block: bool = True, timeout: Optional[float] = None):
        if block:
            ray_tpu.get(self.actor.put.remote(item, timeout))
        else:
            ray_tpu.get(self.actor.put_nowait.remote(item))

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        if block:
            return ray_tpu.get(self.actor.get.remote(timeout))
        return ray_tpu.get(self.actor.get_nowait.remote())

    def put_nowait(self, item):
        self.put(item, block=False)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def put_async(self, item):
        return self.actor.put.remote(item, None)

    def get_async(self):
        return self.actor.get.remote(None)

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return ray_tpu.get(self.actor.empty.remote())

    def full(self) -> bool:
        return ray_tpu.get(self.actor.full.remote())

    def shutdown(self):
        ray_tpu.kill(self.actor)
