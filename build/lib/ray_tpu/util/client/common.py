"""Shared wire helpers for the client proxy
(reference: python/ray/util/client/ — the ray_client.proto:326 surface,
re-designed over our msgpack RPC instead of gRPC).

Serialization contract: values cross the wire as cloudpickle bytes. Object
refs and actor handles never serialize their runtime state — a custom
``persistent_id`` swaps them for (kind, id) tickets, and the peer's
``persistent_load`` resolves tickets against its own table (client side:
ClientObjectRef stubs; server side: real ObjectRefs/ActorHandles owned by the
hosted driver). This mirrors the reference client's ClientObjectRef
indirection without needing a proto schema.
"""

from __future__ import annotations

import io
import pickle
from typing import Any, Callable

import cloudpickle


def dumps_with_tickets(value: Any, ticket_of: Callable[[Any], Any]) -> bytes:
    """cloudpickle.dumps, but objects for which ticket_of returns non-None
    are replaced by persistent-id tickets."""
    buf = io.BytesIO()

    class P(cloudpickle.CloudPickler):
        def persistent_id(self, obj):
            return ticket_of(obj)

    P(buf, protocol=pickle.HIGHEST_PROTOCOL).dump(value)
    return buf.getvalue()


def loads_with_tickets(data: bytes, resolve: Callable[[Any], Any]) -> Any:
    class U(pickle.Unpickler):
        def persistent_load(self, pid):
            return resolve(pid)

    return U(io.BytesIO(data)).load()
