"""Remote-driver client: drive a cluster from a process outside it
(reference: python/ray/util/client/ — ClientContext, api.py, worker.py; proto
surface ray_client.proto:326. Ours rides the framework msgpack RPC).

Usage (no ray_tpu.init in this process):

    from ray_tpu.util import client
    ctx = client.connect("127.0.0.1:10001")
    f = ctx.remote(lambda x: x * 2)
    assert ctx.get(f.remote(21)) == 42
    ctx.disconnect()

Functions/classes are shipped by cloudpickle; object refs and actor handles
stay server-side, the client holds tickets (ClientObjectRef/ClientActorHandle)
that release on GC or disconnect.
"""

from __future__ import annotations

import inspect
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from ray_tpu._private.rpc import IoThread, RpcClient
from ray_tpu.util.client.common import dumps_with_tickets, loads_with_tickets


class ClientObjectRef:
    __slots__ = ("id", "_ctx", "__weakref__")

    def __init__(self, rid: bytes, ctx: "ClientContext"):
        self.id = rid
        self._ctx = ctx

    def binary(self) -> bytes:
        return self.id

    def hex(self) -> str:
        return self.id.hex()

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ClientObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ClientObjectRef({self.id.hex()[:16]})"

    def __del__(self):
        try:
            ctx = self._ctx
            if ctx is not None and ctx.is_connected():
                ctx._queue_release(ref_id=self.id)
        except Exception:
            pass


class ClientRemoteMethod:
    def __init__(self, handle: "ClientActorHandle", name: str):
        self._handle = handle
        self._name = name

    def remote(self, *args, **kwargs) -> ClientObjectRef:
        return self._handle._ctx._actor_call(
            self._handle._id, self._name, args, kwargs
        )


class ClientActorHandle:
    def __init__(self, aid: bytes, ctx: "ClientContext"):
        self._id = aid
        self._ctx = ctx

    def __getattr__(self, name: str) -> ClientRemoteMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return ClientRemoteMethod(self, name)

    def __repr__(self):
        return f"ClientActorHandle({self._id.hex()[:16]})"


class ClientRemoteFunction:
    def __init__(self, fn, ctx: "ClientContext", opts: Optional[dict] = None):
        self._fn = fn
        self._ctx = ctx
        self._opts = opts or {}
        # Cache key = content digest of the pickled function (as the
        # reference client does): id()-based keys alias after GC, making
        # the server silently run a stale cached function.
        self._fn_bytes: Optional[bytes] = None
        self._fn_id: Optional[bytes] = None

    def options(self, **opts) -> "ClientRemoteFunction":
        merged = {**self._opts, **opts}
        out = ClientRemoteFunction(self._fn, self._ctx, merged)
        out._fn_bytes, out._fn_id = self._fn_bytes, self._fn_id
        return out

    def remote(self, *args, **kwargs) -> ClientObjectRef:
        if self._fn_id is None:
            import hashlib

            self._fn_bytes = self._ctx._dumps(self._fn)
            self._fn_id = hashlib.sha256(self._fn_bytes).hexdigest().encode()
        return self._ctx._task(self._fn_bytes, self._fn_id, self._opts,
                               args, kwargs)


class ClientActorClass:
    def __init__(self, cls, ctx: "ClientContext", opts: Optional[dict] = None):
        self._cls = cls
        self._ctx = ctx
        self._opts = opts or {}

    def options(self, **opts) -> "ClientActorClass":
        return ClientActorClass(self._cls, self._ctx, {**self._opts, **opts})

    def remote(self, *args, **kwargs) -> ClientActorHandle:
        return self._ctx._create_actor(self._cls, self._opts, args, kwargs)


class ClientContext:
    """A connection to a ClientServer; exposes the core API surface."""

    def __init__(self, host: str, port: int):
        self._io = IoThread.current()
        self._client = RpcClient(host, port)
        self._io.run(self._client.connect())
        self._release_lock = threading.Lock()
        self._pending_release: List[bytes] = []
        self._pending_actor_release: List[bytes] = []
        self._call("client_ping", {})

    # ------------------------------------------------------------ plumbing

    def _call(self, method: str, payload, timeout: Optional[float] = None):
        return self._io.run(self._client.call(method, payload), timeout)

    def is_connected(self) -> bool:
        try:
            return self._client.is_connected()
        except Exception:
            return False

    def _ticket_of(self, obj):
        if isinstance(obj, ClientObjectRef):
            return ("ref", obj.id)
        if isinstance(obj, ClientActorHandle):
            return ("actor", obj._id)
        return None

    def _resolve(self, pid):
        kind, rid = pid
        if kind == "ref":
            return ClientObjectRef(rid, self)
        if kind == "actor":
            return ClientActorHandle(rid, self)
        raise KeyError(kind)

    def _dumps(self, value) -> bytes:
        return dumps_with_tickets(value, self._ticket_of)

    def _loads(self, data: bytes):
        return loads_with_tickets(data, self._resolve)

    def _queue_release(self, ref_id: bytes = None, actor_id: bytes = None):
        with self._release_lock:
            if ref_id is not None:
                self._pending_release.append(ref_id)
            if actor_id is not None:
                self._pending_actor_release.append(actor_id)
            flush = (len(self._pending_release)
                     + len(self._pending_actor_release)) >= 64
            if flush:
                ids, aids = self._pending_release, self._pending_actor_release
                self._pending_release, self._pending_actor_release = [], []
        if flush:
            try:
                self._io.post(self._client.notify(
                    "client_release", {"ids": ids, "actor_ids": aids}
                ))
            except Exception:
                pass

    # ----------------------------------------------------------- public API

    def remote(self, obj=None, **opts):
        """Like ray_tpu.remote: decorate a function or class; with only
        keyword options, returns a decorator."""
        if obj is None:
            return lambda o: self.remote(o, **opts)
        if inspect.isclass(obj):
            return ClientActorClass(obj, self, opts)
        return ClientRemoteFunction(obj, self, opts)

    def put(self, value: Any) -> ClientObjectRef:
        r = self._call("client_put", {"data": self._dumps(value)})
        return ClientObjectRef(r["id"], self)

    def get(self, refs: Union[ClientObjectRef, Sequence[ClientObjectRef]],
            *, timeout: Optional[float] = None) -> Any:
        single = isinstance(refs, ClientObjectRef)
        ids = [refs.id] if single else [r.id for r in refs]
        r = self._call(
            "client_get", {"ids": ids, "timeout": timeout},
            timeout=None if timeout is None else timeout + 10,
        )
        values = self._loads(r["data"])
        return values[0] if single else values

    def wait(self, refs: Sequence[ClientObjectRef], *, num_returns: int = 1,
             timeout: Optional[float] = None
             ) -> Tuple[List[ClientObjectRef], List[ClientObjectRef]]:
        r = self._call("client_wait", {
            "ids": [x.id for x in refs],
            "num_returns": num_returns,
            "timeout": timeout,
        })
        by_id = {x.id: x for x in refs}
        return ([by_id[i] for i in r["ready"]],
                [by_id[i] for i in r["pending"]])

    def _task(self, fn_bytes, fn_id, opts, args, kwargs) -> ClientObjectRef:
        r = self._call("client_task", {
            "fn": fn_bytes,
            "fn_id": fn_id,
            "opts": opts,
            "args": self._dumps((list(args), kwargs)),
        })
        return ClientObjectRef(r["id"], self)

    def _create_actor(self, cls, opts, args, kwargs) -> ClientActorHandle:
        r = self._call("client_create_actor", {
            "cls": self._dumps(cls),
            "opts": opts,
            "args": self._dumps((list(args), kwargs)),
        })
        return ClientActorHandle(r["id"], self)

    def _actor_call(self, aid, method, args, kwargs) -> ClientObjectRef:
        r = self._call("client_actor_call", {
            "id": aid,
            "method": method,
            "args": self._dumps((list(args), kwargs)),
        })
        return ClientObjectRef(r["id"], self)

    def kill(self, handle: ClientActorHandle, *, no_restart: bool = True):
        self._call("client_kill_actor",
                   {"id": handle._id, "no_restart": no_restart})

    def get_actor(self, name: str) -> ClientActorHandle:
        r = self._call("client_get_actor", {"name": name})
        return ClientActorHandle(r["id"], self)

    def cluster_info(self) -> Dict[str, Any]:
        return self._call("client_cluster_info", {})

    def disconnect(self):
        try:
            with self._release_lock:
                ids = self._pending_release
                aids = self._pending_actor_release
                self._pending_release, self._pending_actor_release = [], []
            if ids or aids:
                self._io.run(self._client.notify(
                    "client_release", {"ids": ids, "actor_ids": aids}
                ))
        except Exception:
            pass
        self._io.run(self._client.close())


def connect(address: str) -> ClientContext:
    """Connect to a ClientServer at 'host:port'."""
    host, _, port = address.rpartition(":")
    return ClientContext(host or "127.0.0.1", int(port))
