"""Client proxy server: hosts a real driver on the cluster and serves the
remote-driver API (reference: python/ray/util/client/server/server.py — the
RayletServicer; our transport is the framework's msgpack RPC, not gRPC).

Run standalone:  python -m ray_tpu.util.client.server --address <gcs> --port N
or in-process:   ClientServer(port).start()  (requires ray_tpu.init first)

Blocking operations (get/wait/task results) run on a thread pool so the RPC
io-loop never stalls; the hosted CoreWorker's API is thread-safe.
"""

from __future__ import annotations

import asyncio
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict

import ray_tpu
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.rpc import IoThread, RpcServer
from ray_tpu.actor import ActorHandle
from ray_tpu.util.client.common import dumps_with_tickets, loads_with_tickets


def _actor_key(handle) -> bytes:
    aid = handle._actor_id
    return aid if isinstance(aid, bytes) else aid.binary()


class ClientServer:
    def __init__(self, port: int = 0, host: str = "0.0.0.0"):
        self._server = RpcServer(host)
        self._port = port
        self.port = None
        # Tables of live server-side objects, keyed by ticket id (bytes).
        self._refs: Dict[bytes, ObjectRef] = {}
        self._actors: Dict[bytes, ActorHandle] = {}
        self._fn_cache: Dict[bytes, Any] = {}
        self._lock = threading.Lock()
        self._pool = ThreadPoolExecutor(
            max_workers=16, thread_name_prefix="client-server"
        )

    # ------------------------------------------------------------ lifecycle

    def start(self) -> int:
        io = IoThread.current()
        self._server.register_all(self)
        self.port = io.run(self._server.start(self._port))
        return self.port

    def stop(self):
        io = IoThread.current()
        io.run(self._server.stop())
        self._pool.shutdown(wait=False)

    # -------------------------------------------------------- serialization

    def _ticket_of(self, obj):
        if isinstance(obj, ObjectRef):
            with self._lock:
                self._refs[obj.binary()] = obj
            return ("ref", obj.binary())
        if isinstance(obj, ActorHandle):
            aid = _actor_key(obj)
            with self._lock:
                self._actors[aid] = obj
            return ("actor", aid)
        return None

    def _resolve(self, pid):
        kind, rid = pid
        with self._lock:
            if kind == "ref":
                return self._refs[rid]
            if kind == "actor":
                return self._actors[rid]
        raise KeyError(f"unknown ticket kind {kind!r}")

    def _dumps(self, value) -> bytes:
        return dumps_with_tickets(value, self._ticket_of)

    def _loads(self, data: bytes):
        return loads_with_tickets(data, self._resolve)

    async def _blocking(self, fn, *args):
        return await asyncio.get_running_loop().run_in_executor(
            self._pool, fn, *args
        )

    # ------------------------------------------------------------- handlers

    async def handle_client_ping(self, payload):
        # NB: every handler runs ON the io loop; sync framework APIs
        # (ray_tpu.get/put/nodes/kill) post coroutines to that same loop and
        # block — so they must always go through the thread pool.
        n = await self._blocking(lambda: len(ray_tpu.nodes()))
        return {"ok": True, "num_nodes": n}

    async def handle_client_put(self, payload):
        value = self._loads(payload["data"])
        ref = await self._blocking(ray_tpu.put, value)
        with self._lock:
            self._refs[ref.binary()] = ref
        return {"id": ref.binary()}

    async def handle_client_get(self, payload):
        with self._lock:
            refs = [self._refs[i] for i in payload["ids"]]

        def do_get():
            return ray_tpu.get(refs, timeout=payload.get("timeout"))

        values = await self._blocking(do_get)
        return {"data": self._dumps(values)}

    async def handle_client_wait(self, payload):
        with self._lock:
            refs = [self._refs[i] for i in payload["ids"]]

        def do_wait():
            return ray_tpu.wait(
                refs,
                num_returns=payload["num_returns"],
                timeout=payload.get("timeout"),
            )

        ready, pending = await self._blocking(do_wait)
        return {
            "ready": [r.binary() for r in ready],
            "pending": [r.binary() for r in pending],
        }

    def _remote_fn(self, payload):
        key = payload.get("fn_id")
        fn = self._fn_cache.get(key)
        if fn is None:
            fn = self._loads(payload["fn"])
            if key:
                self._fn_cache[key] = fn
        opts = payload.get("opts") or {}
        return ray_tpu.remote(**opts)(fn) if opts else ray_tpu.remote(fn)

    async def handle_client_task(self, payload):
        rf = self._remote_fn(payload)
        args, kwargs = self._loads(payload["args"])
        ref = await self._blocking(lambda: rf.remote(*args, **kwargs))
        with self._lock:
            self._refs[ref.binary()] = ref
        return {"id": ref.binary()}

    async def handle_client_create_actor(self, payload):
        cls = self._loads(payload["cls"])
        opts = payload.get("opts") or {}
        actor_cls = ray_tpu.remote(**opts)(cls) if opts else ray_tpu.remote(cls)
        args, kwargs = self._loads(payload["args"])
        handle = await self._blocking(
            lambda: actor_cls.remote(*args, **kwargs)
        )
        aid = _actor_key(handle)
        with self._lock:
            self._actors[aid] = handle
        return {"id": aid}

    async def handle_client_actor_call(self, payload):
        with self._lock:
            handle = self._actors[payload["id"]]
        args, kwargs = self._loads(payload["args"])
        method = getattr(handle, payload["method"])
        ref = await self._blocking(lambda: method.remote(*args, **kwargs))
        with self._lock:
            self._refs[ref.binary()] = ref
        return {"id": ref.binary()}

    async def handle_client_kill_actor(self, payload):
        with self._lock:
            handle = self._actors.get(payload["id"])
        if handle is not None:
            await self._blocking(
                lambda: ray_tpu.kill(
                    handle, no_restart=payload.get("no_restart", True)
                )
            )
        return {}

    async def handle_client_get_actor(self, payload):
        handle = await self._blocking(
            lambda: ray_tpu.get_actor(payload["name"])
        )
        aid = _actor_key(handle)
        with self._lock:
            self._actors[aid] = handle
        return {"id": aid}

    async def handle_client_release(self, payload):
        with self._lock:
            for rid in payload.get("ids", []):
                self._refs.pop(rid, None)
            for aid in payload.get("actor_ids", []):
                self._actors.pop(aid, None)
        return {}

    async def handle_client_cluster_info(self, payload):
        return await self._blocking(lambda: {
            "nodes": len(ray_tpu.nodes()),
            "resources": ray_tpu.cluster_resources(),
            "available": ray_tpu.available_resources(),
        })


def main():
    import argparse
    import time

    ap = argparse.ArgumentParser()
    ap.add_argument("--address", default=None,
                    help="GCS address of an existing cluster (host:port); "
                         "omit to start a local cluster")
    ap.add_argument("--port", type=int, default=10001)
    ap.add_argument("--num-cpus", type=int, default=None)
    args = ap.parse_args()

    if args.address:
        ray_tpu.init(address=args.address)
    else:
        ray_tpu.init(num_cpus=args.num_cpus)
    srv = ClientServer(args.port)
    port = srv.start()
    print(f"client server listening on {port}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()


if __name__ == "__main__":
    main()
