"""Serializability inspection
(reference: python/ray/util/check_serialize.py inspect_serializability —
walk an object that fails to cloudpickle and report WHICH nested member is
the culprit, instead of an opaque pickling error)."""

from __future__ import annotations

import inspect
from typing import Any, List, Set, Tuple

import cloudpickle


class FailureTuple:
    def __init__(self, obj: Any, name: str, parent: str):
        self.obj = obj
        self.name = name
        self.parent = parent

    def __repr__(self):
        return f"FailureTuple({self.name!r} inside {self.parent!r})"


def _serializable(obj: Any) -> bool:
    try:
        cloudpickle.dumps(obj)
        return True
    except Exception:
        return False


def _children(obj: Any) -> List[Tuple[str, Any]]:
    out: List[Tuple[str, Any]] = []
    if inspect.isfunction(obj):
        if obj.__closure__:
            names = obj.__code__.co_freevars
            for name, cell in zip(names, obj.__closure__):
                try:
                    out.append((name, cell.cell_contents))
                except ValueError:
                    pass
        out.extend((k, v) for k, v in (obj.__globals__ or {}).items()
                   if k in obj.__code__.co_names
                   and not inspect.ismodule(v))
    elif isinstance(obj, dict):
        out.extend((str(k), v) for k, v in obj.items())
    elif isinstance(obj, (list, tuple, set)):
        out.extend((f"[{i}]", v) for i, v in enumerate(obj))
    elif hasattr(obj, "__dict__") and not inspect.isclass(obj):
        out.extend(obj.__dict__.items())
    return out


def _inspect(obj: Any, name: str, parent: str, depth: int,
             seen: Set[int]) -> List[FailureTuple]:
    """Failures under obj; each names its enclosing container correctly.
    A child with no identifiable failing members IS the culprit."""
    failures: List[FailureTuple] = []
    if depth > 0:
        for child_name, child in _children(obj):
            if id(child) in seen:
                continue
            seen.add(id(child))
            if not _serializable(child):
                deeper = _inspect(child, child_name, name, depth - 1, seen)
                failures.extend(deeper)
    if not failures:
        failures.append(FailureTuple(obj, name, parent))
    return failures


def inspect_serializability(obj: Any, name: str = None, *,
                            print_failures: bool = True
                            ) -> Tuple[bool, List[FailureTuple]]:
    """Returns (is_serializable, failures). Each failure names the deepest
    non-serializable member found and the container holding it."""
    name = name or getattr(obj, "__name__", type(obj).__name__)
    if _serializable(obj):
        return True, []
    failures = _inspect(obj, name, name, depth=3, seen={id(obj)})
    if print_failures:
        for f in failures:
            print(f"  !!! {f.name!r} (inside {f.parent!r}) is not "
                  f"serializable: {type(f.obj)}")
    return False, failures


check_serializability = inspect_serializability
