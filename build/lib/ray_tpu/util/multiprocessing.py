"""multiprocessing.Pool shim over actors
(reference: python/ray/util/multiprocessing/pool.py — drop-in Pool whose
workers are cluster actors, so `Pool(8).map(f, xs)` scales past one host).

Supported surface: map/map_async/starmap/starmap_async/apply/apply_async/
imap/imap_unordered, context manager, close/terminate/join.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


class AsyncResult:
    """multiprocessing.pool.AsyncResult lookalike over object refs."""

    def __init__(self, refs, single: bool):
        self._refs = refs
        self._single = single

    def get(self, timeout: Optional[float] = None):
        values = ray_tpu.get(self._refs, timeout=timeout)
        return values[0] if self._single else values

    def wait(self, timeout: Optional[float] = None):
        ray_tpu.wait(self._refs, num_returns=len(self._refs),
                     timeout=timeout)

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                                timeout=0)
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        if not self.ready():
            raise ValueError("result is not ready")
        try:
            ray_tpu.get(self._refs, timeout=0)
            return True
        except Exception:
            return False


class _PoolWorker:
    def run(self, fn, args, kwargs):
        return fn(*args, **(kwargs or {}))

    def run_batch(self, fn, chunk):
        return [fn(*args) for args in chunk]


class Pool:
    def __init__(self, processes: Optional[int] = None, *,
                 ray_actor_options: Optional[dict] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init(ignore_reinit_error=True)
        if processes is None:
            total = ray_tpu.cluster_resources().get("CPU", 1)
            processes = max(1, int(total))
        opts = dict(ray_actor_options or {})
        opts.setdefault("num_cpus", 1)
        cls = ray_tpu.remote(_PoolWorker)
        self._actors = [cls.options(**opts).remote()
                        for _ in range(processes)]
        self._rr = itertools.cycle(range(processes))
        self._closed = False
        self._inflight: List[Any] = []

    # ------------------------------------------------------------- plumbing

    def _check(self):
        if self._closed:
            raise ValueError("Pool not running")

    def _next(self):
        return self._actors[next(self._rr)]

    @staticmethod
    def _star(iterable) -> List[tuple]:
        return [args if isinstance(args, tuple) else (args,)
                for args in iterable]

    def _submit_chunks(self, func: Callable, items: List[tuple],
                       chunksize: Optional[int]) -> List[Any]:
        if chunksize is None:
            chunksize = max(1, len(items) // (len(self._actors) * 4) or 1)
        refs = []
        for i in range(0, len(items), chunksize):
            chunk = items[i:i + chunksize]
            refs.append(self._next().run_batch.remote(func, chunk))
        self._inflight.extend(refs)
        return refs

    # --------------------------------------------------------------- public

    def apply(self, func: Callable, args: tuple = (), kwds: dict = None):
        return self.apply_async(func, args, kwds).get()

    def apply_async(self, func: Callable, args: tuple = (),
                    kwds: dict = None) -> AsyncResult:
        self._check()
        ref = self._next().run.remote(func, tuple(args), kwds or {})
        self._inflight.append(ref)
        return AsyncResult([ref], single=True)

    def map(self, func: Callable, iterable: Iterable,
            chunksize: Optional[int] = None) -> List[Any]:
        return self.starmap(func, [(x,) for x in iterable], chunksize)

    def map_async(self, func: Callable, iterable: Iterable,
                  chunksize: Optional[int] = None) -> AsyncResult:
        return self.starmap_async(func, [(x,) for x in iterable], chunksize)

    def starmap(self, func: Callable, iterable: Iterable,
                chunksize: Optional[int] = None) -> List[Any]:
        self._check()
        items = self._star(iterable)
        refs = self._submit_chunks(func, items, chunksize)
        out: List[Any] = []
        for chunk in ray_tpu.get(refs):
            out.extend(chunk)
        return out

    def starmap_async(self, func: Callable, iterable: Iterable,
                      chunksize: Optional[int] = None) -> AsyncResult:
        self._check()
        items = self._star(iterable)
        refs = self._submit_chunks(func, items, chunksize)

        class _Flat(AsyncResult):
            def get(self, timeout: Optional[float] = None):
                out: List[Any] = []
                for chunk in ray_tpu.get(self._refs, timeout=timeout):
                    out.extend(chunk)
                return out

        return _Flat(refs, single=False)

    def _lazy_chunks(self, func: Callable, iterable: Iterable,
                     chunksize: int, window: int):
        """Generator of chunk refs, submitting at most `window` ahead of
        consumption — imap over an infinite/huge iterable streams instead
        of materializing (multiprocessing.Pool.imap laziness)."""
        it = iter(iterable)
        inflight: List[Any] = []
        while True:
            while len(inflight) < window:
                chunk = [(x,) for x in itertools.islice(it, chunksize)]
                if not chunk:
                    break
                ref = self._next().run_batch.remote(func, chunk)
                self._inflight.append(ref)
                inflight.append(ref)
            if not inflight:
                return
            yield inflight.pop(0)

    def imap(self, func: Callable, iterable: Iterable,
             chunksize: int = 1):
        """Ordered lazy iteration: a bounded window of chunks is in flight
        while earlier results stream out."""
        self._check()
        window = max(2, len(self._actors) * 2)
        for ref in self._lazy_chunks(func, iterable, chunksize, window):
            yield from ray_tpu.get(ref)

    def imap_unordered(self, func: Callable, iterable: Iterable,
                       chunksize: int = 1):
        self._check()
        window = max(2, len(self._actors) * 2)
        pending: List[Any] = []
        gen = self._lazy_chunks(func, iterable, chunksize, window)
        exhausted = False
        while True:
            while not exhausted and len(pending) < window:
                try:
                    pending.append(next(gen))
                except StopIteration:
                    exhausted = True
            if not pending:
                return
            ready, pending = ray_tpu.wait(pending, num_returns=1)
            yield from ray_tpu.get(ready[0])

    # ------------------------------------------------------------ lifecycle

    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        self._actors = []

    def join(self):
        """Barrier: wait for all submitted work to finish
        (multiprocessing.Pool.join semantics; requires close() first)."""
        if not self._closed:
            raise ValueError("Pool is still running")
        if self._inflight:
            try:
                ray_tpu.wait(self._inflight,
                             num_returns=len(self._inflight))
            except Exception:
                pass
            self._inflight = []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()
