"""Device-mesh construction and sharding-rule utilities.

This is the TPU-native substitute for the reference's NCCL process groups
(reference: python/ray/util/collective/collective_group/nccl_collective_group.py):
instead of creating communicator handles and calling collectives imperatively,
we build a `jax.sharding.Mesh` over the slice's devices, annotate arrays with
`NamedSharding`s, and let XLA insert ICI collectives during compilation
(psum/all-gather/reduce-scatter chosen by the partitioner).

Axis conventions used across the framework:
  dp    — data parallel (batch dimension)
  fsdp  — parameter/optimizer sharding (ZeRO-style), usually merged with dp
  tp    — tensor parallel (hidden/heads dimension)
  sp    — sequence/context parallel (ring attention rides this axis)
  ep    — expert parallel (MoE)
  pp    — pipeline stages (handled by the compiled-DAG layer, not the mesh)
"""

from __future__ import annotations

import math
import re
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    axes: Dict[str, int],
    *,
    devices: Optional[Sequence] = None,
    allow_split_physical: bool = True,
) -> Mesh:
    """Build a Mesh with the given axis sizes (-1 once to mean 'the rest').

    Axis order in `axes` is the layout order: the last axis varies fastest over
    the device list, so put the most bandwidth-hungry axis (tp, then dp) last —
    adjacent devices share the fastest ICI links.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    sizes = dict(axes)
    unknown = [k for k, v in sizes.items() if v == -1]
    if len(unknown) > 1:
        raise ValueError("only one axis may be -1")
    if unknown:
        known = math.prod(v for v in sizes.values() if v != -1)
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        sizes[unknown[0]] = n // known
    total = math.prod(sizes.values())
    if total != n:
        raise ValueError(f"mesh axes {sizes} need {total} devices, have {n}")
    arr = np.array(devices).reshape(*sizes.values())
    return Mesh(arr, tuple(sizes.keys()))


def single_axis_mesh(name: str = "dp", devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.array(devices), (name,))


class ShardingRules:
    """Map parameter-path regexes to PartitionSpecs.

    Rules are checked in order; first match wins. Paths are '/'-joined pytree
    key paths, e.g. 'transformer/h_3/attn/c_attn/kernel'.
    """

    def __init__(self, rules: Sequence[Tuple[str, P]], default: P = P()):
        self._rules = [(re.compile(pat), spec) for pat, spec in rules]
        self._default = default

    def spec_for(self, path: str) -> P:
        for pat, spec in self._rules:
            if pat.search(path):
                return spec
        return self._default

    def tree_specs(self, tree):
        """PartitionSpec pytree matching `tree`'s structure."""
        flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
        specs = []
        for keypath, _leaf in flat:
            path = "/".join(_key_str(k) for k in keypath)
            specs.append(self.spec_for(path))
        return jax.tree_util.tree_unflatten(treedef, specs)

    def tree_shardings(self, tree, mesh: Mesh):
        specs = self.tree_specs(tree)
        return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                            is_leaf=lambda x: isinstance(x, P))


def _key_str(k) -> str:
    if hasattr(k, "key"):
        return str(k.key)
    if hasattr(k, "idx"):
        return str(k.idx)
    return str(k)


def filter_spec_for_mesh(spec: P, mesh: Mesh) -> P:
    """Drop axis names the mesh doesn't have (lets one rule set serve many
    mesh shapes — e.g. tp rules are no-ops on a pure-dp mesh)."""
    def keep(entry):
        if entry is None:
            return None
        if isinstance(entry, (tuple, list)):
            kept = tuple(e for e in entry if e in mesh.axis_names and mesh.shape[e] > 1)
            return kept if kept else None
        return entry if entry in mesh.axis_names and mesh.shape[entry] > 1 else None

    return P(*(keep(e) for e in spec))


def filtered_tree_specs(rules: ShardingRules, tree, mesh: Mesh):
    """Rule-derived PartitionSpecs with axes the mesh lacks dropped."""
    specs = rules.tree_specs(tree)
    return jax.tree.map(lambda s: filter_spec_for_mesh(s, mesh), specs,
                        is_leaf=lambda x: isinstance(x, P))


def filtered_tree_shardings(rules: ShardingRules, tree, mesh: Mesh):
    specs = filtered_tree_specs(rules, tree, mesh)
    return specs, jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                               is_leaf=lambda x: isinstance(x, P))


def shard_tree(tree, mesh: Mesh, rules: ShardingRules):
    """device_put a pytree with rule-derived (mesh-filtered) shardings."""
    _, shardings = filtered_tree_shardings(rules, tree, mesh)
    return jax.device_put(tree, shardings), shardings


def batch_sharding(mesh: Mesh, *, data_axes=("dp", "fsdp"), seq_axis="sp") -> NamedSharding:
    """Sharding for a [batch, seq, ...] input batch."""
    data = tuple(a for a in data_axes if a in mesh.axis_names and mesh.shape[a] > 1)
    seq = seq_axis if seq_axis in mesh.axis_names and mesh.shape[seq_axis] > 1 else None
    return NamedSharding(mesh, P(data if data else None, seq))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def local_slice_info() -> dict:
    """Topology of the slice this process sees."""
    devs = jax.devices()
    return {
        "num_devices": len(devs),
        "num_local_devices": len(jax.local_devices()),
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "platform": devs[0].platform if devs else "none",
        "device_kind": devs[0].device_kind if devs else "",
    }
