"""Mesh construction and sharding-rule helpers for pjit/shard_map."""
