"""Pipeline parallelism (pp) for the transformer stack, jax-idiomatic.

The reference's pipeline story is mechanism-level: compiled actor DAGs
moving tensors between stage actors over NCCL channels
(reference: python/ray/dag/compiled_dag_node.py:391,
experimental/channel/torch_tensor_nccl_channel.py:191). On TPU the idiomatic
equivalent *inside one jit* is a mesh axis: transformer blocks stack along a
leading layer dim sharded over the `pp` axis, and a GPipe microbatch
schedule runs as a `lax.scan` over clock ticks with `lax.ppermute` shifting
activations stage-to-stage over ICI. Autodiff through scan+ppermute gives
the pipeline backward pass for free (the transpose of a ppermute is the
reverse ppermute), so one `jax.value_and_grad` covers the whole 1F-then-1B
schedule without hand-written bubbles.

Layout: `pp` shards the stacked block params' leading (layer) dim; `dp`
shards the batch. Embedding and head run outside the pipeline region,
replicated over pp (a production deployment would pin them to the first and
last stage; at dryrun scale replication is clearer and costs one broadcast).

For cross-HOST pipelining where the stages cannot share one jit program,
the compiled-DAG socket channels (ray_tpu/experimental/channel.py
SocketChannel) carry the stage handoffs instead — this module is the
within-slice (ICI) path.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.models.gpt2 import GPT2Config, Block, loss_fn


def _stack_layers(per_layer_params):
    """[{layer params}...] -> one pytree with a leading layer dim."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_layer_params)


def pipeline_apply(mesh: Mesh, block_apply, stacked, h, num_micro: int):
    """Run `h` through pp-sharded stacked blocks with a GPipe schedule.

    mesh must have a `pp` axis; `dp` (if present) shards the batch dim of h.
    block_apply(layer_params, x) -> x applies ONE block. stacked is the
    full [n_layer, ...] parameter stack (sharded on dim 0 over pp).
    """
    pp = mesh.shape["pp"]
    has_dp = "dp" in mesh.axis_names and mesh.shape["dp"] > 1
    dp_spec = "dp" if has_dp else None

    def run_stack(local_stack, x):
        # my stage's n_layer/pp blocks, sequentially (scan over layers)
        def body(xc, p):
            return block_apply(p, xc), None

        out, _ = jax.lax.scan(body, x, local_stack)
        return out

    def stage(local_stack, h_loc):
        r = jax.lax.axis_index("pp")
        Bl, T, D = h_loc.shape
        mb = Bl // num_micro
        hm = h_loc.reshape(num_micro, mb, T, D)
        ticks = num_micro + pp - 1

        outs0 = jnp.zeros_like(hm)
        recv0 = jnp.zeros_like(hm[0])

        def tick(carry, t):
            recv, outs = carry
            # stage 0 ingests microbatch t; later stages take the ppermuted
            # output of their predecessor from the previous tick
            ingest = hm[jnp.clip(t, 0, num_micro - 1)]
            x = jnp.where(r == 0, ingest, recv)
            y = run_stack(local_stack, x)
            recv_next = jax.lax.ppermute(
                y, "pp", [(i, i + 1) for i in range(pp - 1)]
            )
            # the last stage finishes microbatch t-(pp-1) at tick t
            out_idx = t - (pp - 1)
            valid = (out_idx >= 0) & (r == pp - 1)
            idx = jnp.clip(out_idx, 0, num_micro - 1)
            outs = jnp.where(valid, outs.at[idx].set(y), outs)
            return (recv_next, outs), None

        (_, outs), _ = jax.lax.scan(
            tick, (recv0, outs0), jnp.arange(ticks)
        )
        # replicate the last stage's result over pp so the (replicated)
        # head/loss downstream sees identical values on every pp rank
        outs = jnp.where(r == pp - 1, outs, jnp.zeros_like(outs))
        outs = jax.lax.psum(outs, "pp")
        return outs.reshape(Bl, T, D)

    specs_stack = jax.tree.map(lambda _: P("pp"), stacked)
    fn = shard_map(
        stage,
        mesh=mesh,
        in_specs=(specs_stack, P(dp_spec, None, None)),
        out_specs=P(dp_spec, None, None),
        check_vma=False,
    )
    return fn(stacked, h)


class PipelineTrainStep:
    """Compiled (init, step) for GPT-2 on a (dp, pp) mesh.

    The counterpart of parallel.train_step.TrainStep for the pipeline axis:
    same state dict shape ({params, opt_state, step}), same step contract
    (state, {idx, targets}) -> (state, metrics).
    """

    def __init__(
        self,
        model_cfg: GPT2Config,
        mesh: Mesh,
        *,
        num_microbatches: Optional[int] = None,
        learning_rate: float = 3e-4,
        weight_decay: float = 0.1,
        grad_clip: float = 1.0,
    ):
        if "pp" not in mesh.axis_names:
            raise ValueError("PipelineTrainStep needs a 'pp' mesh axis")
        pp = mesh.shape["pp"]
        if model_cfg.n_layer % pp:
            raise ValueError(
                f"n_layer={model_cfg.n_layer} not divisible by pp={pp}"
            )
        self.model_cfg = model_cfg
        self.mesh = mesh
        self.pp = pp
        self.num_micro = num_microbatches or 2 * pp
        def decay_mask(params):
            # Stacking adds a leading layer dim, so inside `blocks` a bias
            # is 2-D and a kernel 3-D; the decay rule must match the
            # unstacked TrainStep (decay kernels, not biases/norms).
            def f(path, p):
                keys = [getattr(k, "key", "") for k in path]
                return p.ndim > (2 if "blocks" in keys else 1)

            return jax.tree_util.tree_map_with_path(f, params)

        self.optimizer = optax.chain(
            optax.clip_by_global_norm(grad_clip),
            optax.adamw(
                learning_rate, weight_decay=weight_decay, mask=decay_mask,
            ),
        )
        cfg = model_cfg
        block = Block(cfg)
        embed_dim = cfg.n_embd

        def init_fn(rng):
            T = min(8, cfg.block_size)
            k_wte, k_wpe, k_blocks, k_lnf = jax.random.split(rng, 4)
            wte = jax.random.normal(
                k_wte, (cfg.vocab_size, embed_dim), jnp.float32
            ) * 0.02
            wpe = jax.random.normal(
                k_wpe, (cfg.block_size, embed_dim), jnp.float32
            ) * 0.02
            x = jnp.zeros((2, T, embed_dim), cfg.dtype)
            per_layer = [
                block.init(jax.random.fold_in(k_blocks, i), x)["params"]
                for i in range(cfg.n_layer)
            ]
            params = {
                "wte": wte,
                "wpe": wpe,
                "blocks": _stack_layers(per_layer),
                "ln_f": {
                    "scale": jnp.ones((embed_dim,), jnp.float32),
                    "bias": jnp.zeros((embed_dim,), jnp.float32),
                },
            }
            return {
                "params": params,
                "opt_state": self.optimizer.init(params),
                "step": jnp.zeros((), jnp.int32),
            }

        # shardings: stacked blocks on pp (dim 0), everything else
        # replicated; batch on dp
        state_shape = jax.eval_shape(init_fn, jax.random.PRNGKey(0))

        def spec_of(path, _leaf):
            keys = [getattr(k, "key", getattr(k, "name", "")) for k in path]
            return P("pp") if "blocks" in keys else P()

        self.state_specs = jax.tree_util.tree_map_with_path(
            spec_of, state_shape
        )
        self.state_shardings = jax.tree.map(
            lambda s: NamedSharding(mesh, s), self.state_specs,
            is_leaf=lambda x: isinstance(x, P),
        )
        self._init = jax.jit(init_fn, out_shardings=self.state_shardings)

        has_dp = "dp" in mesh.axis_names and mesh.shape["dp"] > 1
        self.batch_sharding = NamedSharding(
            mesh, P("dp" if has_dp else None, None)
        )

        def block_apply(p, x):
            return block.apply({"params": p}, x)

        def forward(params, idx):
            B, T = idx.shape
            h = (
                params["wte"].astype(cfg.dtype)[idx]
                + params["wpe"].astype(cfg.dtype)[jnp.arange(T)][None]
            )
            h = pipeline_apply(
                mesh, block_apply, params["blocks"], h, self.num_micro
            )
            mean = h.mean(-1, keepdims=True)
            var = ((h - mean) ** 2).mean(-1, keepdims=True)
            h = (h - mean) * jax.lax.rsqrt(var + 1e-5)
            h = h * params["ln_f"]["scale"] + params["ln_f"]["bias"]
            return h.astype(jnp.float32) @ params["wte"].T  # tied head

        self.forward = forward

        def step_fn(state, batch):
            def loss_of(params):
                logits = forward(params, batch["idx"])
                return loss_fn(logits, batch["targets"])

            loss, grads = jax.value_and_grad(loss_of)(state["params"])
            updates, opt_state = self.optimizer.update(
                grads, state["opt_state"], state["params"]
            )
            params = optax.apply_updates(state["params"], updates)
            return (
                {"params": params, "opt_state": opt_state,
                 "step": state["step"] + 1},
                {"loss": loss, "grad_norm": optax.global_norm(grads)},
            )

        self._step = jax.jit(
            step_fn,
            out_shardings=(self.state_shardings, None),
            donate_argnums=(0,),
        )
        self._traced = False

    def init(self, rng):
        with self.mesh:
            return self._init(rng)

    def shard_batch(self, batch):
        return jax.device_put(batch, self.batch_sharding)

    def step(self, state, batch):
        B = batch["idx"].shape[0]
        dp = self.mesh.shape.get("dp", 1)
        if B % dp or (B // dp) % self.num_micro:
            raise ValueError(
                f"batch size {B} must divide by dp={dp} and the per-shard "
                f"batch ({B // dp if B % dp == 0 else '?'}) by "
                f"num_microbatches={self.num_micro}; pass a compatible "
                "batch size or num_microbatches to PipelineTrainStep"
            )
        if self._traced:
            return self._step(state, batch)
        with self.mesh:
            out = self._step(state, batch)
        self._traced = True
        return out
