"""Multi-node-on-one-machine test cluster.

Counterpart of the reference's ray.cluster_utils.Cluster
(reference: python/ray/cluster_utils.py:135) — the single highest-leverage
test asset: N raylets as real separate processes on one machine, each
pretending to be a node, sharing one GCS. Used by multi-node scheduling,
spillback, object-transfer and failure tests without real machines.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ray_tpu._private.node import Node, new_session_dir


class Cluster:
    def __init__(
        self,
        initialize_head: bool = True,
        connect: bool = False,
        head_node_args: Optional[dict] = None,
    ):
        self.session_dir = new_session_dir()
        self.nodes: List[Node] = []
        self.head_node: Optional[Node] = None
        self.gcs_address: Optional[str] = None
        if initialize_head:
            self.head_node = Node(
                head=True, session_dir=self.session_dir, node_name="head",
                **(head_node_args or {}),
            )
            self.nodes.append(self.head_node)
            self.gcs_address = self.head_node.gcs_address
            if connect:
                self.connect()

    @property
    def address(self) -> str:
        return self.gcs_address

    def connect(self):
        import ray_tpu

        ray_tpu.init(address=self.gcs_address)

    def add_node(
        self,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        object_store_memory: Optional[int] = None,
        node_name: str = "",
        **kwargs,
    ) -> Node:
        node = Node(
            head=False,
            gcs_address=self.gcs_address,
            resources=resources,
            labels=labels,
            object_store_memory=object_store_memory,
            session_dir=self.session_dir,
            node_name=node_name or f"node{len(self.nodes)}",
        )
        self.nodes.append(node)
        return node

    def remove_node(self, node: Node, allow_graceful: bool = True):
        node.shutdown()
        if node in self.nodes:
            self.nodes.remove(node)

    def wait_for_nodes(self, timeout: float = 30.0):
        """Block until every started node is ALIVE in the GCS."""
        from ray_tpu._private.gcs.client import GcsClient

        gcs = GcsClient.from_address(self.gcs_address)
        deadline = time.time() + timeout
        want = len(self.nodes)
        while time.time() < deadline:
            alive = [n for n in gcs.get_all_node_info() if n["state"] == "ALIVE"]
            if len(alive) >= want:
                return
            time.sleep(0.1)
        raise TimeoutError(f"only {len(alive)}/{want} nodes alive after {timeout}s")

    def shutdown(self):
        import ray_tpu

        if ray_tpu.is_initialized():
            ray_tpu.shutdown()
        for node in self.nodes:
            node.shutdown()
        self.nodes.clear()
