"""PPO (reference: rllib/algorithms/ppo/ppo.py:401 + config :67, new-stack
shape: EnvRunnerGroup sampling + LearnerGroup update per training_step
:1674). CPU rollouts feed a jax learner whose update is pjit-compiled over
the device mesh — the reference's torch-DDP learner re-designed TPU-first.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, Optional

import numpy as np


class PPOConfig:
    def __init__(self):
        self.env_name: Optional[str] = None
        self.num_env_runners = 2
        self.num_envs_per_runner = 8
        self.rollout_fragment_length = 128
        self.lr = 3e-4
        self.gamma = 0.99
        self.lambda_ = 0.95
        self.clip = 0.2
        self.vf_coeff = 0.5
        self.entropy_coeff = 0.01
        self.num_epochs = 4
        self.minibatch_size = 512
        self.hidden = (64, 64)
        self.seed = 0
        self.remote_learner = True

    # Fluent sections mirroring the reference AlgorithmConfig.
    def environment(self, env: str) -> "PPOConfig":
        self.env_name = env
        return self

    def env_runners(self, *, num_env_runners: Optional[int] = None,
                    num_envs_per_env_runner: Optional[int] = None,
                    rollout_fragment_length: Optional[int] = None
                    ) -> "PPOConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, *, lr=None, gamma=None, lambda_=None, clip_param=None,
                 vf_loss_coeff=None, entropy_coeff=None, num_epochs=None,
                 minibatch_size=None, model_hidden=None) -> "PPOConfig":
        for name, val in [("lr", lr), ("gamma", gamma), ("lambda_", lambda_),
                          ("clip", clip_param), ("vf_coeff", vf_loss_coeff),
                          ("entropy_coeff", entropy_coeff),
                          ("num_epochs", num_epochs),
                          ("minibatch_size", minibatch_size),
                          ("hidden", model_hidden)]:
            if val is not None:
                setattr(self, name, val)
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "PPOConfig":
        if seed is not None:
            self.seed = seed
        return self

    def build(self) -> "PPO":
        assert self.env_name, "call .environment(env_name) first"
        return PPO(self)


class PPO:
    """The algorithm driver (a Tune trainable shape: train() returns a result
    dict per iteration)."""

    def __init__(self, config: PPOConfig):
        from ray_tpu.rllib.core.learner import LearnerGroup
        from ray_tpu.rllib.env.env_runner import EnvRunnerGroup

        self.config = config
        self.env_runner_group = EnvRunnerGroup(
            config.env_name,
            num_runners=config.num_env_runners,
            num_envs_per_runner=config.num_envs_per_runner,
            gamma=config.gamma, lambda_=config.lambda_, seed=config.seed,
        )
        obs_dim, num_actions = self.env_runner_group.obs_and_action_dims()
        self.learner_group = LearnerGroup(
            obs_dim, num_actions,
            config={
                "lr": config.lr, "clip": config.clip,
                "vf_coeff": config.vf_coeff,
                "entropy_coeff": config.entropy_coeff,
                "hidden": config.hidden, "seed": config.seed,
            },
            remote=config.remote_learner,
        )
        self._weights = self.learner_group.get_weights()
        self._iteration = 0
        self._recent_returns: deque = deque(maxlen=100)
        self._timesteps = 0

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        batch = self.env_runner_group.sample(
            self._weights, cfg.rollout_fragment_length
        )
        episode_returns = batch.pop("episode_returns")
        self._recent_returns.extend(episode_returns.tolist())
        self._timesteps += len(batch["obs"])
        losses = self.learner_group.update_from_batch(
            batch, num_epochs=cfg.num_epochs,
            minibatch_size=cfg.minibatch_size,
            seed=cfg.seed + self._iteration,
        )
        self._weights = self.learner_group.get_weights()
        return losses

    def train(self) -> Dict[str, Any]:
        t0 = time.perf_counter()
        losses = self.training_step()
        self._iteration += 1
        mean_ret = (float(np.mean(self._recent_returns))
                    if self._recent_returns else 0.0)
        return {
            "training_iteration": self._iteration,
            "episode_return_mean": mean_ret,
            "num_env_steps_sampled_lifetime": self._timesteps,
            "time_this_iter_s": time.perf_counter() - t0,
            **{f"learner/{k}": v for k, v in losses.items()},
        }

    def get_weights(self):
        return self._weights

    def save(self, checkpoint_dir: Optional[str] = None) -> str:
        """Persist weights + config + counters (reference:
        Algorithm.save / Checkpointable)."""
        import os
        import tempfile

        import cloudpickle

        path = checkpoint_dir or tempfile.mkdtemp(prefix="ppo_ckpt_")
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "algorithm_state.pkl"), "wb") as f:
            cloudpickle.dump({
                "algo": "PPO",
                "config": self.config,
                "weights": self._weights,
                "iteration": self._iteration,
                "timesteps": self._timesteps,
            }, f)
        return path

    def restore(self, checkpoint_path: str, _state: dict = None):
        import os

        import cloudpickle

        if _state is not None:
            state = _state
        else:
            with open(os.path.join(checkpoint_path, "algorithm_state.pkl"),
                      "rb") as f:
                state = cloudpickle.load(f)
        self._weights = state["weights"]
        self._iteration = state["iteration"]
        self._timesteps = state["timesteps"]
        self.learner_group.set_weights(self._weights)
        return self

    @classmethod
    def from_checkpoint(cls, checkpoint_path: str) -> "PPO":
        import os

        import cloudpickle

        with open(os.path.join(checkpoint_path, "algorithm_state.pkl"),
                  "rb") as f:
            state = cloudpickle.load(f)
        algo = cls(state["config"])
        return algo.restore(checkpoint_path, _state=state)

    def stop(self):
        self.env_runner_group.shutdown()
        self.learner_group.shutdown()
