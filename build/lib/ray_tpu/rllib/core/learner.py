"""JaxLearner + LearnerGroup (reference: rllib/core/learner/learner.py:114,
learner_group.py:83, torch_learner.py:254 — the torch-DDP gradient sync is
replaced by a pjit'd update over a jax device Mesh, with the batch sharded on
the dp axis and XLA inserting the gradient collectives).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

import ray_tpu


class JaxLearner:
    """Owns params/optimizer on a device mesh; PPO clipped-surrogate update
    compiled once and minibatch-stepped per epoch."""

    def __init__(self, obs_dim: int, num_actions: int, *,
                 lr: float = 3e-4, clip: float = 0.2, vf_coeff: float = 0.5,
                 entropy_coeff: float = 0.01, hidden=(64, 64), seed: int = 0,
                 mesh_devices: Optional[int] = None):
        import jax
        import jax.numpy as jnp
        import optax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from ray_tpu.rllib.core.rl_module import ActorCriticModule

        self.module = ActorCriticModule(num_actions=num_actions,
                                        hidden=tuple(hidden))
        self.params = self.module.init_params(obs_dim, seed)
        self.opt = optax.adam(lr)
        self.opt_state = self.opt.init(self.params)

        devices = jax.devices()[:mesh_devices] if mesh_devices else jax.devices()
        self.mesh = Mesh(np.array(devices), ("dp",))
        self._batch_sharding = NamedSharding(self.mesh, P("dp"))
        self._replicated = NamedSharding(self.mesh, P())
        module = self.module

        def loss_fn(params, batch):
            logits, v = module.apply({"params": params}, batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None], axis=-1
            )[:, 0]
            ratio = jnp.exp(logp - batch["logp_old"])
            adv = batch["advantages"]
            unclipped = ratio * adv
            clipped = jnp.clip(ratio, 1 - clip, 1 + clip) * adv
            pi_loss = -jnp.mean(jnp.minimum(unclipped, clipped))
            vf_loss = jnp.mean((v - batch["returns"]) ** 2)
            entropy = -jnp.mean(
                jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1)
            )
            total = pi_loss + vf_coeff * vf_loss - entropy_coeff * entropy
            return total, {"pi_loss": pi_loss, "vf_loss": vf_loss,
                           "entropy": entropy}

        def update_fn(params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch
            )
            updates, opt_state = self.opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            aux["total_loss"] = loss
            return params, opt_state, aux

        # Batch sharded over dp; params/opt replicated — XLA inserts the
        # psum for the gradient reduction (the NCCL-DDP equivalent).
        self._update = jax.jit(
            update_fn,
            in_shardings=(self._replicated, self._replicated,
                          self._batch_sharding),
            out_shardings=(self._replicated, self._replicated, None),
        )

    def _pad_to_devices(self, batch: Dict[str, np.ndarray]):
        import jax

        n = len(batch["obs"])
        d = self.mesh.size
        pad = (-n) % d
        if pad:
            batch = {
                k: np.concatenate([v, np.repeat(v[-1:], pad, axis=0)])
                for k, v in batch.items()
            }
        return jax.device_put(batch, self._batch_sharding)

    def update_from_batch(self, batch: Dict[str, np.ndarray], *,
                          num_epochs: int = 4, minibatch_size: int = 512,
                          seed: int = 0) -> Dict[str, float]:
        """Minibatch-SGD over the rollout batch (reference:
        Learner.update_from_batch :913)."""
        n = len(batch["obs"])
        adv = batch["advantages"]
        batch = dict(batch)
        batch["advantages"] = (adv - adv.mean()) / (adv.std() + 1e-8)
        rng = np.random.default_rng(seed)
        aux: Dict[str, Any] = {}
        for _ in range(num_epochs):
            perm = rng.permutation(n)
            for i in range(0, n, minibatch_size):
                idx = perm[i:i + minibatch_size]
                mb = {k: v[idx] for k, v in batch.items()}
                self.params, self.opt_state, aux = self._update(
                    self.params, self.opt_state, self._pad_to_devices(mb)
                )
        return {k: float(v) for k, v in aux.items()}

    def get_weights(self):
        import jax

        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights):
        """Load a host-side weight pytree onto the mesh (checkpoint
        restore; opt state restarts fresh like the reference's
        from_checkpoint on a new Learner)."""
        import jax

        self.params = jax.device_put(weights, self._replicated)
        self.opt_state = self.opt.init(self.params)
        return True

    def num_devices(self) -> int:
        return self.mesh.size


class LearnerGroup:
    """One (or more) learner actors (reference: learner_group.py:83 — remote
    learners). A single jax learner already spans its whole mesh; multiple
    learners would map to multi-host via jax.distributed."""

    def __init__(self, obs_dim: int, num_actions: int, *, config: dict,
                 remote: bool = True):
        learner_cls = ray_tpu.remote(JaxLearner)
        kw = dict(
            lr=config.get("lr", 3e-4), clip=config.get("clip", 0.2),
            vf_coeff=config.get("vf_coeff", 0.5),
            entropy_coeff=config.get("entropy_coeff", 0.01),
            hidden=config.get("hidden", (64, 64)),
            seed=config.get("seed", 0),
        )
        if remote:
            self._actor = learner_cls.options(num_cpus=1).remote(
                obs_dim, num_actions, **kw
            )
            self._local = None
        else:
            self._actor = None
            self._local = JaxLearner(obs_dim, num_actions, **kw)

    def update_from_batch(self, batch, **kw) -> Dict[str, float]:
        if self._local is not None:
            return self._local.update_from_batch(batch, **kw)
        return ray_tpu.get(
            self._actor.update_from_batch.remote(batch, **kw), timeout=300
        )

    def get_weights(self):
        if self._local is not None:
            return self._local.get_weights()
        return ray_tpu.get(self._actor.get_weights.remote(), timeout=60)

    def set_weights(self, weights):
        if self._local is not None:
            return self._local.set_weights(weights)
        return ray_tpu.get(self._actor.set_weights.remote(weights),
                           timeout=120)

    def num_devices(self) -> int:
        if self._local is not None:
            return self._local.num_devices()
        return ray_tpu.get(self._actor.num_devices.remote(), timeout=60)

    def shutdown(self):
        if self._actor is not None:
            try:
                ray_tpu.kill(self._actor)
            except Exception:
                pass
