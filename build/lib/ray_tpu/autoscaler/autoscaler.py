"""The autoscaler control loop.

Counterpart of the reference's v2 Autoscaler
(reference: python/ray/autoscaler/v2/autoscaler.py:42 — read cluster state
from the GCS AutoscalerStateService, run the demand scheduler, reconcile
through the instance manager / node provider; v1 loop shape:
autoscaler/_private/autoscaler.py:172 StandardAutoscaler.update + Monitor
monitor.py:126). Scaling unit = node type = one whole TPU slice.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ray_tpu._private.gcs.client import GcsClient
from ray_tpu.autoscaler.node_provider import NodeProvider
from ray_tpu.autoscaler.scheduler import ResourceDemandScheduler

logger = logging.getLogger("ray_tpu.autoscaler")


@dataclass
class NodeTypeConfig:
    resources: Dict[str, float]
    min_workers: int = 0
    max_workers: int = 10
    labels: Dict[str, str] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "resources": dict(self.resources),
            "min_workers": self.min_workers,
            "max_workers": self.max_workers,
            "labels": dict(self.labels),
        }


class Autoscaler:
    def __init__(
        self,
        gcs_address: str,
        provider: NodeProvider,
        node_types: Dict[str, NodeTypeConfig],
        idle_timeout_s: float = 60.0,
        update_interval_s: float = 1.0,
        launch_cooldown_s: float = 10.0,
        boot_grace_s: float = 300.0,
    ):
        self.gcs = GcsClient.from_address(gcs_address)
        self.provider = provider
        self.node_types = {
            name: cfg.to_dict() if isinstance(cfg, NodeTypeConfig) else dict(cfg)
            for name, cfg in node_types.items()
        }
        self.scheduler = ResourceDemandScheduler(self.node_types)
        self.idle_timeout_s = idle_timeout_s
        self.update_interval_s = update_interval_s
        self.launch_cooldown_s = launch_cooldown_s
        self.boot_grace_s = boot_grace_s
        self._idle_since: Dict[str, float] = {}  # provider id -> ts
        self._last_launch: Dict[str, float] = {}  # node_type -> ts
        self._launched_at: Dict[str, float] = {}  # provider id -> ts
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- control

    def start(self):
        # Announce ourselves: raylets switch infeasible demand from
        # fail-fast to queue-and-wait while an autoscaler can add capacity.
        # The value is a timestamp, refreshed every round — a crashed
        # autoscaler goes stale within 30s and raylets fail fast again.
        self._announce()
        self._thread = threading.Thread(
            target=self._run, name="autoscaler", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        try:
            self.gcs.kv_del("", b"__autoscaler_active__")
        except Exception:
            pass

    def _announce(self):
        try:
            self.gcs.kv_put("", b"__autoscaler_active__", str(time.time()).encode())
        except Exception:
            logger.exception("could not announce autoscaler")

    def _run(self):
        while not self._stop.is_set():
            try:
                self._announce()
                self.update()
            except Exception:
                logger.exception("autoscaler update failed")
            self._stop.wait(self.update_interval_s)

    # -------------------------------------------------------------- update

    def update(self) -> Dict[str, int]:
        """One reconciliation round; returns what was launched (by type)."""
        load = self.gcs.call("GetClusterLoad", {})
        provider_nodes = self.provider.non_terminated_nodes()
        counts_by_type: Dict[str, int] = {}
        for node_type in provider_nodes.values():
            counts_by_type[node_type] = counts_by_type.get(node_type, 0) + 1

        demands: List[Dict[str, float]] = []
        demands.extend(load.get("pending_tasks", []))
        demands.extend(load.get("pending_actors", []))
        demands.extend(b["resources"] for b in load.get("pending_pg_bundles", []))

        states = self._node_states(load, provider_nodes)
        capacities = [dict(n["resources_available"]) for n in load.get("nodes", [])]
        # Provider nodes still inside their boot window count as pending
        # capacity (reference: v2 scheduler counts launching instances), so
        # one demand never double-launches across rounds. Nodes that never
        # registered within the grace window (or whose raylet died) are
        # terminated — phantom capacity would suppress a needed launch
        # forever.
        for pid, st in states.items():
            if st["registered"]:
                continue
            if st["age"] < self.boot_grace_s:
                capacities.append(
                    dict(self.node_types.get(st["type"], {}).get("resources", {}))
                )
            else:
                logger.warning("terminating dead/unregistered node %s", pid)
                try:
                    self.provider.terminate_node(pid)
                except Exception:
                    # Keep it in the counts: max_workers must still see it,
                    # or repeated failed terminations over-launch unboundedly.
                    logger.exception("termination of %s failed", pid)
                    continue
                provider_nodes.pop(pid, None)
                counts_by_type[st["type"]] -= 1

        to_launch, infeasible = self.scheduler.schedule(
            demands, capacities, counts_by_type
        )
        for name, deficit in self.scheduler.min_workers_to_launch(
            counts_by_type
        ).items():
            to_launch[name] = max(to_launch.get(name, 0), deficit)

        launched: Dict[str, int] = {}
        now = time.time()
        for node_type, count in to_launch.items():
            # Cooldown: load reports lag placement by a report period, so a
            # demand satisfied moments ago can look pending while the node
            # it landed on already shows the capacity as consumed. Don't
            # launch the same type again until the dust settles.
            if now - self._last_launch.get(node_type, 0.0) < self.launch_cooldown_s:
                logger.info("launch of %s suppressed by cooldown", node_type)
                continue
            try:
                created = self.provider.create_node(node_type, count)
                for pid in created:
                    self._launched_at[pid] = time.time()
                launched[node_type] = count
                self._last_launch[node_type] = time.time()
                logger.info("launched %d x %s", count, node_type)
            except Exception:
                logger.exception("launch of %s failed", node_type)
        if infeasible:
            logger.warning(
                "infeasible demand (no node type fits, or max_workers hit): %s",
                infeasible[:5],
            )

        self._terminate_idle(states, provider_nodes, counts_by_type)
        return launched

    def _node_states(self, load, provider_nodes) -> Dict[str, dict]:
        """Per provider node: {type, age, registered, row}. Uses an exact
        provider-node -> raylet-node-id mapping when the provider exposes
        one (FakeMultiNodeProvider does); otherwise matches GCS rows to
        provider nodes of the same node_type label by count."""
        now = time.time()
        node_id_of = getattr(self.provider, "raylet_node_id", None)
        rows_by_id = {n["node_id"]: n for n in load.get("nodes", [])}
        rows_by_label: Dict[str, List[dict]] = {}
        for n in load.get("nodes", []):
            label = n.get("labels", {}).get("node_type", "")
            rows_by_label.setdefault(label, []).append(n)

        states: Dict[str, dict] = {}
        claimed: set = set()
        for pid, node_type in provider_nodes.items():
            st = {
                "type": node_type,
                # setdefault: a node first seen NOW (autoscaler restart,
                # pre-existing provider nodes) starts aging from discovery —
                # a .get(pid, now) default would pin its age at 0 forever,
                # making a dead node permanent phantom capacity.
                "age": now - self._launched_at.setdefault(pid, now),
                "registered": False,
                "row": None,
            }
            if node_id_of is not None:
                nid = node_id_of(pid)
                row = rows_by_id.get(nid)
                if row is not None:
                    st["registered"] = True
                    st["row"] = row
            else:
                for row in rows_by_label.get(node_type, []):
                    if id(row) not in claimed:
                        claimed.add(id(row))
                        st["registered"] = True
                        st["row"] = row
                        break
            states[pid] = st
        return states

    def _terminate_idle(self, states, provider_nodes, counts_by_type):
        """Scale down nodes idle past the timeout, never below min_workers
        (reference: v1 autoscaler idle termination). Per-node busyness from
        that node's own GCS row; unregistered (booting) nodes are never
        idle candidates."""
        now = time.time()
        for pid, st in list(states.items()):
            if pid not in provider_nodes or not st["registered"]:
                self._idle_since.pop(pid, None)
                continue
            row = st["row"]
            busy = (
                row.get("num_leases", 0) > 0
                or row["resources_available"] != row["resources_total"]
            )
            if busy:
                self._idle_since.pop(pid, None)
                continue
            first_idle = self._idle_since.setdefault(pid, now)
            node_type = st["type"]
            cfg = self.node_types.get(node_type, {})
            if (
                now - first_idle > self.idle_timeout_s
                and counts_by_type.get(node_type, 0) > cfg.get("min_workers", 0)
            ):
                logger.info("terminating idle node %s (%s)", pid, node_type)
                try:
                    self.provider.terminate_node(pid)
                    counts_by_type[node_type] -= 1
                except Exception:
                    logger.exception("termination of %s failed", pid)
                self._idle_since.pop(pid, None)
                self._launched_at.pop(pid, None)
