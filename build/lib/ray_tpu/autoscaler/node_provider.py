"""Node providers: the pluggable "launch me a node" backend.

Counterpart of the reference's NodeProvider plugin API
(reference: python/ray/autoscaler/node_provider.py:13) and the fake
multi-node provider used for cloud-free autoscaler e2e tests
(reference: autoscaler/_private/fake_multi_node/node_provider.py).
"""

from __future__ import annotations

import threading
import uuid
from typing import Dict, List, Optional


class NodeProvider:
    """Minimal provider contract: launch/terminate/list, by node type."""

    def create_node(self, node_type: str, count: int = 1) -> List[str]:
        raise NotImplementedError

    def terminate_node(self, provider_node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> Dict[str, str]:
        """provider_node_id -> node_type"""
        raise NotImplementedError


class FakeMultiNodeProvider(NodeProvider):
    """Launches REAL raylet processes on this machine, one per 'node'
    (reference: fake_multi_node/node_provider.py — autoscaler e2e without a
    cloud). Each created node joins the target cluster's GCS with the node
    type's resources/labels.
    """

    def __init__(self, gcs_address: str, node_types: Dict[str, dict], session_dir: str = ""):
        self.gcs_address = gcs_address
        self.node_types = node_types
        self.session_dir = session_dir
        self._nodes: Dict[str, dict] = {}  # provider id -> {"node": Node, "type": str}
        self._lock = threading.Lock()

    def create_node(self, node_type: str, count: int = 1) -> List[str]:
        from ray_tpu._private.node import Node

        cfg = self.node_types[node_type]
        created = []
        for _ in range(count):
            pid = f"fake-{node_type}-{uuid.uuid4().hex[:6]}"
            node = Node(
                head=False,
                gcs_address=self.gcs_address,
                resources=dict(cfg.get("resources", {})),
                labels={**cfg.get("labels", {}), "node_type": node_type},
                session_dir=self.session_dir or None,
                node_name=pid,
            )
            with self._lock:
                self._nodes[pid] = {"node": node, "type": node_type}
            created.append(pid)
        return created

    def terminate_node(self, provider_node_id: str) -> None:
        with self._lock:
            rec = self._nodes.pop(provider_node_id, None)
        if rec is not None:
            rec["node"].shutdown()

    def non_terminated_nodes(self) -> Dict[str, str]:
        with self._lock:
            return {pid: rec["type"] for pid, rec in self._nodes.items()}

    def raylet_node_id(self, provider_node_id: str) -> Optional[bytes]:
        with self._lock:
            rec = self._nodes.get(provider_node_id)
        return rec["node"].node_id.binary() if rec else None

    def shutdown(self):
        with self._lock:
            nodes, self._nodes = list(self._nodes.values()), {}
        for rec in nodes:
            rec["node"].shutdown()


class RecordingNodeProvider(NodeProvider):
    """Test double that only records launch/terminate calls."""

    def __init__(self, node_types: Optional[Dict[str, dict]] = None):
        self.node_types = node_types or {}
        self.launches: List[str] = []
        self.terminations: List[str] = []
        self._nodes: Dict[str, str] = {}

    def create_node(self, node_type: str, count: int = 1) -> List[str]:
        out = []
        for _ in range(count):
            pid = f"rec-{node_type}-{len(self.launches)}"
            self.launches.append(node_type)
            self._nodes[pid] = node_type
            out.append(pid)
        return out

    def terminate_node(self, provider_node_id: str) -> None:
        self.terminations.append(provider_node_id)
        self._nodes.pop(provider_node_id, None)

    def non_terminated_nodes(self) -> Dict[str, str]:
        return dict(self._nodes)
