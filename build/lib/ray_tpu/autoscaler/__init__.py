"""Autoscaler (v2-shaped): slice-granular demand-driven scaling.

Counterpart of the reference's autoscaler v2
(reference: python/ray/autoscaler/v2/autoscaler.py:42 — instance manager +
ResourceDemandScheduler v2/scheduler.py:624 consuming the GCS
AutoscalerStateService). TPU-first difference: the scaling unit is a node
*type* that represents a whole ICI slice (e.g. a v5e-8 host group), never a
fraction of one — demand for a ``TPU-<type>-head`` resource launches an
entire slice.
"""

from ray_tpu.autoscaler.autoscaler import Autoscaler, NodeTypeConfig
from ray_tpu.autoscaler.node_provider import FakeMultiNodeProvider, NodeProvider
from ray_tpu.autoscaler.scheduler import ResourceDemandScheduler

__all__ = [
    "Autoscaler",
    "NodeTypeConfig",
    "NodeProvider",
    "FakeMultiNodeProvider",
    "ResourceDemandScheduler",
]
