"""Resource-demand scheduler: bin-pack pending demand, pick node types.

Counterpart of the reference's v2 scheduler
(reference: python/ray/autoscaler/v2/scheduler.py:624
ResourceDemandScheduler — simulate placing the pending demand onto existing
+ already-launching nodes, launch the cheapest node types covering the
rest). Slice granularity: a node type is an indivisible unit (one TPU
slice); we never launch partial slices.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple


def _fits(avail: Dict[str, float], demand: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) >= v for k, v in demand.items())


def _take(avail: Dict[str, float], demand: Dict[str, float]) -> None:
    for k, v in demand.items():
        avail[k] = avail.get(k, 0.0) - v


class ResourceDemandScheduler:
    """Pure function of (demand, capacity, config) -> launch decisions."""

    def __init__(self, node_types: Dict[str, dict]):
        # node_types: name -> {"resources": {...}, "max_workers": int,
        #                      "min_workers": int, "labels": {...}}
        self.node_types = node_types

    def schedule(
        self,
        demands: List[Dict[str, float]],
        node_capacities: List[Dict[str, float]],
        counts_by_type: Dict[str, int],
    ) -> Tuple[Dict[str, int], List[Dict[str, float]]]:
        """Returns (to_launch {node_type: count}, infeasible demands).

        ``node_capacities``: available resources of existing + pending
        nodes. ``counts_by_type``: current node count per type (enforces
        max_workers).
        """
        capacities = [dict(c) for c in node_capacities]
        to_launch: Dict[str, int] = {}
        launched_capacity: List[Dict[str, float]] = []
        infeasible: List[Dict[str, float]] = []

        # Largest demands first: classic first-fit-decreasing keeps a big
        # slice demand from being starved by many small CPU demands.
        def size(d):
            return (len(d), sum(d.values()))

        for demand in sorted(demands, key=size, reverse=True):
            if not demand:
                continue
            placed = False
            for cap in capacities + launched_capacity:
                if _fits(cap, demand):
                    _take(cap, demand)
                    placed = True
                    break
            if placed:
                continue
            node_type = self._pick_type(demand, counts_by_type, to_launch)
            if node_type is None:
                infeasible.append(demand)
                continue
            to_launch[node_type] = to_launch.get(node_type, 0) + 1
            cap = dict(self.node_types[node_type].get("resources", {}))
            _take(cap, demand)
            launched_capacity.append(cap)
        return to_launch, infeasible

    def _pick_type(
        self,
        demand: Dict[str, float],
        counts_by_type: Dict[str, int],
        to_launch: Dict[str, int],
    ) -> Optional[str]:
        """Smallest node type that satisfies the demand and has headroom."""
        candidates = []
        for name, cfg in self.node_types.items():
            res = cfg.get("resources", {})
            if not _fits(dict(res), demand):
                continue
            current = counts_by_type.get(name, 0) + to_launch.get(name, 0)
            if current >= cfg.get("max_workers", 2**31):
                continue
            candidates.append((sum(res.values()), len(res), name))
        if not candidates:
            return None
        candidates.sort()
        return candidates[0][2]

    def min_workers_to_launch(
        self, counts_by_type: Dict[str, int]
    ) -> Dict[str, int]:
        out = {}
        for name, cfg in self.node_types.items():
            deficit = cfg.get("min_workers", 0) - counts_by_type.get(name, 0)
            if deficit > 0:
                out[name] = deficit
        return out
