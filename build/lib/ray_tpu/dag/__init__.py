from ray_tpu.dag.node import (  # noqa: F401
    ClassNode,
    DAGNode,
    FunctionNode,
    InputNode,
    MultiOutputNode,
)
from ray_tpu.dag.compiled import CompiledDAG, CompiledDAGRef  # noqa: F401
