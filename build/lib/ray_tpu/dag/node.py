"""DAG nodes for lazy task/actor graphs (reference: python/ray/dag/).

``fn.bind(...)`` builds a DAGNode graph; ``.execute()`` walks it submitting
tasks/actor calls; ``experimental_compile()`` (ray_tpu.dag.compiled) turns a
static actor DAG into a channel-connected pipeline.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple


class DAGNode:
    def __init__(self, args: Tuple, kwargs: Dict[str, Any]):
        self._bound_args = args
        self._bound_kwargs = kwargs

    def _resolve(self, value, input_value):
        if isinstance(value, DAGNode):
            return value.execute(input_value)
        return value

    def _resolved_args(self, input_value):
        args = [self._resolve(a, input_value) for a in self._bound_args]
        kwargs = {k: self._resolve(v, input_value) for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def execute(self, input_value=None):
        raise NotImplementedError

    def experimental_compile(self, **kwargs):
        from ray_tpu.dag.compiled import CompiledDAG

        return CompiledDAG(self, **kwargs)

    # __getitem__ projects an element of this node's (tuple/dict) output;
    # __iter__=None keeps that from turning nodes into infinite sequences.
    __iter__ = None

    def __getitem__(self, key):
        return _AttrProxy(self, key)


class InputNode(DAGNode):
    """Placeholder for the value fed at execute() time."""

    _current = None

    def __init__(self):
        super().__init__((), {})

    def __enter__(self):
        InputNode._current = self
        return self

    def __exit__(self, *a):
        InputNode._current = None

    def execute(self, input_value=None):
        return input_value


class FunctionNode(DAGNode):
    def __init__(self, remote_fn, args, kwargs):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn

    def execute(self, input_value=None):
        args, kwargs = self._resolved_args(input_value)
        return self._remote_fn.remote(*args, **kwargs)


class ClassNode(DAGNode):
    """A bound actor constructor; method calls on it create ClassMethodNodes."""

    def __init__(self, actor_cls, args, kwargs):
        super().__init__(args, kwargs)
        self._actor_cls = actor_cls
        self._handle = None

    def _ensure_actor(self):
        if self._handle is None:
            args, kwargs = self._resolved_args(None)
            self._handle = self._actor_cls.remote(*args, **kwargs)
        return self._handle

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        return _ClassMethodBinder(self, item)

    def execute(self, input_value=None):
        return self._ensure_actor()


class _ClassMethodBinder:
    def __init__(self, class_node: ClassNode, method_name: str):
        self._class_node = class_node
        self._method_name = method_name

    def bind(self, *args, **kwargs) -> "ClassMethodNode":
        return ClassMethodNode(self._class_node, self._method_name, args, kwargs)


class ClassMethodNode(DAGNode):
    def __init__(self, class_node: ClassNode, method_name: str, args, kwargs):
        super().__init__(args, kwargs)
        self._class_node = class_node
        self._method_name = method_name

    def execute(self, input_value=None):
        handle = self._class_node._ensure_actor()
        args, kwargs = self._resolved_args(input_value)
        method = getattr(handle, self._method_name)
        return method.remote(*args, **kwargs)


class MultiOutputNode(DAGNode):
    def __init__(self, nodes: List[DAGNode]):
        super().__init__(tuple(nodes), {})
        self._nodes = nodes

    def execute(self, input_value=None):
        return [n.execute(input_value) for n in self._nodes]


class _LiveActorNode:
    """ClassNode stand-in wrapping an already-created actor handle, so
    ``handle.method.bind(...)`` composes with ClassMethodNode."""

    def __init__(self, handle):
        self._handle = handle

    def _ensure_actor(self):
        return self._handle


class _AttrProxy(DAGNode):
    """x[i] projection of an upstream node's output (``inp[0]``-style).

    One level only: nested projections (x[0][1]) are rejected — the compiled
    path unwraps exactly one level, and one level covers the tuple-return
    idiom the reference supports.
    """

    # Explicitly non-iterable: without this, __getitem__ would make every
    # node an infinite sequence under tuple-unpack / list() / iteration.
    __iter__ = None

    def __init__(self, base: DAGNode, key):
        super().__init__((), {})
        if isinstance(base, _AttrProxy):
            raise ValueError(
                "nested projections (node[i][j]) are not supported; "
                "project once and index inside the consuming method"
            )
        if not isinstance(key, (int, str)):
            raise TypeError(f"projection key must be int or str, got {key!r}")
        self._base = base
        self._key = key

    def execute(self, input_value=None):
        from ray_tpu._private.object_ref import ObjectRef

        v = self._base.execute(input_value)
        if isinstance(v, ObjectRef):
            import ray_tpu

            v = ray_tpu.get(v)
        return v[self._key]
