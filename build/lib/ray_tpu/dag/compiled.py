"""Compiled actor DAGs: static graphs executed through preallocated
shared-memory channels with persistent per-actor exec loops.

Reference architecture: python/ray/dag/compiled_dag_node.py:391 (CompiledDAG,
do_exec_tasks :84, execute :1408) + shared_memory_channel.py:147. The
TPU-native difference: channels are in-place-mutated plasma objects on the
node segment (one memcpy handoff, no per-step task submission), and values
that are jax/numpy arrays ride the serializer's zero-copy buffer path, so a
same-host pipeline stage handoff never round-trips device data through RPC.

Usage::

    with InputNode() as inp:
        x = a.f.bind(inp)
        y = b.g.bind(x)
    dag = y.experimental_compile()
    for step in range(1000):
        ref = dag.execute(step)        # no task submission per step
        out = ref.get()
    dag.teardown()

Constraints (same as the reference's aDAG v1): every bound method must be an
actor method (plain tasks cannot host a persistent loop), the graph is
static, and all participating actors must live on the driver's node (the
shared-memory plane is node-local; cross-node pipelines shard by stage).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from ray_tpu.dag.node import (
    ClassMethodNode,
    DAGNode,
    InputNode,
    MultiOutputNode,
    _AttrProxy,
)
from ray_tpu.experimental.channel import (
    Channel,
    ChannelClosed,
    SocketChannel,
    _PropagatedError,
    attach_channel,
    close_registered,
    register_channel,
)


class _FROM_CHANNEL:
    """Sentinel marking a positional arg fed by a channel read. A class is
    pickled by reference, so identity survives the __ray_call__ hop."""


class CompiledDAGRef:
    """Result handle for one execute(); reads the output channels."""

    def __init__(self, dag: "CompiledDAG", seq: int):
        self._dag = dag
        self._seq = seq
        self._value = None
        self._consumed = False

    def get(self, timeout: Optional[float] = 60.0):
        return self._dag._read_output(self, timeout)


def _exec_loop(self, tasks: List[dict]):
    """Runs inside the actor (shipped via __ray_call__): read inputs, call
    the bound method, write the output — forever, until teardown closes a
    channel. This is the reference's do_exec_tasks."""
    attached: Dict[bytes, Channel] = {}

    def chan(desc, reader_index):
        # keyed by reader slot too: two tasks on one actor consuming the
        # same upstream own distinct slots and must ack independently
        key = (desc.get("oid") or desc["token"], reader_index)
        if key not in attached:
            attached[key] = attach_channel(desc, reader_index)
        return attached[key]

    try:
        while True:
            for t in tasks:
                # One read per channel per task-tick: a method consuming the
                # same upstream twice (f.bind(x, x)) must not double-read.
                # Per-task (not per-tick): each task owns a distinct reader
                # slot and must perform its own read to ack it.
                tick_cache: Dict[bytes, Any] = {}
                args = []
                error = None
                for desc, ridx, unpack in t["reads"]:
                    key = desc.get("oid") or desc["token"]
                    if key in tick_cache:
                        v = tick_cache[key]
                    else:
                        try:
                            v = chan(desc, ridx).read()
                        except _PropagatedError as e:
                            v = e
                        tick_cache[key] = v
                    if isinstance(v, _PropagatedError):
                        error = v
                        args.append(None)  # placeholder; error short-circuits
                    elif unpack is None:
                        args.append(v)
                    else:
                        args.append(v[unpack])
                out_chan = chan(t["write"], None)
                if error is not None:
                    out_chan.write(error.inner, is_error=True)
                    continue
                it = iter(args)
                bound = [next(it) if s is _FROM_CHANNEL else s
                         for s in t["static_args"]]
                try:
                    result = getattr(self, t["method"])(*bound, **t["kwargs"])
                except Exception as e:
                    out_chan.write(e, is_error=True)
                    continue
                out_chan.write(result)
    except ChannelClosed:
        return None


def _start_exec_loop(self, tasks: List[dict]):
    t = threading.Thread(
        target=_exec_loop, args=(self, tasks), daemon=True,
        name="rtpu-dag-exec",
    )
    t.start()
    return True


def _get_node_id(self):
    import ray_tpu

    return ray_tpu.get_runtime_context().get_node_id()


def _remote_create_shm_channel(self, n_readers: int, buffer_size: int):
    """Create a shared-memory channel in THIS actor's process (its node's
    plasma) and register it for driver-directed teardown."""
    from ray_tpu.experimental.channel import Channel, register_channel

    ch = Channel.create(n_readers, buffer_size)
    desc = ch.descriptor()
    desc["token"] = desc["oid"]
    register_channel(desc["token"], ch)
    return desc


def _remote_create_socket_channel(self, n_readers: int, buffer_size: int):
    """Create a cross-node socket channel with THIS actor's process as the
    writer end."""
    from ray_tpu.experimental.channel import SocketChannel, register_channel

    ch = SocketChannel.create(n_readers)
    desc = ch.descriptor()
    register_channel(desc["token"], ch)
    return desc


def _remote_close_channel(self, token: bytes):
    from ray_tpu.experimental.channel import close_registered

    close_registered(token)
    return True


class CompiledDAG:
    def __init__(self, output_node: DAGNode,
                 buffer_size_bytes: int = 4 * 1024 * 1024):
        self._buffer_size = buffer_size_bytes
        self._torn_down = False
        self._seq = 0
        self._next_read_seq = 1
        self._in_flight: List[CompiledDAGRef] = []
        self._lock = threading.Lock()
        self._compile(output_node)

    # ------------------------------------------------------------- compile

    def _compile(self, output_node: DAGNode):
        if isinstance(output_node, MultiOutputNode):
            outputs = list(output_node._nodes)
        else:
            outputs = [output_node]
        for n in outputs:
            if not isinstance(n, ClassMethodNode):
                raise ValueError(
                    "compiled DAGs support actor-method nodes only "
                    "(reference: compiled_dag_node.py NotImplementedError)"
                )

        # Topological collection (args before consumers).
        order: List[ClassMethodNode] = []
        seen = set()
        self._input_node: Optional[InputNode] = None

        def visit(n):
            if id(n) in seen:
                return
            seen.add(id(n))
            if isinstance(n, InputNode):
                self._input_node = n
                return
            if isinstance(n, _AttrProxy):
                visit(n._base)
                return
            if not isinstance(n, ClassMethodNode):
                if isinstance(n, DAGNode):
                    raise ValueError(
                        f"unsupported node type in compiled DAG: {type(n)}"
                    )
                return
            for a in list(n._bound_args) + list(n._bound_kwargs.values()):
                if isinstance(a, DAGNode):
                    visit(a)
            order.append(n)

        for n in outputs:
            visit(n)
        if not order:
            raise ValueError("empty DAG")

        # Reader bookkeeping: channel per producing node + the input channel.
        # Consumer lists are UNIQUE per node: a method consuming the same
        # upstream twice still occupies one reader slot (the exec loop reads
        # each channel once per tick), and every allocated slot must have a
        # live reader or the writer's all-acked wait never completes.
        consumers: Dict[int, List] = {id(n): [] for n in order}
        input_consumers: List = []
        for n in order:
            seen_bases = set()
            for a in n._bound_args:
                base = a._base if isinstance(a, _AttrProxy) else a
                if id(base) in seen_bases:
                    continue
                seen_bases.add(id(base))
                if isinstance(base, InputNode):
                    input_consumers.append(n)
                elif isinstance(base, ClassMethodNode):
                    consumers[id(base)].append(n)
        out_reader_idx: Dict[int, int] = {}
        for n in outputs:
            consumers[id(n)].append("driver")

        # Resolve actors and their nodes first: channel placement follows
        # the node topology — a same-node edge rides shared memory, a
        # cross-node edge rides a socket stream (the DCN hop; reference GPU
        # analogue torch_tensor_nccl_channel.py:191).
        import ray_tpu

        my_node = ray_tpu.get_runtime_context().get_node_id()
        handle_of: Dict[int, Any] = {}
        for n in order:
            handle_of[id(n)] = n._class_node._ensure_actor()
        uniq_handles = {id(h): h for h in handle_of.values()}
        node_refs = {
            hid: h.__ray_call__.remote(_get_node_id)
            for hid, h in uniq_handles.items()
        }
        node_of_handle = {hid: ray_tpu.get(r) for hid, r in node_refs.items()}
        node_of = {
            nid: node_of_handle[id(h)] for nid, h in handle_of.items()
        }

        self._local_channels: List[Any] = []
        self._remote_tokens: List[tuple] = []  # (actor handle, token)

        def make_channel(writer_nid, reader_nodes, n_readers):
            """Allocate a channel in the writer's process. writer_nid is
            id(node) for an actor writer, None for the driver."""
            writer_node = my_node if writer_nid is None else node_of[writer_nid]
            cross = any(rn != writer_node for rn in reader_nodes)
            n_readers = max(1, n_readers)
            if writer_nid is None:
                ch = (SocketChannel.create(n_readers) if cross
                      else Channel.create(n_readers, self._buffer_size))
                desc = ch.descriptor()
                if "token" not in desc:
                    desc["token"] = desc["oid"]
                self._local_channels.append(ch)
                return ch, desc
            h = handle_of[writer_nid]
            fn = (_remote_create_socket_channel if cross
                  else _remote_create_shm_channel)
            desc = ray_tpu.get(
                h.__ray_call__.remote(fn, n_readers, self._buffer_size)
            )
            self._remote_tokens.append((h, desc["token"]))
            return None, desc

        # Reader indices.
        input_rix: Dict[int, int] = {}
        for i, c in enumerate(input_consumers):
            input_rix.setdefault(id(c), i)
        node_rix: Dict[int, Dict[int, int]] = {}
        for n in order:
            node_rix[id(n)] = {}
            for i, c in enumerate(consumers[id(n)]):
                if c == "driver":
                    out_reader_idx[id(n)] = i
                else:
                    node_rix[id(n)][id(c)] = i

        # Allocate: the input channel is written by the driver; each node's
        # output channel is written by its actor.
        self._input_channel = None
        input_desc = None
        if input_consumers:
            self._input_channel, input_desc = make_channel(
                None, [node_of[id(c)] for c in input_consumers],
                len(input_consumers),
            )
        node_desc: Dict[int, dict] = {}
        for n in order:
            reader_nodes = [
                my_node if c == "driver" else node_of[id(c)]
                for c in consumers[id(n)]
            ]
            _, node_desc[id(n)] = make_channel(
                id(n), reader_nodes, len(consumers[id(n)])
            )

        # Build per-actor task descriptors.
        by_actor: Dict[Any, List[dict]] = {}
        self._actors = []
        for n in order:
            handle = handle_of[id(n)]
            reads = []
            static_args = []
            kwargs = {}
            for a in n._bound_args:
                unpack = None
                base = a
                if isinstance(a, _AttrProxy):
                    unpack = a._key
                    base = a._base
                if isinstance(base, InputNode):
                    reads.append((input_desc, input_rix[id(n)], unpack))
                    static_args.append(_FROM_CHANNEL)
                elif isinstance(base, ClassMethodNode):
                    reads.append((node_desc[id(base)],
                                  node_rix[id(base)][id(n)], unpack))
                    static_args.append(_FROM_CHANNEL)
                else:
                    static_args.append(base)
            for k, v in n._bound_kwargs.items():
                if isinstance(v, DAGNode):
                    raise ValueError("DAG deps must be positional args")
                kwargs[k] = v
            by_actor.setdefault(handle, []).append({
                "method": n._method_name,
                "reads": reads,
                "static_args": static_args,
                "kwargs": kwargs,
                "write": node_desc[id(n)],
            })

        # Launch exec loops.
        started = [
            handle.__ray_call__.remote(_start_exec_loop, tasks)
            for handle, tasks in by_actor.items()
        ]
        ray_tpu.get(started)
        self._actors = list(by_actor)
        self._output_readers = [
            attach_channel(node_desc[id(n)], out_reader_idx[id(n)])
            for n in outputs
        ]
        self._multi_output = isinstance(output_node, MultiOutputNode)

    # ------------------------------------------------------------- execute

    def execute(self, *args, timeout: Optional[float] = 60.0):
        if self._torn_down:
            raise RuntimeError("compiled DAG was torn down")
        with self._lock:
            self._seq += 1
            ref = CompiledDAGRef(self, self._seq)
            self._in_flight.append(ref)
        if self._input_channel is not None:
            value = args[0] if len(args) == 1 else args
            self._input_channel.write(value, timeout=timeout)
        return ref

    def _read_output(self, ref: CompiledDAGRef, timeout: Optional[float]):
        with self._lock:
            if ref._consumed:
                return ref._value
            # Channel reads are strictly ordered; service older refs first.
            for pending in list(self._in_flight):
                if pending._seq > ref._seq:
                    break
                outs = []
                err = None
                for rd in self._output_readers:
                    try:
                        outs.append(rd.read(timeout=timeout))
                    except _PropagatedError as e:
                        err = e.inner
                        outs.append(None)
                pending._consumed = True
                if err is not None:
                    pending._value = err
                    pending._error = True
                else:
                    pending._value = (
                        outs if self._multi_output else outs[0]
                    )
                    pending._error = False
                self._in_flight.remove(pending)
            if getattr(ref, "_error", False):
                raise ref._value
            return ref._value

    def teardown(self):
        if self._torn_down:
            return
        self._torn_down = True
        import ray_tpu

        for ch in self._local_channels:
            try:
                ch.destroy()
            except Exception:
                pass
        for rd in self._output_readers:
            try:
                rd.close()
            except Exception:
                pass
            # shm readers pin the 4 MiB channel segment via plasma.get at
            # attach; drop the pin or every compile/teardown cycle leaks it
            release = getattr(rd, "release", None)
            if release is not None:
                try:
                    release()
                except Exception:
                    pass
        closes = []
        for handle, token in self._remote_tokens:
            try:
                closes.append(
                    handle.__ray_call__.remote(_remote_close_channel, token)
                )
            except Exception:
                pass
        for ref in closes:
            try:
                ray_tpu.get(ref, timeout=10)
            except Exception:
                pass


