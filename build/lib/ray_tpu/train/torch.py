"""TorchTrainer: data-parallel torch training over the actor worker group
(reference: python/ray/train/torch/torch_trainer.py:11 + config.py:65
_setup_torch_process_group + train_loop_utils.py:453 prepare_model / :313
prepare_data_loader).

The jax path is this framework's flagship (JaxTrainer); TorchTrainer exists
for API parity with the reference's most-used trainer. Workers form a
torch.distributed gloo process group (CPU boxes; NCCL is a GPU concern the
TPU stack doesn't carry), DDP averages gradients, and the session
report/checkpoint machinery is shared with every other trainer.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

from ray_tpu.train._trainer import DataParallelTrainer, logger


@dataclasses.dataclass
class TorchConfig:
    """Process-group config (reference: train/torch/config.py:65)."""

    backend: str = "gloo"
    init_timeout_s: float = 120.0


class TorchTrainer(DataParallelTrainer):
    """Runs `train_loop_per_worker` on every worker inside one
    torch.distributed process group."""

    def __init__(self, *args, torch_config: Optional[TorchConfig] = None, **kw):
        super().__init__(*args, **kw)
        self.torch_config = torch_config or TorchConfig()

    def _worker_env(self) -> Dict[str, str]:
        # gloo rendezvous env is set per-worker in _on_group_start
        return {}

    def _on_group_start(self, group):
        if group.num_workers <= 1:
            return
        ip = group.execute_single(0, "node_ip")
        port = group.execute_single(0, "free_port")
        import ray_tpu

        refs = [
            group.async_call(
                i, "init_torch_process_group",
                ip, port, group.num_workers, i,
                self.torch_config.backend,
                self.torch_config.init_timeout_s,
            )
            for i in range(group.num_workers)
        ]
        ray_tpu.get(refs, timeout=self.torch_config.init_timeout_s + 60)
        logger.info("torch.distributed(%s) up: %d ranks",
                    self.torch_config.backend, group.num_workers)


# ------------------------------------------------------- worker-side helpers


def get_device():
    """The device this worker should use (reference:
    train/torch/train_loop_utils.py get_device). CPU here — TPU math goes
    through the jax path."""
    import torch

    return torch.device("cpu")


def prepare_model(model):
    """Wrap the model for distributed training (reference:
    train_loop_utils.py:453 — DDP when world_size > 1)."""
    import torch.distributed as dist

    if dist.is_initialized() and dist.get_world_size() > 1:
        from torch.nn.parallel import DistributedDataParallel

        return DistributedDataParallel(model)
    return model


def prepare_data_loader(loader):
    """Shard a DataLoader across workers with a DistributedSampler
    (reference: train_loop_utils.py:313). Preserves the loader's shuffle
    setting; loaders built with a custom batch_sampler can't be resharded
    automatically and are rejected."""
    import torch.distributed as dist

    if not dist.is_initialized() or dist.get_world_size() <= 1:
        return loader
    import torch.utils.data as tud

    if loader.batch_size is None:
        raise ValueError(
            "prepare_data_loader cannot reshard a DataLoader built with a "
            "custom batch_sampler; construct a DistributedSampler-aware "
            "batch_sampler yourself"
        )
    shuffle = isinstance(loader.sampler, tud.RandomSampler)
    sampler = tud.distributed.DistributedSampler(
        loader.dataset, shuffle=shuffle
    )
    return tud.DataLoader(
        loader.dataset,
        batch_size=loader.batch_size,
        sampler=sampler,
        num_workers=0,
        collate_fn=loader.collate_fn,
        drop_last=loader.drop_last,
    )
