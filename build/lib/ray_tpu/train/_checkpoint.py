"""Checkpoint: a directory of files addressed by local path OR storage URI
(reference: python/ray/train/_checkpoint.py:56 — a dir + pyarrow-fs URI).
Local paths cover single-node and shared-FS clusters (also what orbax
writes); URIs (mock://, s3://, ...) go through ray_tpu.train._storage so
driver and workers never assume a shared filesystem."""

from __future__ import annotations

import contextlib
import os
import shutil
import tempfile
from typing import Optional


class Checkpoint:
    def __init__(self, path: str):
        from ray_tpu.train._storage import is_remote_uri

        self._remote = is_remote_uri(path)
        self.path = path if self._remote else os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    @classmethod
    def from_uri(cls, uri: str) -> "Checkpoint":
        return cls(uri)

    @property
    def uri(self) -> Optional[str]:
        return self.path if self._remote else None

    def as_directory(self):
        """Context manager yielding a local directory with the contents.
        Remote checkpoints download to a temp dir cleaned up on exit."""
        if not self._remote:
            return contextlib.nullcontext(self.path)

        @contextlib.contextmanager
        def _dl():
            tmp = tempfile.mkdtemp(prefix="rtpu_ckpt_")
            try:
                yield self.to_directory(tmp)
            finally:
                shutil.rmtree(tmp, ignore_errors=True)

        return _dl()

    def to_directory(self, path: Optional[str] = None) -> str:
        dest = path or tempfile.mkdtemp(prefix="rtpu_ckpt_")
        if self._remote:
            from ray_tpu.train._storage import get_storage

            return get_storage(self.path).download_dir("", dest)
        if os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    def __repr__(self):
        return f"Checkpoint(path={self.path!r})"

    def __reduce__(self):
        return (Checkpoint, (self.path,))
