"""ray_tpu.train — SPMD training over actor worker groups, jax-first.

Counterpart of Ray Train (reference: python/ray/train/, call stack SURVEY.md
§3.4) with the torch/NCCL data plane replaced by jax: one worker actor per
host, `jax.distributed` coordination, a global device mesh over ICI, and the
sharded train step compiled by XLA (ray_tpu/parallel/train_step.py).
"""

from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train._config import (
    CheckpointConfig,
    FailureConfig,
    JaxConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train._session import (
    get_checkpoint,
    get_context,
    get_dataset_shard,
    report,
)
from ray_tpu.train._trainer import (
    DataParallelTrainer,
    JaxTrainer,
    Result,
    TrainingFailedError,
)

__all__ = [
    "Checkpoint",
    "CheckpointConfig",
    "DataParallelTrainer",
    "FailureConfig",
    "JaxConfig",
    "JaxTrainer",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "TrainingFailedError",
    "get_checkpoint",
    "get_context",
    "get_dataset_shard",
    "report",
]
