"""Train/AIR configuration dataclasses (reference: python/ray/air/config.py —
ScalingConfig :103, RunConfig :594, FailureConfig :395, CheckpointConfig :445;
train/torch/config.py for the backend config notion)."""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional


@dataclasses.dataclass
class ScalingConfig:
    """How many workers and what each one owns.

    TPU-first: `topology` names a slice type (e.g. "v5e-8"); a worker then
    requests that slice's head resource so exactly one worker lands per slice
    (reference accelerator manager: _private/accelerators/tpu.py:362-381).
    """

    num_workers: int = 1
    use_tpu: bool = False
    topology: str = ""
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"

    def worker_resources(self) -> Dict[str, float]:
        if self.resources_per_worker is not None:
            return dict(self.resources_per_worker)
        if self.topology:
            return {f"TPU-{self.topology}-head": 1}
        if self.use_tpu:
            return {"TPU": 1}
        return {"CPU": 1}


@dataclasses.dataclass
class FailureConfig:
    """Group-restart fault tolerance: on worker failure the whole group
    restarts from the last checkpoint (reference: air/config.py:395; no
    elastic resize, same as the reference)."""

    max_failures: int = 0


@dataclasses.dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"


@dataclasses.dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: Optional[FailureConfig] = None
    checkpoint_config: Optional[CheckpointConfig] = None


@dataclasses.dataclass
class JaxConfig:
    """Backend config (reference analogue: TorchConfig train/torch/config.py:65
    — but instead of dist.init_process_group, workers run
    jax.distributed.initialize against rank 0's coordinator)."""

    # Initialize jax.distributed across workers (multi-host mesh). With one
    # worker the local process sees its chips directly and this is skipped.
    distributed: Optional[bool] = None
    # Env vars applied in each worker before jax initializes (e.g. forcing
    # JAX_PLATFORMS=cpu + a virtual device count in chip-free tests).
    env: Dict[str, str] = dataclasses.field(default_factory=dict)
    coordinator_port: int = 0  # 0: pick a free port
