"""Checkpoint storage abstraction: URI-addressed persistence so Train/Tune
work on clusters WITHOUT a shared filesystem
(reference: python/ray/train/_internal/storage.py:352 StorageContext — the
reference uses pyarrow.fs URIs; we keep that for real remote schemes and add
a cluster-backed mock scheme for chip-free tests).

Schemes:
  /plain/path, file:///path  → LocalStorage (copytree; same-FS clusters)
  mock://bucket/prefix       → MockRemoteStorage: contents live in a detached
                               named actor, reachable from every node of the
                               cluster — simulates S3/GCS in tests and proves
                               the no-shared-FS path end to end
  s3://, gs://, hdfs://, ... → ArrowStorage via pyarrow.fs.FileSystem.from_uri

Workers upload checkpoints from their own node (`upload_dir`); the driver
only ever handles URIs, never worker-local paths.
"""

from __future__ import annotations

import os
import shutil
from typing import Dict, List, Optional
from urllib.parse import urlparse

MOCK_STORAGE_ACTOR = "_rtpu_mock_storage"


def is_remote_uri(path: Optional[str]) -> bool:
    if not path:
        return False
    scheme = urlparse(path).scheme
    return scheme not in ("", "file")


def get_storage(uri: str) -> "Storage":
    scheme = urlparse(uri).scheme
    if scheme in ("", "file"):
        return LocalStorage(urlparse(uri).path if scheme else uri)
    if scheme == "mock":
        return MockRemoteStorage(uri)
    return ArrowStorage(uri)


class Storage:
    """upload/download directories addressed by a path relative to the root
    URI. `uri_of(rel)` returns the absolute URI of a relative path."""

    def uri_of(self, rel: str) -> str:
        raise NotImplementedError

    def upload_dir(self, local_dir: str, rel: str) -> str:
        raise NotImplementedError

    def download_dir(self, rel: str, local_dir: str) -> str:
        raise NotImplementedError

    def delete_dir(self, rel: str):
        raise NotImplementedError

    def list_dirs(self, rel: str = "") -> List[str]:
        raise NotImplementedError


class LocalStorage(Storage):
    def __init__(self, root: str):
        self.root = os.path.abspath(root)

    def uri_of(self, rel: str) -> str:
        return os.path.join(self.root, rel)

    def upload_dir(self, local_dir: str, rel: str) -> str:
        dest = self.uri_of(rel)
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        shutil.copytree(local_dir, dest, dirs_exist_ok=True)
        return dest

    def download_dir(self, rel: str, local_dir: str) -> str:
        src = rel if os.path.isabs(rel) else self.uri_of(rel)
        shutil.copytree(src, local_dir, dirs_exist_ok=True)
        return local_dir

    def delete_dir(self, rel: str):
        shutil.rmtree(self.uri_of(rel), ignore_errors=True)

    def list_dirs(self, rel: str = "") -> List[str]:
        path = self.uri_of(rel) if rel else self.root
        if not os.path.isdir(path):
            return []
        return sorted(
            d for d in os.listdir(path)
            if os.path.isdir(os.path.join(path, d))
        )


class _MockStorageActor:
    """Detached actor holding {path: bytes} — the 'remote bucket'."""

    def __init__(self):
        self._files: Dict[str, bytes] = {}

    def put_files(self, files: Dict[str, bytes]):
        self._files.update(files)
        return True

    def get_files(self, prefix: str) -> Dict[str, bytes]:
        prefix = prefix.rstrip("/") + "/"
        return {k: v for k, v in self._files.items() if k.startswith(prefix)}

    def delete_prefix(self, prefix: str):
        prefix = prefix.rstrip("/") + "/"
        for k in [k for k in self._files if k.startswith(prefix)]:
            del self._files[k]
        return True

    def list_dirs(self, prefix: str) -> List[str]:
        prefix = prefix.rstrip("/")
        pre = prefix + "/" if prefix else ""
        out = set()
        for k in self._files:
            if k.startswith(pre):
                rest = k[len(pre):]
                if "/" in rest:
                    out.add(rest.split("/", 1)[0])
        return sorted(out)


class MockRemoteStorage(Storage):
    """mock://bucket/prefix — files live in a detached named actor, so any
    node of the cluster can up/download without a shared filesystem."""

    def __init__(self, uri: str):
        p = urlparse(uri)
        self.uri_root = uri.rstrip("/")
        self.prefix = (p.netloc + p.path).rstrip("/")

    def _actor(self):
        import ray_tpu

        try:
            return ray_tpu.get_actor(MOCK_STORAGE_ACTOR)
        except Exception:
            try:
                return (
                    ray_tpu.remote(_MockStorageActor)
                    .options(name=MOCK_STORAGE_ACTOR, lifetime="detached",
                             num_cpus=0)
                    .remote()
                )
            except Exception:
                return ray_tpu.get_actor(MOCK_STORAGE_ACTOR)

    def uri_of(self, rel: str) -> str:
        return f"{self.uri_root}/{rel}" if rel else self.uri_root

    def _key(self, rel: str) -> str:
        return f"{self.prefix}/{rel}" if rel else self.prefix

    def upload_dir(self, local_dir: str, rel: str) -> str:
        import ray_tpu

        files = {}
        base = self._key(rel)
        for dirpath, _, names in os.walk(local_dir):
            for n in names:
                fp = os.path.join(dirpath, n)
                rp = os.path.relpath(fp, local_dir)
                with open(fp, "rb") as f:
                    files[f"{base}/{rp}"] = f.read()
        ray_tpu.get(self._actor().put_files.remote(files), timeout=120)
        return self.uri_of(rel)

    def download_dir(self, rel: str, local_dir: str) -> str:
        import ray_tpu

        base = self._key(rel)
        files = ray_tpu.get(self._actor().get_files.remote(base), timeout=120)
        if not files:
            raise FileNotFoundError(f"{self.uri_of(rel)} is empty/missing")
        for key, data in files.items():
            rp = key[len(base) + 1:]
            dest = os.path.join(local_dir, rp)
            os.makedirs(os.path.dirname(dest), exist_ok=True)
            with open(dest, "wb") as f:
                f.write(data)
        return local_dir

    def delete_dir(self, rel: str):
        import ray_tpu

        ray_tpu.get(self._actor().delete_prefix.remote(self._key(rel)),
                    timeout=60)

    def list_dirs(self, rel: str = "") -> List[str]:
        import ray_tpu

        return ray_tpu.get(self._actor().list_dirs.remote(self._key(rel)),
                           timeout=60)


class ArrowStorage(Storage):
    """Real remote filesystems through pyarrow.fs (s3://, gs://, hdfs://)."""

    def __init__(self, uri: str):
        import pyarrow.fs as pafs

        self.uri_root = uri.rstrip("/")
        self.fs, self.root_path = pafs.FileSystem.from_uri(self.uri_root)

    def uri_of(self, rel: str) -> str:
        return f"{self.uri_root}/{rel}" if rel else self.uri_root

    def _key(self, rel: str) -> str:
        return f"{self.root_path}/{rel}" if rel else self.root_path

    def upload_dir(self, local_dir: str, rel: str) -> str:
        import pyarrow.fs as pafs

        pafs.copy_files(local_dir, self._key(rel),
                        destination_filesystem=self.fs)
        return self.uri_of(rel)

    def download_dir(self, rel: str, local_dir: str) -> str:
        import pyarrow.fs as pafs

        src = rel if "://" in rel else self._key(rel)
        pafs.copy_files(src, local_dir, source_filesystem=self.fs)
        return local_dir

    def delete_dir(self, rel: str):
        self.fs.delete_dir_contents(self._key(rel), missing_dir_ok=True)

    def list_dirs(self, rel: str = "") -> List[str]:
        import pyarrow.fs as pafs

        sel = pafs.FileSelector(self._key(rel), allow_not_found=True)
        return sorted(
            os.path.basename(f.path) for f in self.fs.get_file_info(sel)
            if f.type == pafs.FileType.Directory
        )
