"""ray_tpu — a TPU-native distributed AI runtime with the capabilities of Ray.

Core runtime: tasks, actors, a shared-memory object store, ownership-based
distributed refcounting, resource-aware two-level scheduling, placement
groups, fault tolerance — plus ML libraries (train/tune/data/serve/rllib)
whose device plane is jax/XLA/pallas over TPU ICI instead of torch/NCCL.

Attribute access is lazy (PEP 562) so control-plane processes (gcs_server,
raylet) that import only their own submodules don't pay for the full API.
"""

from ray_tpu._version import version as __version__  # noqa: F401

_API = {
    "available_resources", "cancel", "cluster_resources", "get", "init",
    "is_initialized", "kill", "nodes", "put", "remote", "shutdown",
    "timeline", "wait",
}

__all__ = sorted(
    _API
    | {
        "__version__", "ObjectRef", "ActorClass", "ActorHandle", "get_actor",
        "RemoteFunction", "get_runtime_context", "exceptions", "method",
    }
)


def __getattr__(name):
    if name in _API:
        import ray_tpu.api as _api

        return getattr(_api, name)
    if name == "ObjectRef":
        from ray_tpu._private.object_ref import ObjectRef

        return ObjectRef
    if name in ("ActorClass", "ActorHandle", "get_actor"):
        import ray_tpu.actor as _actor

        return getattr(_actor, name)
    if name == "RemoteFunction":
        from ray_tpu.remote_function import RemoteFunction

        return RemoteFunction
    if name == "get_runtime_context":
        from ray_tpu.runtime_context import get_runtime_context

        return get_runtime_context
    if name == "exceptions":
        import ray_tpu.exceptions as _exc

        return _exc
    if name == "method":
        from ray_tpu.actor import method

        return method
    if name == "util":
        import ray_tpu.util as _util

        return _util
    if name == "cluster_utils":
        import ray_tpu.cluster_utils as _cu

        return _cu
    if name in ("train", "tune", "data", "serve", "rllib", "workflow",
                "dag", "autoscaler", "job_submission"):
        import importlib

        return importlib.import_module(f"ray_tpu.{name}")
    raise AttributeError(f"module 'ray_tpu' has no attribute '{name}'")
