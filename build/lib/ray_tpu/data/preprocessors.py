"""Fit/transform preprocessors over Datasets
(reference: python/ray/data/preprocessors/ — scaler/encoder/concatenator
subset). A preprocessor computes its statistics with one aggregation pass
(`fit`), then `transform` is a stateless map_batches stage that streams
through the executor like any other operator.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ray_tpu.data.dataset import Dataset


class Preprocessor:
    """Base: fit(ds) -> self; transform(ds) -> Dataset; fit_transform."""

    _fitted = False

    def fit(self, ds: Dataset) -> "Preprocessor":
        self._fit(ds)
        self._fitted = True
        return self

    def transform(self, ds: Dataset) -> Dataset:
        if not self._fitted and self._needs_fit():
            raise RuntimeError(f"{type(self).__name__} must be fit first")
        return ds.map_batches(self._transform_block)

    def fit_transform(self, ds: Dataset) -> Dataset:
        return self.fit(ds).transform(ds)

    def _needs_fit(self) -> bool:
        return True

    def _fit(self, ds: Dataset):
        raise NotImplementedError

    def _transform_block(self, block):
        raise NotImplementedError


class StandardScaler(Preprocessor):
    """(x - mean) / std per column (reference: preprocessors/scaler.py)."""

    def __init__(self, columns: List[str]):
        self.columns = columns
        self.stats_: Dict[str, tuple] = {}

    def _fit(self, ds: Dataset):
        for c in self.columns:
            v = ds._column(c).astype(np.float64)
            std = v.std()
            self.stats_[c] = (v.mean(), std if std > 0 else 1.0)

    def _transform_block(self, block):
        out = dict(block)
        for c in self.columns:
            mean, std = self.stats_[c]
            out[c] = (np.asarray(block[c], dtype=np.float64) - mean) / std
        return out


class MinMaxScaler(Preprocessor):
    def __init__(self, columns: List[str]):
        self.columns = columns
        self.stats_: Dict[str, tuple] = {}

    def _fit(self, ds: Dataset):
        for c in self.columns:
            v = ds._column(c).astype(np.float64)
            lo, hi = v.min(), v.max()
            self.stats_[c] = (lo, (hi - lo) if hi > lo else 1.0)

    def _transform_block(self, block):
        out = dict(block)
        for c in self.columns:
            lo, span = self.stats_[c]
            out[c] = (np.asarray(block[c], dtype=np.float64) - lo) / span
        return out


class LabelEncoder(Preprocessor):
    """Map category values to dense int codes
    (reference: preprocessors/encoder.py OrdinalEncoder/LabelEncoder)."""

    def __init__(self, label_column: str):
        self.label_column = label_column
        self.classes_: Optional[np.ndarray] = None

    def _fit(self, ds: Dataset):
        self.classes_ = np.asarray(ds.unique(self.label_column))

    def _transform_block(self, block):
        out = dict(block)
        vals = np.asarray(block[self.label_column])
        codes = np.searchsorted(self.classes_, vals)
        bad = (codes >= len(self.classes_)) | (self.classes_[
            np.minimum(codes, len(self.classes_) - 1)] != vals)
        if bad.any():
            raise ValueError(
                f"unseen {self.label_column!r} categories: "
                f"{sorted(set(np.asarray(vals)[bad].tolist()))[:5]}"
            )
        out[self.label_column] = codes.astype(np.int64)
        return out


class OneHotEncoder(Preprocessor):
    def __init__(self, columns: List[str]):
        self.columns = columns
        self.classes_: Dict[str, np.ndarray] = {}

    def _fit(self, ds: Dataset):
        for c in self.columns:
            self.classes_[c] = np.asarray(ds.unique(c))

    def _transform_block(self, block):
        out = dict(block)
        for c in self.columns:
            classes = self.classes_[c]
            vals = np.asarray(block[c])
            codes = np.searchsorted(classes, vals)
            bad = (codes >= len(classes)) | (classes[
                np.minimum(codes, len(classes) - 1)] != vals)
            if bad.any():
                raise ValueError(
                    f"unseen {c!r} categories: "
                    f"{sorted(set(vals[bad].tolist()))[:5]}"
                )
            eye = np.eye(len(classes), dtype=np.float32)
            del out[c]
            hot = eye[codes]
            for j, cls in enumerate(classes):
                out[f"{c}_{cls}"] = hot[:, j]
        return out


class Concatenator(Preprocessor):
    """Pack multiple numeric columns into one feature matrix column
    (reference: preprocessors/concatenator.py) — the usual last stage before
    a jax device_put, so the train loop sees one (B, F) array."""

    def __init__(self, columns: List[str], output_column: str = "features",
                 dtype=np.float32):
        self.columns = columns
        self.output_column = output_column
        self.dtype = dtype

    def _needs_fit(self) -> bool:
        return False

    def _fit(self, ds: Dataset):
        pass

    def _transform_block(self, block):
        out = {k: v for k, v in block.items() if k not in self.columns}
        mats = [np.asarray(block[c], dtype=self.dtype).reshape(
            len(np.asarray(block[c])), -1) for c in self.columns]
        out[self.output_column] = np.concatenate(mats, axis=1)
        return out


class Chain(Preprocessor):
    """Apply preprocessors in sequence (reference: preprocessors/chain.py)."""

    def __init__(self, *stages: Preprocessor):
        self.stages = stages

    def fit(self, ds: Dataset) -> "Chain":
        for st in self.stages:
            ds = st.fit_transform(ds)
        self._fitted = True
        return self

    def transform(self, ds: Dataset) -> Dataset:
        for st in self.stages:
            ds = st.transform(ds)
        return ds

    def fit_transform(self, ds: Dataset) -> Dataset:
        for st in self.stages:
            ds = st.fit_transform(ds)
        return ds
