"""Block model: the unit of data movement (reference: python/ray/data/block.py).

A block is either a row block (``list`` of items) or a column block
(``dict[str, np.ndarray]``). Blocks travel between operators as object-store
refs, so a map stage on another worker reads them zero-copy from plasma.
"""

from __future__ import annotations

from typing import Any, Dict, List, Union

import numpy as np

Block = Union[List[Any], Dict[str, np.ndarray]]


def block_num_rows(block: Block) -> int:
    if isinstance(block, dict):
        if not block:
            return 0
        return len(next(iter(block.values())))
    return len(block)


def slice_block(block: Block, start: int, end: int) -> Block:
    if isinstance(block, dict):
        return {k: v[start:end] for k, v in block.items()}
    return block[start:end]


def concat_blocks(blocks: List[Block]) -> Block:
    blocks = [b for b in blocks if block_num_rows(b)]
    if not blocks:
        return []
    if isinstance(blocks[0], dict):
        keys = blocks[0].keys()
        return {k: np.concatenate([np.asarray(b[k]) for b in blocks])
                for k in keys}
    out: List[Any] = []
    for b in blocks:
        out.extend(b)
    return out


def block_schema(block: Block):
    if isinstance(block, dict):
        return {k: np.asarray(v).dtype for k, v in block.items()}
    if block:
        return type(block[0])
    return None


def rows_of(block: Block):
    """Iterate a block as python rows (dict rows for column blocks)."""
    if isinstance(block, dict):
        keys = list(block.keys())
        n = block_num_rows(block)
        for i in range(n):
            yield {k: block[k][i] for k in keys}
    else:
        yield from block
