"""Mixture-of-Experts FFN with expert parallelism, TPU-first.

The reference has no MoE/expert-parallel machinery at all (SURVEY §2.4:
"Expert parallel (EP/MoE): Absent") — this is green-field, built the way
TPU MoE is actually done (Switch/Mixtral-style, the Mesh-TensorFlow dense
dispatch/combine formulation used by t5x/flaxformer): top-k routing with a
static per-expert capacity, dispatch/combine as einsums so everything is
static-shaped and XLA lowers the expert-sharded contractions to
all-to-alls over the 'ep' mesh axis — no ragged ops, no host control flow.

Layout: expert weights carry a leading E dim sharded on 'ep'
(``MOE_SHARDING_RULES``); tokens stay sharded on dp/sp. Under pjit the
dispatch einsum becomes the a2a scatter and the combine einsum the a2a
gather, riding ICI.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 8
    top_k: int = 2
    # capacity per expert = ceil(top_k * tokens * capacity_factor / E)
    capacity_factor: float = 1.25
    # Switch-style load-balance auxiliary loss weight
    aux_loss_weight: float = 0.01


def top_k_routing(
    probs: jnp.ndarray, k: int, capacity: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """probs (B,S,E) → (dispatch (B,S,E,C) bool-ish, combine (B,S,E,C)).

    Tokens beyond an expert's capacity are dropped (their combine weight is
    zero → they pass through the residual only), earlier sequence positions
    win — the standard static-capacity contract.
    """
    B, S, E = probs.shape
    gate_vals, gate_idx = jax.lax.top_k(probs, k)  # (B,S,k)
    # renormalize the kept gates so they sum to 1 per token
    gate_vals = gate_vals / jnp.maximum(
        gate_vals.sum(axis=-1, keepdims=True), 1e-9
    )
    dispatch = jnp.zeros((B, S, E, capacity), dtype=probs.dtype)
    combine = jnp.zeros((B, S, E, capacity), dtype=probs.dtype)
    # tokens already admitted per (batch, expert)
    used = jnp.zeros((B, E), dtype=jnp.int32)
    for i in range(k):
        mask_i = jax.nn.one_hot(gate_idx[..., i], E, dtype=jnp.int32)  # (B,S,E)
        # position of each token within its expert's buffer
        pos_i = jnp.cumsum(mask_i, axis=1) - 1 + used[:, None, :]
        keep = mask_i * (pos_i < capacity)
        used = used + keep.sum(axis=1)
        pos_oh = jax.nn.one_hot(pos_i, capacity, dtype=probs.dtype)  # (B,S,E,C)
        sel = keep.astype(probs.dtype)[..., None] * pos_oh
        dispatch = dispatch + sel
        combine = combine + sel * gate_vals[..., i, None, None]
    return dispatch, combine


def load_balance_loss(probs: jnp.ndarray, dispatch: jnp.ndarray) -> jnp.ndarray:
    """Switch aux loss: E * Σ_e (token fraction_e · mean prob_e)."""
    E = probs.shape[-1]
    tokens_per_expert = dispatch.sum(axis=(1, 3))  # (B,E)
    total = jnp.maximum(tokens_per_expert.sum(axis=-1, keepdims=True), 1.0)
    fraction = tokens_per_expert / total
    mean_prob = probs.mean(axis=1)  # (B,E)
    return E * (fraction * mean_prob).sum(axis=-1).mean()


class MoE(nn.Module):
    """Drop-in FFN replacement: (B,S,C) → (B,S,C) plus an aux loss that the
    caller adds to the objective (collected via self.sow 'losses')."""

    d_model: int
    d_ff: int
    moe: MoEConfig
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, deterministic=True):
        B, S, C = x.shape
        E, k = self.moe.num_experts, self.moe.top_k
        capacity = max(
            1, int(-(-k * S * self.moe.capacity_factor // E))
        )
        # Router always in fp32: tiny matmul, big numerical leverage.
        gate_logits = nn.Dense(
            E, dtype=jnp.float32, param_dtype=jnp.float32, name="router"
        )(x.astype(jnp.float32))
        probs = jax.nn.softmax(gate_logits, axis=-1)
        dispatch, combine = top_k_routing(probs, k, capacity)
        aux = load_balance_loss(probs, dispatch) * self.moe.aux_loss_weight
        self.sow("losses", "moe_aux", aux)

        wi = self.param(
            "wi",
            nn.initializers.lecun_normal(),
            (E, C, self.d_ff),
            jnp.float32,
        )
        wo = self.param(
            "wo",
            nn.initializers.lecun_normal(),
            (E, self.d_ff, C),
            jnp.float32,
        )
        dispatch = dispatch.astype(self.dtype)
        combine = combine.astype(self.dtype)
        xd = x.astype(self.dtype)
        # scatter tokens to experts (a2a over 'ep' under pjit)
        expert_in = jnp.einsum(
            "bsec,bsm->ebcm", dispatch, xd, preferred_element_type=self.dtype
        )
        h = jnp.einsum(
            "ebcm,emf->ebcf",
            expert_in,
            wi.astype(self.dtype),
            preferred_element_type=jnp.float32,
        )
        h = nn.gelu(h.astype(self.dtype), approximate=True)
        out = jnp.einsum(
            "ebcf,efm->ebcm",
            h,
            wo.astype(self.dtype),
            preferred_element_type=jnp.float32,
        ).astype(self.dtype)
        # gather back (the reverse a2a)
        return jnp.einsum(
            "bsec,ebcm->bsm", combine, out, preferred_element_type=jnp.float32
        ).astype(x.dtype)


# Expert weights sharded over 'ep' (leading E dim), inner dims reuse the
# dense tp/fsdp layout; router replicated.
MOE_SHARDING_PATTERNS = [
    (r"moe/router/kernel", P()),
    (r"moe/router/bias", P()),
    (r"moe/wi", P("ep", "fsdp", "tp")),
    (r"moe/wo", P("ep", "tp", "fsdp")),
]
