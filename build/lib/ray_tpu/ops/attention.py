"""Fused causal attention: pallas flash kernel (TPU) with an XLA fallback.

FlashAttention-2-style tiling: the query axis is the pallas grid, K/V are
streamed block-by-block with an online softmax (running max + sum in VMEM
scratch, fp32). The backward pass recomputes attention per tile from the saved
logsumexp — O(T) memory instead of O(T^2). All matmuls run on the MXU with
fp32 accumulation.

The reference framework has no attention kernels at all (its data plane is
torch); this op is the building block its GPU stack gets from flash-attn, and
the ring-attention layer (ray_tpu/ops/ring_attention.py) composes it per-step
for sequence parallelism.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _pick_block(t: int, target: int = 128) -> int:
    if t % target == 0:
        return target
    for b in (64, 32, 16, 8):
        if t % b == 0:
            return b
    return t


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, block_q, block_k, seq_len):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale  # (block_q, d)
    d = q.shape[-1]

    m = jnp.full((block_q, 1), NEG_INF, dtype=jnp.float32)
    l = jnp.zeros((block_q, 1), dtype=jnp.float32)
    acc = jnp.zeros((block_q, d), dtype=jnp.float32)

    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(kj, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (block_q, block_k)
        k_pos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l, acc

    num_k_blocks = (qi + 1) * block_q // block_k  # causal: only blocks at/below diag
    m, l, acc = jax.lax.fori_loop(0, num_k_blocks, body, (m, l, acc))
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    # lse rides a (bh, 1, t) layout: block (1, 1, block_q) keeps Mosaic's
    # last-two-dims tiling rule satisfied (a (1, block_q) rank-2 block is not)
    lse_ref[0, 0] = (m + jnp.log(l))[:, 0]


def _flash_fwd(q, k, v, *, block_q, block_k, interpret):
    bh, t, d = q.shape
    scale = 1.0 / math.sqrt(d)
    grid = (bh, t // block_q)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, block_q=block_q, block_k=block_k, seq_len=t
    )
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, t), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse


# --------------------------------------------------------------------------
# backward
# --------------------------------------------------------------------------


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, scale, block_q, block_k):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, 0][:, None]
    delta = delta_ref[0, 0][:, None]
    d = q.shape[-1]
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(kj, dq):
        k = k_ref[0, pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        k_pos = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        return dq + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    num_k_blocks = (qi + 1) * block_q // block_k
    dq = jax.lax.fori_loop(0, num_k_blocks, body, jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
                    *, scale, block_q, block_k, seq_len):
    kj = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    d = k.shape[-1]
    k_pos = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32) * scale
        do = do_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(qi * block_q, block_q)][:, None]
        delta = delta_ref[0, 0, pl.ds(qi * block_q, block_q)][:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)
        dv = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        dk = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dk, dv

    first_q_block = kj * block_k // block_q  # causal: q blocks at/after the diagonal
    num_q_blocks = seq_len // block_q
    dk, dv = jax.lax.fori_loop(
        first_q_block, num_q_blocks, body,
        (jnp.zeros((block_k, d), jnp.float32), jnp.zeros((block_k, d), jnp.float32)),
    )
    # q was pre-scaled, so ds^T @ q_scaled already carries the 1/sqrt(d) factor.
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd(res, g, *, block_q, block_k, interpret):
    q, k, v, o, lse = res
    do = g
    bh, t, d = q.shape
    scale = 1.0 / math.sqrt(d)
    delta = jnp.sum(
        o.astype(jnp.float32) * do.astype(jnp.float32), axis=-1
    )[:, None, :]  # (bh, 1, t) — same layout as lse

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, block_q=block_q, block_k=block_k),
        grid=(bh, t // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, scale=scale, block_q=block_q, block_k=block_k, seq_len=t
        ),
        grid=(bh, t // block_k),
        in_specs=[
            pl.BlockSpec((1, t, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, t, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, 1, t), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, 1, t), lambda b, j: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# --------------------------------------------------------------------------
# public entry points
# --------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, block_q, block_k, interpret):
    o, _ = _flash_fwd(q, k, v, block_q=block_q, block_k=block_k, interpret=interpret)
    return o


def _flash_fwd_rule(q, k, v, block_q, block_k, interpret):
    o, lse = _flash_fwd(q, k, v, block_q=block_q, block_k=block_k, interpret=interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd_rule(block_q, block_k, interpret, res, g):
    return _flash_bwd(res, g, block_q=block_q, block_k=block_k, interpret=interpret)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_causal_attention(q, k, v, *, block_q=None, block_k=None, interpret=False):
    """q/k/v: (B, H, T, D) → (B, H, T, D); fused causal attention."""
    b, h, t, d = q.shape
    block_q = block_q or _pick_block(t)
    block_k = block_k or _pick_block(t)
    # The kernel's causal lower bound num_k_blocks = (qi+1)*block_q//block_k
    # is 0 for early q blocks when block_q < block_k, leaving l==0 and o=NaN.
    if block_q < block_k or block_q % block_k:
        raise ValueError(
            f"block_q ({block_q}) must be a multiple of block_k ({block_k}) "
            "for the causal flash kernel: its causal bound "
            "(qi+1)*block_q//block_k floors, skipping keys otherwise"
        )
    if t % block_q or t % block_k:
        raise ValueError(f"seq len {t} must be divisible by block sizes")
    qf = q.reshape(b * h, t, d)
    kf = k.reshape(b * h, t, d)
    vf = v.reshape(b * h, t, d)
    o = _flash(qf, kf, vf, block_q, block_k, interpret)
    return o.reshape(b, h, t, d)


def xla_causal_attention(q, k, v):
    """Plain einsum-softmax reference path; XLA fuses it adequately on TPU."""
    d = q.shape[-1]
    t = q.shape[2]
    s = jnp.einsum("bhtd,bhsd->bhts", q, k, preferred_element_type=jnp.float32)
    s = s / math.sqrt(d)
    mask = jnp.tril(jnp.ones((t, t), dtype=bool))
    s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhts,bhsd->bhtd", p, v)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def causal_attention(q, k, v):
    """Layout-adapting entry: q/k/v (B, T, H, D) → (B, T, H, D).

    Uses the pallas flash kernel on TPU for sequences long enough to matter;
    XLA path elsewhere (CPU tests, tiny shapes).
    """
    B, T, H, D = q.shape
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    if _on_tpu() and T >= 256 and T % 128 == 0:
        o = flash_causal_attention(qt, kt, vt)
    else:
        o = xla_causal_attention(qt, kt, vt)
    return o.transpose(0, 2, 1, 3)
