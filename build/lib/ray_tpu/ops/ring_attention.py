"""Ring attention: causal attention with the sequence axis sharded over a mesh.

Green-field work — the reference has no sequence/context parallelism at all
(verified in SURVEY.md §2.4: no ring-attention/Ulysses anywhere in it). Design:

- q/k/v live sharded on the 'sp' mesh axis: each device holds a contiguous
  sequence chunk (B, T/n, H, D).
- K/V chunks rotate around the ring with `jax.lax.ppermute` (one ICI hop per
  step, n-1 steps) while each device's q chunk stays put; communication
  overlaps with the chunk attention compute under XLA's scheduler.
- Per-chunk results merge with the standard streaming-softmax rule in
  log-space (running logsumexpt), so the result is exactly softmax over the
  full sequence — verified against single-device attention in tests.
- Causality is enforced by *global* position masks (chunk offset = owner
  device index × chunk length), so fully-masked chunks contribute -inf lse
  and drop out of the merge.

Use `ring_causal_attention` inside shard_map/pjit with an 'sp' axis, or the
`ring_attention_sharded` convenience wrapper that builds the shard_map.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NEG_INF = -1e30


def _chunk_attn(q, k, v, q_offset, k_offset, scale):
    """Attention of a q chunk over one k/v chunk with global causal masking.

    q: (B, Tq, H, D); k/v: (B, Tk, H, D). Returns (o, lse) with
    o: (B, Tq, H, D) fp32 *unnormalized by global softmax* (normalized within
    chunk), lse: (B, Tq, H) log-sum-exp of this chunk's scores.
    """
    Tq, Tk = q.shape[1], k.shape[1]
    s = jnp.einsum(
        "bthd,bshd->bhts", q, k, preferred_element_type=jnp.float32
    ) * scale
    q_pos = q_offset + jax.lax.broadcasted_iota(jnp.int32, (Tq, Tk), 0)
    k_pos = k_offset + jax.lax.broadcasted_iota(jnp.int32, (Tq, Tk), 1)
    s = jnp.where((q_pos >= k_pos)[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)                                   # (B, H, Tq)
    # guard fully-masked rows: exp(NEG_INF - NEG_INF) would be 1
    safe_m = jnp.maximum(m, -1e29)
    p = jnp.exp(s - safe_m[..., None])
    p = jnp.where((q_pos >= k_pos)[None, None], p, 0.0)
    l = jnp.sum(p, axis=-1)                                   # (B, H, Tq)
    o = jnp.einsum("bhts,bshd->bthd", p.astype(q.dtype), v,
                   preferred_element_type=jnp.float32)
    lse = jnp.where(l > 0, safe_m + jnp.log(jnp.maximum(l, 1e-30)), NEG_INF)
    # o is sum(exp(s - safe_m) * v); caller renormalizes via lse
    return o, lse.transpose(0, 2, 1), safe_m.transpose(0, 2, 1)  # (B, Tq, H)


def ring_causal_attention(q, k, v, axis_name: str = "sp"):
    """Causal attention across the ring; call inside shard_map over axis_name.

    q/k/v: local chunks (B, Tl, H, D). Returns (B, Tl, H, D).
    """
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, Tl, H, D = q.shape
    scale = 1.0 / math.sqrt(D)
    q_offset = idx * Tl

    def step(carry, s):
        k_cur, v_cur, acc, lse_acc = carry
        owner = (idx - s) % n            # which device's chunk we hold now
        k_offset = owner * Tl
        o, lse, m = _chunk_attn(q, k_cur, v_cur, q_offset, k_offset, scale)
        # merge (streaming softmax in log space); o is scaled by exp(-m)
        new_lse = jnp.logaddexp(lse_acc, lse)
        w_old = jnp.exp(jnp.clip(lse_acc - new_lse, -80, 0))
        w_new = jnp.exp(jnp.clip(lse - new_lse, -80, 0))
        # o currently = softmax-numerator / exp(m) → renormalize by exp(lse - m)
        o_norm = o * jnp.exp(jnp.clip(m - lse, -80, 80))[..., None].transpose(0, 1, 2, 3)
        acc = acc * w_old[..., None] + o_norm * w_new[..., None]
        lse_acc = new_lse
        # rotate k/v to the next device (ring over ICI)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, acc, lse_acc), None

    acc0 = jnp.zeros((B, Tl, H, D), jnp.float32)
    lse0 = jnp.full((B, Tl, H), NEG_INF, jnp.float32)
    (k_f, v_f, acc, lse_acc), _ = jax.lax.scan(
        step, (k, v, acc0, lse0), jnp.arange(n)
    )
    return acc.astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh: Mesh, axis_name: str = "sp",
                           batch_axes=("dp", "fsdp")):
    """Global-array convenience wrapper: shard_map over the sequence axis."""
    from jax import shard_map

    data = tuple(a for a in batch_axes if a in mesh.axis_names)
    spec = P(data if data else None, axis_name, None, None)
    fn = shard_map(
        functools.partial(ring_causal_attention, axis_name=axis_name),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
