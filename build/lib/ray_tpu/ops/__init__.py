"""TPU compute ops: pallas kernels with XLA fallbacks."""
