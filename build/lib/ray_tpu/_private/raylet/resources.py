"""Fixed-point resource accounting.

Mirrors the reference's FixedPoint resource arithmetic
(reference: src/ray/common/scheduling/fixed_point.h,
cluster_resource_data.h:36): quantities are stored in integer 1/10000 units so
repeated grant/release cycles can't drift the way float arithmetic does.
Fractional resources (e.g. num_cpus=0.5) therefore work exactly.
"""

from __future__ import annotations

from typing import Dict, Iterable

PRECISION = 10_000


def to_fixed(resources: Dict[str, float]) -> Dict[str, int]:
    return {k: int(round(v * PRECISION)) for k, v in resources.items() if v}


def from_fixed(resources: Dict[str, int]) -> Dict[str, float]:
    return {k: v / PRECISION for k, v in resources.items()}


class ResourceSet:
    """Mutable set of named resource quantities in fixed-point units."""

    __slots__ = ("_r",)

    def __init__(self, resources: Dict[str, float] | None = None, fixed: Dict[str, int] | None = None):
        if fixed is not None:
            self._r = {k: v for k, v in fixed.items() if v}
        else:
            self._r = to_fixed(resources or {})

    def fits(self, demand: "ResourceSet") -> bool:
        return all(self._r.get(k, 0) >= v for k, v in demand._r.items())

    def acquire(self, demand: "ResourceSet") -> bool:
        if not self.fits(demand):
            return False
        for k, v in demand._r.items():
            self._r[k] = self._r.get(k, 0) - v
        return True

    def release(self, demand: "ResourceSet"):
        for k, v in demand._r.items():
            self._r[k] = self._r.get(k, 0) + v

    def add(self, other: "ResourceSet"):
        self.release(other)

    def subtract_capped(self, other: "ResourceSet"):
        for k, v in other._r.items():
            self._r[k] = max(0, self._r.get(k, 0) - v)

    def get(self, name: str) -> float:
        return self._r.get(name, 0) / PRECISION

    def to_dict(self) -> Dict[str, float]:
        return from_fixed(self._r)

    def copy(self) -> "ResourceSet":
        rs = ResourceSet()
        rs._r = dict(self._r)
        return rs

    def keys(self) -> Iterable[str]:
        return self._r.keys()

    def is_empty(self) -> bool:
        return not any(v > 0 for v in self._r.values())

    def __repr__(self):
        return f"ResourceSet({self.to_dict()})"
