"""Prometheus-format metrics: registry, text rendering, HTTP endpoint.

Counterpart of the reference's stats pipeline
(reference: src/ray/stats/metric.h + metric_defs.cc ~48 OpenCensus metrics
exported through the per-node MetricsAgent to a Prometheus scrape endpoint,
python/ray/_private/metrics_agent.py:483). Here each control-plane process
(GCS, raylet) serves its own /metrics directly from one tiny asyncio HTTP
listener; user-defined metrics (ray_tpu.util.metrics) are pushed to the GCS
and exported from its endpoint.
"""

from __future__ import annotations

import asyncio
from typing import Callable, Dict, List, Optional, Tuple

# sample: (name, labels-dict, value)
Sample = Tuple[str, Dict[str, str], float]


def render_prometheus(
    samples: List[Sample], help_text: Optional[Dict[str, str]] = None
) -> str:
    """Render samples in the Prometheus text exposition format."""
    help_text = help_text or {}
    by_name: Dict[str, List[Sample]] = {}
    for s in samples:
        by_name.setdefault(s[0], []).append(s)
    out = []
    for name in sorted(by_name):
        if name in help_text:
            out.append(f"# HELP {name} {help_text[name]}")
        out.append(f"# TYPE {name} gauge")
        for _, labels, value in by_name[name]:
            if labels:
                inner = ",".join(
                    f'{k}="{_escape(v)}"' for k, v in sorted(labels.items())
                )
                out.append(f"{name}{{{inner}}} {value}")
            else:
                out.append(f"{name} {value}")
    return "\n".join(out) + "\n"


def _escape(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


async def start_metrics_http_server(
    host: str, collect: Callable[[], str], port: int = 0
) -> Tuple[asyncio.AbstractServer, int]:
    """Serve GET /metrics (and anything else) with the collector's output."""

    async def _handle(reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            # Read and discard the request head; we serve one document.
            try:
                await asyncio.wait_for(reader.readuntil(b"\r\n\r\n"), 5.0)
            except Exception:
                return
            try:
                body = collect().encode()
                status = b"200 OK"
            except Exception as e:
                body = f"collector error: {e}".encode()
                status = b"500 Internal Server Error"
            writer.write(
                b"HTTP/1.1 " + status + b"\r\n"
                b"Content-Type: text/plain; version=0.0.4; charset=utf-8\r\n"
                + f"Content-Length: {len(body)}\r\n".encode()
                + b"Connection: close\r\n\r\n"
                + body
            )
            await writer.drain()
        finally:
            try:
                writer.close()
            except Exception:
                pass

    server = await asyncio.start_server(_handle, host, port)
    return server, server.sockets[0].getsockname()[1]
