"""In-process stack sampling for on-demand profiling.

Reference: the dashboard's py-spy/memray integration
(dashboard/modules/reporter/profile_manager.py:78/:189). The same
capability without the binary dependency: any worker can sample its own
threads' stacks via sys._current_frames at a fixed rate and return
flamegraph-compatible folded lines ("a;b;c 42"). The dashboard asks the
raylet, the raylet asks the worker (both plain RPCs), so profiling any
process in the cluster is one HTTP call.
"""

from __future__ import annotations

import sys
import threading
import time
from collections import Counter
from typing import Dict


def _frame_label(frame) -> str:
    code = frame.f_code
    fname = code.co_filename.rsplit("/", 1)[-1]
    return f"{code.co_name} ({fname}:{frame.f_lineno})"


def sample_stacks(duration_s: float = 2.0, hz: float = 100.0,
                  include_idle: bool = False) -> Dict[str, int]:
    """Sample all threads for duration_s; returns {folded_stack: count}.

    Runs in the CALLING thread — callers dispatch it to a sampler thread
    (the worker RPC handler does) so the sampled threads keep running.
    """
    duration_s = min(float(duration_s), 60.0)
    hz = min(max(1.0, float(hz)), 500.0)
    period = 1.0 / hz
    me = threading.get_ident()
    names = {t.ident: t.name for t in threading.enumerate()}
    counts: Counter = Counter()
    end = time.monotonic() + duration_s
    while time.monotonic() < end:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            name = names.get(tid) or str(tid)
            if not include_idle and (
                name.startswith("rtpu-io")
                or name.endswith("-watchdog")
            ):
                # the io loop is ~always parked in epoll; skip unless asked
                continue
            stack = []
            f = frame
            depth = 0
            while f is not None and depth < 128:
                stack.append(_frame_label(f))
                f = f.f_back
                depth += 1
            stack.reverse()
            counts[f"{name};" + ";".join(stack)] += 1
        time.sleep(period)
        names = {t.ident: t.name for t in threading.enumerate()}
    return dict(counts)


def folded_text(counts: Dict[str, int]) -> str:
    """flamegraph.pl-compatible folded output, heaviest first."""
    return "\n".join(
        f"{stack} {n}"
        for stack, n in sorted(counts.items(), key=lambda kv: -kv[1])
    )
