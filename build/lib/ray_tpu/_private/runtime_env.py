"""runtime_env materialization: working_dir packaging + per-node extraction.

Counterpart of the reference's runtime_env packaging + agent
(reference: python/ray/_private/runtime_env/packaging.py — zip working_dir
into the GCS KV keyed by content hash; runtime_env/agent/runtime_env_agent.py
— per-node download/extract before worker start). Here the driver uploads,
and the raylet extracts into <session_dir>/runtime_envs/<hash>/ the first
time a lease needs it; workers chdir there via RTPU_WORKING_DIR.
"""

from __future__ import annotations

import hashlib
import io
import os
import zipfile
from typing import Optional

KV_NAMESPACE = "runtime_env"
URI_PREFIX = "kv:"
WORKING_DIR_ENV = "RTPU_WORKING_DIR"

_EXCLUDE_DIRS = {".git", "__pycache__", ".venv", "node_modules"}
_MAX_WORKING_DIR_BYTES = 512 * 1024 * 1024


def package_working_dir(path: str, arc_prefix: str = "") -> bytes:
    """Deterministically zip a local directory (stable hash for same
    content). arc_prefix nests entries under a directory inside the
    archive — py_modules use the module dir's basename so the EXTRACTED
    root is a sys.path entry from which `import <basename>` works
    (reference py_modules contract)."""
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        raise ValueError(f"runtime_env working_dir {path!r} is not a directory")
    buf = io.BytesIO()
    total = 0
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs if d not in _EXCLUDE_DIRS)
            for fname in sorted(files):
                full = os.path.join(root, fname)
                rel = os.path.relpath(full, path)
                if arc_prefix:
                    rel = os.path.join(arc_prefix, rel)
                try:
                    total += os.path.getsize(full)
                except OSError:
                    continue
                if total > _MAX_WORKING_DIR_BYTES:
                    raise ValueError(
                        f"working_dir {path!r} exceeds "
                        f"{_MAX_WORKING_DIR_BYTES} bytes"
                    )
                # Fixed date_time so identical content hashes identically.
                info = zipfile.ZipInfo(rel, date_time=(2000, 1, 1, 0, 0, 0))
                info.external_attr = (os.stat(full).st_mode & 0xFFFF) << 16
                with open(full, "rb") as f:
                    zf.writestr(info, f.read())
    return buf.getvalue()


def upload_working_dir(gcs, path: str, arc_prefix: str = "") -> str:
    """Zip + upload to the GCS KV; returns the kv:<hash> URI."""
    blob = package_working_dir(path, arc_prefix)
    digest = hashlib.sha1(blob).hexdigest()
    key = digest.encode()
    if not gcs.kv_exists(KV_NAMESPACE, key):
        gcs.kv_put(KV_NAMESPACE, key, blob, overwrite=False)
    return URI_PREFIX + digest


def materialized_path(uri: str, base_dir: str) -> str:
    """Where an uploaded working_dir lives once extracted on this node."""
    assert uri.startswith(URI_PREFIX), uri
    return os.path.join(base_dir, "runtime_envs", uri[len(URI_PREFIX):])


def extract_working_dir(uri: str, blob: Optional[bytes], base_dir: str) -> str:
    """Extract an uploaded working_dir under base_dir; idempotent per hash.

    Returns the extracted directory path. ``blob`` may be None if the
    directory already exists (caller can skip the KV fetch). Concurrent
    extractions are safe: each works in a unique tmp dir and the first
    rename wins.
    """
    import uuid

    target = materialized_path(uri, base_dir)
    if os.path.isdir(target):
        return target
    if blob is None:
        raise FileNotFoundError(f"working_dir {uri} not materialized")
    tmp = target + f".tmp.{uuid.uuid4().hex[:8]}"
    os.makedirs(tmp, exist_ok=True)
    try:
        with zipfile.ZipFile(io.BytesIO(blob)) as zf:
            for info in zf.infolist():
                extracted = zf.extract(info, tmp)
                # extractall/extract ignore permissions; restore the modes
                # packaged in external_attr (executables must stay runnable).
                mode = (info.external_attr >> 16) & 0xFFFF
                if mode:
                    os.chmod(extracted, mode & 0o7777)
        os.rename(tmp, target)
    except OSError:
        # Lost a concurrent-extract race: the winner's tree is equivalent.
        if not os.path.isdir(target):
            raise
    finally:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    return target


def dir_signature(path: str) -> str:
    """Cheap content signature (names+sizes+mtimes) for upload caching."""
    h = hashlib.sha1()
    for root, dirs, files in os.walk(path):
        dirs[:] = sorted(d for d in dirs if d not in _EXCLUDE_DIRS)
        for fname in sorted(files):
            full = os.path.join(root, fname)
            try:
                st = os.stat(full)
            except OSError:
                continue
            h.update(
                f"{os.path.relpath(full, path)}:{st.st_size}:{st.st_mtime_ns}".encode()
            )
    return h.hexdigest()


def is_uploaded(working_dir: Optional[str]) -> bool:
    return bool(working_dir) and working_dir.startswith(URI_PREFIX)
