"""GCS table persistence: a msgpack-framed append log with replay/compaction.

The reference persists GCS tables to Redis so the control plane can restart
without losing cluster state (reference: src/ray/gcs/store_client/
redis_store_client.h, gcs_table_storage.h). We keep the same recovery
contract with a much smaller mechanism: every table mutation appends one
framed msgpack record to ``<session_dir>/gcs.log``; on restart the log is
replayed last-write-wins into the in-memory tables and then compacted into a
snapshot so the log never grows unboundedly.

Record layout: 4-byte little-endian length, then ``[kind, data]`` msgpack.
Kinds:
    "kv"    -> [ns, key, value_or_None]           (None = delete)
    "job"   -> job record dict
    "actor" -> actor record dict (incl. creation_spec, for rescheduling)
    "named" -> [ns, name, actor_id_or_None]       (None = released)
    "pg"    -> placement-group record dict (sans ready_event)
    "node"  -> node record dict
A torn tail frame (crash mid-append) is detected by the length prefix and
discarded; everything before it replays.
"""

from __future__ import annotations

import os
import struct
from typing import Iterator, List, Optional, Tuple

import msgpack

_LEN = struct.Struct("<I")
_MAX_RECORD = 1 << 30


class GcsLog:
    """Append-only persistence log for GCS tables."""

    def __init__(self, path: str, fsync: bool = False):
        self.path = path
        self.fsync = fsync
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = None

    def _open(self):
        if self._f is None:
            self._f = open(self.path, "ab")
        return self._f

    def append(self, kind: str, data) -> None:
        body = msgpack.packb([kind, data], use_bin_type=True)
        f = self._open()
        f.write(_LEN.pack(len(body)) + body)
        f.flush()
        if self.fsync:
            os.fsync(f.fileno())

    def replay(self) -> Iterator[Tuple[str, object]]:
        """Yield (kind, data) for every intact record; stop at a torn tail."""
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as f:
            while True:
                header = f.read(_LEN.size)
                if len(header) < _LEN.size:
                    return
                (length,) = _LEN.unpack(header)
                if length > _MAX_RECORD:
                    return
                body = f.read(length)
                if len(body) < length:
                    return  # torn tail: crash mid-append
                try:
                    kind, data = msgpack.unpackb(
                        body, raw=False, strict_map_key=False
                    )
                except Exception:
                    return
                yield kind, data

    @staticmethod
    def pack(records: List[Tuple[str, object]]) -> bytes:
        """Serialize records to the framed on-disk form (caller's thread)."""
        out = []
        for kind, data in records:
            body = msgpack.packb([kind, data], use_bin_type=True)
            out.append(_LEN.pack(len(body)) + body)
        return b"".join(out)

    def compact_packed(self, blob: bytes) -> None:
        """Atomically replace the log with pre-packed snapshot bytes.

        Safe to run in a worker thread: the caller packs on the event loop
        (point-in-time consistent), only the write+fsync happens here.
        """
        self.close()
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def compact(self, records: List[Tuple[str, object]]) -> None:
        """Atomically replace the log with a snapshot of current state."""
        self.compact_packed(self.pack(records))

    def size(self) -> int:
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0

    def close(self):
        if self._f is not None:
            try:
                self._f.close()
            except Exception:
                pass
            self._f = None
