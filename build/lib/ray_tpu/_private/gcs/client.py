"""Client for the GCS server (async core + blocking facade).

Counterpart of the reference's gcs_client/accessor
(reference: src/ray/gcs/gcs_client/gcs_client.h, accessor.h) plus the Python
GcsClient binding (reference: python/ray/_raylet.pyx:2670).
"""

from __future__ import annotations

import asyncio
import os
from typing import Dict, List, Optional

from ray_tpu._private.config import RTPU_CONFIG
from ray_tpu._private.rpc import ConnectionLost, IoThread, RpcClient


class GcsAioClient:
    """All methods must run on the IO loop.

    Calls that hit a dead GCS retry with backoff for up to
    ``gcs_reconnect_timeout_s`` — this is what lets raylets and workers ride
    out a GCS restart (reference: gcs_rpc_server_reconnect_timeout_s and the
    retryable gRPC client, src/ray/rpc/gcs_server/gcs_rpc_client.h).
    """

    def __init__(self, host: str, port: int):
        self.host, self.port = host, port
        self._client: Optional[RpcClient] = None
        self._lock = asyncio.Lock()

    async def _c(self) -> RpcClient:
        if self._client is None or not self._client.is_connected():
            async with self._lock:
                if self._client is None or not self._client.is_connected():
                    c = RpcClient(self.host, self.port)
                    await c.connect()
                    self._client = c
        return self._client

    async def call(self, method, payload=None, timeout=None, retry_s=None):
        """Issue an RPC; retry connection failures until ``retry_s`` elapses.

        Only transport failures are retried (the GCS handlers are
        at-least-once safe: table writes are idempotent overwrites); remote
        exceptions and response timeouts propagate immediately.
        """
        if retry_s is None:
            retry_s = RTPU_CONFIG.gcs_reconnect_timeout_s
        deadline = asyncio.get_running_loop().time() + retry_s
        delay = 0.05
        while True:
            try:
                c = await self._c()
                return await c.call(
                    method, payload, timeout or RTPU_CONFIG.gcs_rpc_timeout_s
                )
            except (ConnectionLost, ConnectionError, OSError):
                if asyncio.get_running_loop().time() >= deadline:
                    raise
                await asyncio.sleep(delay)
                delay = min(delay * 2, 1.0)

    async def notify(self, method, payload=None):
        try:
            c = await self._c()
            await c.notify(method, payload)
        except (ConnectionLost, OSError):
            pass

    # convenience wrappers -----------------------------------------------

    async def kv_put(self, ns, key, value, overwrite=True):
        r = await self.call("KVPut", {"ns": ns, "key": key, "value": value, "overwrite": overwrite})
        return r["added"]

    async def kv_get(self, ns, key):
        return (await self.call("KVGet", {"ns": ns, "key": key}))["value"]

    async def kv_del(self, ns, key):
        return (await self.call("KVDel", {"ns": ns, "key": key}))["deleted"]

    async def kv_keys(self, ns, prefix=b""):
        return (await self.call("KVKeys", {"ns": ns, "prefix": prefix}))["keys"]

    async def kv_exists(self, ns, key):
        return (await self.call("KVExists", {"ns": ns, "key": key}))["exists"]

    async def get_all_node_info(self) -> List[dict]:
        return (await self.call("GetAllNodeInfo", {}))["nodes"]

    async def close(self):
        if self._client is not None:
            await self._client.close()


class GcsClient:
    """Blocking facade over GcsAioClient for driver/user threads."""

    def __init__(self, host: str, port: int, io: Optional[IoThread] = None):
        self.aio = GcsAioClient(host, port)
        self._io = io or IoThread.current()

    @classmethod
    def from_address(cls, address: str):
        host, port = address.rsplit(":", 1)
        return cls(host, int(port))

    @property
    def address(self):
        return f"{self.aio.host}:{self.aio.port}"

    def call(self, method, payload=None, timeout=None, retry_s=None):
        return self._io.run(self.aio.call(method, payload, timeout, retry_s))

    def kv_put(self, ns, key, value, overwrite=True):
        return self._io.run(self.aio.kv_put(ns, key, value, overwrite))

    def kv_get(self, ns, key):
        return self._io.run(self.aio.kv_get(ns, key))

    def kv_del(self, ns, key):
        return self._io.run(self.aio.kv_del(ns, key))

    def kv_keys(self, ns, prefix=b""):
        return self._io.run(self.aio.kv_keys(ns, prefix))

    def kv_exists(self, ns, key):
        return self._io.run(self.aio.kv_exists(ns, key))

    def get_all_node_info(self):
        return self._io.run(self.aio.get_all_node_info())

    def get_cluster_resources(self):
        return self.call("GetClusterResources", {})

    def ping(self, timeout=5):
        # Bounded retry window: a ping probe should fail fast, not wait out
        # the full reconnect budget.
        return self.call("Ping", {}, timeout=timeout, retry_s=timeout)
