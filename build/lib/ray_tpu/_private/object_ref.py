"""ObjectRef — the user-facing future/handle for a value in the object store.

Semantics follow the reference's ownership model
(reference: src/ray/core_worker/reference_count.h:61): the worker that created
the ref (by ``put`` or by submitting the task that returns it) *owns* it — the
owner address travels with the ref so any borrower can reach the owner for
value/location queries and reference accounting.
"""

from __future__ import annotations

from typing import Optional, Tuple

from ray_tpu._private.ids import ObjectID

# Set by the worker module once a worker is connected; used for local refcounts
# and for `ref.get()` style conveniences.
_worker_hooks = None


def set_worker_hooks(hooks):
    global _worker_hooks
    _worker_hooks = hooks


class ObjectRef:
    __slots__ = ("_id", "_owner_addr", "_skip_refcount", "__weakref__")

    def __init__(
        self,
        object_id: ObjectID,
        owner_addr: Optional[Tuple[str, int]] = None,
        skip_refcount: bool = False,
    ):
        self._id = object_id
        self._owner_addr = tuple(owner_addr) if owner_addr else None
        self._skip_refcount = skip_refcount
        if not skip_refcount and _worker_hooks is not None:
            _worker_hooks.add_local_ref(self)

    def object_id(self) -> ObjectID:
        return self._id

    def hex(self) -> str:
        return self._id.hex()

    def binary(self) -> bytes:
        return self._id.binary()

    @property
    def owner_address(self):
        return self._owner_addr

    def task_id(self):
        return self._id.task_id()

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def __del__(self):
        if not self._skip_refcount and _worker_hooks is not None:
            try:
                _worker_hooks.remove_local_ref(self)
            except Exception:
                pass

    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        if _worker_hooks is None:
            raise RuntimeError("ray_tpu not initialized")
        return _worker_hooks.as_future(self)

    def __reduce__(self):
        # Plain pickling (outside the runtime serializer) preserves identity but
        # does not register borrows; the runtime serializer intercepts before this.
        return (ObjectRef, (self._id, self._owner_addr))

    def __await__(self):
        if _worker_hooks is None:
            raise RuntimeError("ray_tpu not initialized")
        return _worker_hooks.await_ref(self).__await__()
