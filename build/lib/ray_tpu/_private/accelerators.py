"""Accelerator (TPU-first) detection and resource shaping.

Counterpart of the reference's pluggable accelerator managers
(reference: python/ray/_private/accelerators/tpu.py:71) but TPU is the
*primary* accelerator here, not an afterthought: a node contributes

  - ``TPU``: chips on this host,
  - ``TPU-<pod_type>-head``: 1 on the host that is rank 0 of its pod slice
    (reference: tpu.py:362-381 — lets exactly one task/actor gang-schedule a
    whole slice),
  - node labels ``rtpu.io/pod-type``, ``rtpu.io/slice-name``,
    ``rtpu.io/worker-id`` describing ICI topology for slice-aware placement.

Detection deliberately avoids importing jax (that would initialize the TPU
runtime inside control-plane processes); it reads device files and TPU-VM
environment metadata only.
"""

from __future__ import annotations

import glob
import os
from typing import Dict, Tuple


def num_tpu_chips() -> int:
    env = os.environ.get("RTPU_num_tpu_chips")
    if env is not None:
        return int(env)
    # Real TPU VMs expose one /dev/accel* per chip.
    chips = len(glob.glob("/dev/accel*"))
    if chips:
        return chips
    if len(glob.glob("/dev/vfio/*")) > 1:
        return len(glob.glob("/dev/vfio/*")) - 1
    # Tunneled/virtual TPU (axon) — a single chip endpoint.
    if os.environ.get("PALLAS_AXON_TPU_GEN") or "axon" in os.environ.get("JAX_PLATFORMS", ""):
        return 1
    return 0


def tpu_pod_type() -> str:
    """E.g. 'v5litepod-8', or a generation marker like 'v5e' when unknown."""
    env = os.environ.get("RTPU_tpu_pod_type")
    if env:
        return env
    acc = os.environ.get("TPU_ACCELERATOR_TYPE")
    if acc:
        return acc.lower()
    gen = os.environ.get("PALLAS_AXON_TPU_GEN")
    if gen:
        return gen
    return ""


def tpu_worker_id() -> int:
    return int(os.environ.get("TPU_WORKER_ID", "0"))


def tpu_slice_name() -> str:
    return os.environ.get("TPU_NAME", os.environ.get("HOSTNAME", "local-slice"))


def node_resources_and_labels() -> Tuple[Dict[str, float], Dict[str, str]]:
    resources: Dict[str, float] = {}
    labels: Dict[str, str] = {}
    chips = num_tpu_chips()
    if chips > 0:
        resources["TPU"] = float(chips)
        pod = tpu_pod_type()
        if pod:
            labels["rtpu.io/pod-type"] = pod
            labels["rtpu.io/slice-name"] = tpu_slice_name()
            labels["rtpu.io/worker-id"] = str(tpu_worker_id())
            if tpu_worker_id() == 0:
                # One slice-head resource per pod slice; scheduling one task on
                # it is how a whole-slice SPMD job gang-launches.
                resources[f"TPU-{pod.upper()}-head"] = 1.0
    return resources, labels


def visible_chip_env(chip_ids) -> Dict[str, str]:
    """Env vars limiting a worker to specific chips (reference: TPU_VISIBLE_CHIPS)."""
    ids = ",".join(str(int(c)) for c in chip_ids)
    return {
        "TPU_VISIBLE_CHIPS": ids,
        "TPU_CHIPS_PER_PROCESS_BOUNDS": "1,1,1",
        "TPU_PROCESS_BOUNDS": "1,1,1",
    }
