"""Model zoo: TPU-native flax implementations used by the Train/bench stack."""
