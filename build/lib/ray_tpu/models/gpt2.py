"""GPT-2 in flax, written TPU-first.

This is the framework's flagship train/bench model (BASELINE.json config 2:
GPT-2-124M data-parallel). Design notes for the MXU/HBM:

- all matmuls in bf16 with fp32 accumulation (`preferred_element_type`),
  params kept in fp32 for the optimizer, cast per-step;
- attention uses the fused pallas flash kernel when available
  (ray_tpu/ops/attention.py), falling back to a plain einsum softmax that XLA
  fuses well on TPU;
- static shapes everywhere; the whole step is one jit;
- tensor-parallel PartitionSpecs follow the Megatron layout: column-parallel
  qkv/fc1 (shard output dim on 'tp'), row-parallel proj/fc2 (shard input dim),
  so each block needs exactly one psum on the 'tp' axis per sublayer — XLA
  inserts it from the shardings;
- 'fsdp' shards every weight's first dim (ZeRO-3-style gather-per-layer under
  pjit), 'sp' shards the sequence dim of activations.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ray_tpu.parallel.mesh import ShardingRules


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    block_size: int = 1024
    n_layer: int = 12
    n_head: int = 12
    n_embd: int = 768
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16
    use_flash_attention: bool = True
    # Override the attention primitive, e.g. a shard_map-wrapped ring
    # attention bound to a mesh (ray_tpu/parallel/train_step.py). Signature
    # (q, k, v) -> out, all (B, T, H, D).
    attn_fn: Any = None

    @classmethod
    def gpt2_124m(cls, **kw):
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw):
        base = dict(vocab_size=512, block_size=128, n_layer=2, n_head=4, n_embd=128)
        base.update(kw)
        return cls(**base)


class CausalSelfAttention(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic=True):
        cfg = self.config
        B, T, C = x.shape
        head_dim = C // cfg.n_head
        qkv = nn.Dense(3 * C, dtype=cfg.dtype, name="c_attn")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, cfg.n_head, head_dim)
        k = k.reshape(B, T, cfg.n_head, head_dim)
        v = v.reshape(B, T, cfg.n_head, head_dim)

        if cfg.attn_fn is not None:
            y = cfg.attn_fn(q, k, v)
        elif cfg.use_flash_attention:
            from ray_tpu.ops.attention import causal_attention

            y = causal_attention(q, k, v)
        else:
            att = jnp.einsum(
                "bthd,bshd->bhts", q, k, preferred_element_type=jnp.float32
            ) / math.sqrt(head_dim)
            mask = jnp.tril(jnp.ones((T, T), dtype=bool))
            att = jnp.where(mask[None, None], att, -1e30)
            att = jax.nn.softmax(att, axis=-1).astype(cfg.dtype)
            y = jnp.einsum("bhts,bshd->bthd", att, v)
        y = y.reshape(B, T, C)
        return nn.Dense(C, dtype=cfg.dtype, name="c_proj")(y)


class MLP(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic=True):
        cfg = self.config
        h = nn.Dense(4 * cfg.n_embd, dtype=cfg.dtype, name="c_fc")(x)
        h = nn.gelu(h, approximate=True)
        return nn.Dense(cfg.n_embd, dtype=cfg.dtype, name="c_proj")(h)


class Block(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic=True):
        cfg = self.config
        x = x + CausalSelfAttention(cfg, name="attn")(
            nn.LayerNorm(dtype=cfg.dtype, name="ln_1")(x), deterministic
        )
        x = x + MLP(cfg, name="mlp")(
            nn.LayerNorm(dtype=cfg.dtype, name="ln_2")(x), deterministic
        )
        return x


class GPT2(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, idx, deterministic=True):
        cfg = self.config
        B, T = idx.shape
        pos = jnp.arange(T)[None]
        wte = nn.Embed(cfg.vocab_size, cfg.n_embd, dtype=cfg.dtype, name="wte")
        wpe = nn.Embed(cfg.block_size, cfg.n_embd, dtype=cfg.dtype, name="wpe")
        x = wte(idx) + wpe(pos)
        for i in range(cfg.n_layer):
            # remat each block: recompute activations in the backward pass to
            # trade FLOPs for HBM (jax.checkpoint).
            x = nn.remat(Block)(cfg, name=f"h_{i}")(x, deterministic)
        x = nn.LayerNorm(dtype=cfg.dtype, name="ln_f")(x)
        # weight-tied head
        logits = wte.attend(x.astype(jnp.float32))
        return logits


def loss_fn(logits, targets):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -ll.mean()


def init_params(config: GPT2Config, rng=None):
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    model = GPT2(config)
    idx = jnp.zeros((2, min(8, config.block_size)), dtype=jnp.int32)
    return model.init(rng, idx)["params"]


def forward(config: GPT2Config, params, idx):
    return GPT2(config).apply({"params": params}, idx)


def num_params(params) -> int:
    return sum(x.size for x in jax.tree.leaves(params))


# Megatron-style tensor-parallel layout + fsdp on the complementary dim.
# Rule paths match flax param pytree paths like 'h_3/attn/c_attn/kernel'.
GPT2_SHARDING_PATTERNS = [
    (r"wte/embedding", P("tp", "fsdp")),
    (r"wpe/embedding", P(None, "fsdp")),
    (r"attn/c_attn/kernel", P("fsdp", "tp")),   # column parallel
    (r"attn/c_attn/bias", P("tp")),
    (r"attn/c_proj/kernel", P("tp", "fsdp")),   # row parallel
    (r"attn/c_proj/bias", P()),
    (r"mlp/c_fc/kernel", P("fsdp", "tp")),
    (r"mlp/c_fc/bias", P("tp")),
    (r"mlp/c_proj/kernel", P("tp", "fsdp")),
    (r"mlp/c_proj/bias", P()),
    (r"ln_", P()),
]
GPT2_SHARDING_RULES = ShardingRules(GPT2_SHARDING_PATTERNS, default=P())
