"""Llama-family decoder in flax, written TPU-first.

Second model family of the zoo (beside GPT-2 and its MoE variant): RMSNorm,
rotary position embeddings, grouped-query attention, SwiGLU MLP, untied LM
head, no biases anywhere. The reference framework ships no model code at all
(Ray Train wraps user torch models — reference
python/ray/train/torch/torch_trainer.py:11); the zoo exists so the framework's
Train/Tune/bench stack has first-party TPU workloads.

TPU design notes:
- all matmuls bf16 with fp32 accumulation; params fp32 for the optimizer;
- RoPE is applied in fp32 (sin/cos precision matters at long context) and is
  sequence-shift aware so it composes with sequence parallelism: pass
  `pos_offset` to shift positions per sp shard;
- GQA repeats KV heads via a broadcast-reshape that XLA folds into the
  attention einsum — no materialized copy in HBM;
- attention uses the fused pallas flash kernel via ops/attention.py, or an
  injected `attn_fn` (e.g. a shard_map-wrapped ring attention for the 'sp'
  axis, ray_tpu/parallel/train_step.py);
- tensor-parallel layout is Megatron-style: column-parallel q/k/v/gate/up
  (shard output dim on 'tp'), row-parallel o/down (shard input dim), one psum
  per sublayer inserted by XLA from the shardings;
- each block is wrapped in nn.remat (jax.checkpoint) to trade FLOPs for HBM.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ray_tpu.parallel.mesh import ShardingRules


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    block_size: int = 2048
    n_layer: int = 8
    n_head: int = 8
    n_kv_head: int = 4
    n_embd: int = 512
    intermediate: Optional[int] = None  # default: the 8/3 SwiGLU rule, rounded
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    use_flash_attention: bool = True
    # Override the attention primitive, e.g. ring attention bound to a mesh.
    # Signature (q, k, v) -> out, all (B, T, H, D) with H == n_head.
    attn_fn: Any = None

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head

    @property
    def mlp_dim(self) -> int:
        if self.intermediate is not None:
            return self.intermediate
        # 2/3 * 4 * n_embd rounded up to a multiple of 128 (MXU lane width).
        raw = int(8 * self.n_embd / 3)
        return (raw + 127) // 128 * 128

    @classmethod
    def tiny(cls, **kw):
        base = dict(vocab_size=512, block_size=128, n_layer=2, n_head=4,
                    n_kv_head=2, n_embd=128)
        base.update(kw)
        return cls(**base)

    @classmethod
    def llama_160m(cls, **kw):
        base = dict(vocab_size=32000, block_size=1024, n_layer=12, n_head=12,
                    n_kv_head=4, n_embd=768)
        base.update(kw)
        return cls(**base)


def rms_norm(x, weight, eps):
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + eps)).astype(dtype) * weight


class RMSNorm(nn.Module):
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x):
        w = self.param("weight", nn.initializers.ones, (x.shape[-1],), jnp.float32)
        return rms_norm(x, w.astype(x.dtype), self.eps)


def rope_angles(head_dim: int, theta: float, positions):
    """(T,) int positions -> (T, head_dim//2) fp32 angles."""
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    return positions.astype(jnp.float32)[:, None] * inv[None, :]


def apply_rope(x, angles):
    """x (B, T, H, D); angles (T, D//2). Rotate-half convention, fp32 math."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    x1, x2 = jnp.split(x32, 2, axis=-1)
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(dtype)


class LlamaAttention(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x, pos_offset=0):
        cfg = self.config
        B, T, C = x.shape
        hd = cfg.head_dim
        dense = lambda n, name: nn.Dense(n, use_bias=False, dtype=cfg.dtype, name=name)
        q = dense(cfg.n_head * hd, "wq")(x).reshape(B, T, cfg.n_head, hd)
        k = dense(cfg.n_kv_head * hd, "wk")(x).reshape(B, T, cfg.n_kv_head, hd)
        v = dense(cfg.n_kv_head * hd, "wv")(x).reshape(B, T, cfg.n_kv_head, hd)

        positions = jnp.arange(T) + pos_offset
        ang = rope_angles(hd, cfg.rope_theta, positions)
        q = apply_rope(q, ang)
        k = apply_rope(k, ang)

        if cfg.n_kv_head != cfg.n_head:
            rep = cfg.n_head // cfg.n_kv_head
            # broadcast-reshape; XLA folds this into the attention contraction
            k = jnp.broadcast_to(k[:, :, :, None, :], (B, T, cfg.n_kv_head, rep, hd)
                                 ).reshape(B, T, cfg.n_head, hd)
            v = jnp.broadcast_to(v[:, :, :, None, :], (B, T, cfg.n_kv_head, rep, hd)
                                 ).reshape(B, T, cfg.n_head, hd)

        if cfg.attn_fn is not None:
            y = cfg.attn_fn(q, k, v)
        elif cfg.use_flash_attention:
            from ray_tpu.ops.attention import causal_attention

            y = causal_attention(q, k, v)
        else:
            att = jnp.einsum("bthd,bshd->bhts", q, k,
                             preferred_element_type=jnp.float32) / math.sqrt(hd)
            mask = jnp.tril(jnp.ones((T, T), dtype=bool))
            att = jnp.where(mask[None, None], att, -1e30)
            att = jax.nn.softmax(att, axis=-1).astype(cfg.dtype)
            y = jnp.einsum("bhts,bshd->bthd", att, v)
        y = y.reshape(B, T, cfg.n_head * hd)
        return dense(C, "wo")(y)


class LlamaMLP(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dense = lambda n, name: nn.Dense(n, use_bias=False, dtype=cfg.dtype, name=name)
        return dense(cfg.n_embd, "down")(
            nn.silu(dense(cfg.mlp_dim, "gate")(x)) * dense(cfg.mlp_dim, "up")(x)
        )


class LlamaBlock(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x, pos_offset=0):
        cfg = self.config
        x = x + LlamaAttention(cfg, name="attn")(
            RMSNorm(cfg.rms_eps, name="attn_norm")(x), pos_offset
        )
        x = x + LlamaMLP(cfg, name="mlp")(RMSNorm(cfg.rms_eps, name="mlp_norm")(x))
        return x


class Llama(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, idx, pos_offset=0):
        cfg = self.config
        x = nn.Embed(cfg.vocab_size, cfg.n_embd, dtype=cfg.dtype, name="tok_emb")(idx)
        for i in range(cfg.n_layer):
            x = nn.remat(LlamaBlock)(cfg, name=f"h_{i}")(x, pos_offset)
        x = RMSNorm(cfg.rms_eps, name="final_norm")(x)
        logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=jnp.float32,
                          name="lm_head")(x.astype(jnp.float32))
        return logits


def loss_fn(logits, targets):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -ll.mean()


def init_params(config: LlamaConfig, rng=None):
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    idx = jnp.zeros((2, min(8, config.block_size)), dtype=jnp.int32)
    return Llama(config).init(rng, idx)["params"]


def forward(config: LlamaConfig, params, idx, pos_offset=0):
    return Llama(config).apply({"params": params}, idx, pos_offset)


def num_params(params) -> int:
    return sum(x.size for x in jax.tree.leaves(params))


# Megatron-style TP layout + fsdp on the complementary dim. Paths are flax
# pytree paths like 'h_3/attn/wq/kernel'.
LLAMA_SHARDING_PATTERNS = [
    (r"tok_emb/embedding", P("tp", "fsdp")),
    (r"attn/w[qkv]/kernel", P("fsdp", "tp")),   # column parallel
    (r"attn/wo/kernel", P("tp", "fsdp")),       # row parallel
    (r"mlp/(gate|up)/kernel", P("fsdp", "tp")),
    (r"mlp/down/kernel", P("tp", "fsdp")),
    (r"lm_head/kernel", P("fsdp", "tp")),
    (r"norm", P()),
]
LLAMA_SHARDING_RULES = ShardingRules(LLAMA_SHARDING_PATTERNS, default=P())
