"""GPT-2 with Mixture-of-Experts FFN blocks (expert parallelism).

Green-field TPU-native capability (the reference has no MoE — SURVEY §2.4):
every ``moe_every``-th block swaps its dense MLP for a top-k routed MoE
(ray_tpu/ops/moe.py). Experts shard over the 'ep' mesh axis; everything
else follows the dense GPT-2 Megatron layout.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ray_tpu.models.gpt2 import (
    GPT2Config,
    GPT2_SHARDING_PATTERNS,
    CausalSelfAttention,
    MLP,
    loss_fn,
)
from ray_tpu.ops.moe import MOE_SHARDING_PATTERNS, MoE, MoEConfig
from ray_tpu.parallel.mesh import ShardingRules


@dataclasses.dataclass(frozen=True)
class GPT2MoEConfig(GPT2Config):
    moe: MoEConfig = MoEConfig()
    moe_every: int = 2  # every Nth block is an MoE block (1 = all)

    @classmethod
    def tiny_moe(cls, **kw):
        base = dict(
            vocab_size=512, block_size=128, n_layer=2, n_head=4, n_embd=128,
            moe=MoEConfig(num_experts=4, top_k=2),
            moe_every=1,
        )
        base.update(kw)
        return cls(**base)


class MoEBlock(nn.Module):
    config: GPT2MoEConfig

    @nn.compact
    def __call__(self, x, deterministic=True):
        cfg = self.config
        x = x + CausalSelfAttention(cfg, name="attn")(
            nn.LayerNorm(dtype=cfg.dtype, name="ln_1")(x), deterministic
        )
        x = x + MoE(
            d_model=cfg.n_embd,
            d_ff=4 * cfg.n_embd,
            moe=cfg.moe,
            dtype=cfg.dtype,
            name="moe",
        )(nn.LayerNorm(dtype=cfg.dtype, name="ln_2")(x), deterministic)
        return x


class DenseBlock(nn.Module):
    config: GPT2MoEConfig

    @nn.compact
    def __call__(self, x, deterministic=True):
        cfg = self.config
        x = x + CausalSelfAttention(cfg, name="attn")(
            nn.LayerNorm(dtype=cfg.dtype, name="ln_1")(x), deterministic
        )
        x = x + MLP(cfg, name="mlp")(
            nn.LayerNorm(dtype=cfg.dtype, name="ln_2")(x), deterministic
        )
        return x


class GPT2MoE(nn.Module):
    config: GPT2MoEConfig

    @nn.compact
    def __call__(self, idx, deterministic=True):
        cfg = self.config
        B, T = idx.shape
        pos = jnp.arange(T)[None]
        wte = nn.Embed(cfg.vocab_size, cfg.n_embd, dtype=cfg.dtype, name="wte")
        wpe = nn.Embed(cfg.block_size, cfg.n_embd, dtype=cfg.dtype, name="wpe")
        x = wte(idx) + wpe(pos)
        for i in range(cfg.n_layer):
            is_moe = (i % cfg.moe_every) == (cfg.moe_every - 1)
            block = MoEBlock if is_moe else DenseBlock
            x = block(cfg, name=f"h_{i}")(x, deterministic)
        x = nn.LayerNorm(dtype=cfg.dtype, name="ln_f")(x)
        return wte.attend(x.astype(jnp.float32))


def init_params(config: GPT2MoEConfig, rng=None):
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    idx = jnp.zeros((2, min(8, config.block_size)), dtype=jnp.int32)
    return GPT2MoE(config).init(rng, idx)["params"]


def forward_with_aux(config: GPT2MoEConfig, params, idx):
    """Returns (logits, total_moe_aux_loss)."""
    logits, state = GPT2MoE(config).apply(
        {"params": params}, idx, mutable=["losses"]
    )
    aux_leaves = jax.tree.leaves(state.get("losses", {}))
    aux = sum(aux_leaves) if aux_leaves else jnp.float32(0.0)
    return logits, aux


def moe_loss_fn(config: GPT2MoEConfig, params, idx, targets):
    logits, aux = forward_with_aux(config, params, idx)
    return loss_fn(logits, targets) + aux


# MoE rules first: they are more specific than the dense fallbacks.
GPT2_MOE_SHARDING_RULES = ShardingRules(
    MOE_SHARDING_PATTERNS + GPT2_SHARDING_PATTERNS,
    default=P(),
)
