"""Search-space primitives + the basic variant generator.

Reference: python/ray/tune/search/sample.py (Domain/Categorical/Float/Integer)
and search/basic_variant.py (grid cross-product x num_samples expansion).
"""

from __future__ import annotations

import random
from typing import Any, Dict, List


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Categorical(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Uniform(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low, high):
        import math

        self._lo, self._hi = math.log(low), math.log(high)

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(self._lo, self._hi))


class Randint(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class GridSearch:
    def __init__(self, values):
        self.values = list(values)


def grid_search(values) -> GridSearch:
    return GridSearch(values)


def choice(categories) -> Categorical:
    return Categorical(categories)


def uniform(low, high) -> Uniform:
    return Uniform(low, high)


def loguniform(low, high) -> LogUniform:
    return LogUniform(low, high)


def randint(low, high) -> Randint:
    return Randint(low, high)


def generate_variants(param_space: Dict[str, Any], num_samples: int,
                      seed: int = 0) -> List[Dict[str, Any]]:
    """Expand grid_search dims into a cross-product; draw num_samples of the
    stochastic dims for each grid point (reference basic_variant semantics)."""
    rng = random.Random(seed)
    grid_keys = [k for k, v in param_space.items() if isinstance(v, GridSearch)]
    grids: List[Dict[str, Any]] = [{}]
    for k in grid_keys:
        grids = [dict(g, **{k: v}) for g in grids
                 for v in param_space[k].values]
    variants = []
    for _ in range(num_samples):
        for g in grids:
            cfg = {}
            for k, v in param_space.items():
                if k in g:
                    cfg[k] = g[k]
                elif isinstance(v, Domain):
                    cfg[k] = v.sample(rng)
                else:
                    cfg[k] = v
            variants.append(cfg)
    return variants
