"""Trial schedulers: FIFO, ASHA (async successive halving), PBT,
median-stopping, HyperBand.

Reference: python/ray/tune/schedulers/async_hyperband.py:19 (ASHA brackets /
rung cutoffs), schedulers/pbt.py:221 (exploit top quantile + explore by
perturbation at a fixed interval), schedulers/median_stopping_rule.py,
schedulers/hyperband.py.
"""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"
# PBT: replace this trial's state+config from a donor and restart.
EXPLOIT = "EXPLOIT"


class FIFOScheduler:
    def on_trial_result(self, controller, trial, result) -> str:
        return CONTINUE

    def on_trial_complete(self, controller, trial, result):
        pass


class _Rung:
    def __init__(self, milestone: int):
        self.milestone = milestone
        self.recorded: Dict[str, float] = {}  # trial_id -> metric at milestone

    def cutoff(self, reduction_factor) -> Optional[float]:
        if not self.recorded:
            return None
        vals = sorted(self.recorded.values())
        # keep the top 1/reduction_factor
        k = len(vals) - max(1, int(len(vals) / reduction_factor))
        return vals[k] if 0 <= k < len(vals) else None


class AsyncHyperBandScheduler(FIFOScheduler):
    """ASHA: promote only trials in the top 1/reduction_factor at each rung;
    stop the rest as soon as they report at a milestone."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 4, time_attr: str = "training_iteration"):
        assert mode in ("max", "min")
        self.metric, self.mode = metric, mode
        self.max_t, self.grace = max_t, grace_period
        self.rf = reduction_factor
        self.time_attr = time_attr
        rungs = []
        t = grace_period
        while t < max_t:
            rungs.append(_Rung(t))
            t *= reduction_factor
        self.rungs = rungs[::-1]  # highest milestone first

    def _score(self, result) -> float:
        v = float(result[self.metric])
        return v if self.mode == "max" else -v

    def on_trial_result(self, controller, trial, result) -> str:
        t = result.get(self.time_attr, 0)
        if t >= self.max_t:
            return STOP
        if self.metric not in result:
            return CONTINUE  # warm-up / heartbeat rounds carry no metric
        action = CONTINUE
        for rung in self.rungs:
            if t < rung.milestone:
                continue
            if trial.id in rung.recorded:
                break
            score = self._score(result)
            cutoff = rung.cutoff(self.rf)
            rung.recorded[trial.id] = score
            if cutoff is not None and score < cutoff:
                action = STOP
            break
        return action


ASHAScheduler = AsyncHyperBandScheduler


class PopulationBasedTraining(FIFOScheduler):
    """PBT: every perturbation_interval iterations, a bottom-quantile trial
    clones a top-quantile trial's checkpoint and perturbs its hyperparams."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25, seed: int = 0,
                 time_attr: str = "training_iteration"):
        assert mode in ("max", "min")
        self.metric, self.mode = metric, mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.time_attr = time_attr
        self._rng = random.Random(seed)
        self._last_perturb: Dict[str, int] = {}
        self.num_perturbations = 0

    def _score(self, result) -> float:
        v = float(result[self.metric])
        return v if self.mode == "max" else -v

    def _quantiles(self, controller):
        scored = [
            (t, self._score(t.last_result))
            for t in controller.live_trials()
            if t.last_result and self.metric in t.last_result
        ]
        if len(scored) < 2:
            return [], []
        scored.sort(key=lambda kv: kv[1])
        k = max(1, int(len(scored) * self.quantile))
        return [t for t, _ in scored[:k]], [t for t, _ in scored[-k:]]

    def perturbed(self, config: Dict[str, Any]) -> Dict[str, Any]:
        out = dict(config)
        for k, spec in self.mutations.items():
            if isinstance(spec, list):
                out[k] = self._rng.choice(spec)
            elif callable(spec):
                out[k] = spec()
            elif k in out and isinstance(out[k], (int, float)):
                factor = self._rng.choice([0.8, 1.2])
                out[k] = type(out[k])(out[k] * factor)
        return out

    def on_trial_result(self, controller, trial, result) -> str:
        if self.metric not in result:
            return CONTINUE
        t = result.get(self.time_attr, 0)
        last = self._last_perturb.get(trial.id, 0)
        if t - last < self.interval:
            return CONTINUE
        self._last_perturb[trial.id] = t
        bottom, top = self._quantiles(controller)
        if trial in bottom and top:
            donor = self._rng.choice(top)
            if donor is not trial and donor.latest_checkpoint:
                trial.exploit_from = donor
                trial.exploit_config = self.perturbed(donor.config)
                self.num_perturbations += 1
                return EXPLOIT
        return CONTINUE


class MedianStoppingRule(FIFOScheduler):
    """Stop a trial whose running-average metric falls below the median of
    other trials' running averages at the same step (reference:
    schedulers/median_stopping_rule.py)."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 grace_period: int = 4, min_samples_required: int = 3):
        self.metric = metric
        self.mode = mode
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        # trial_id -> list of metric values per reported step
        self._history: Dict[str, List[float]] = {}

    def _score(self, result) -> float:
        v = float(result.get(self.metric, 0.0))
        return v if self.mode == "max" else -v

    def on_trial_result(self, controller, trial, result) -> str:
        if self.metric not in result:
            return CONTINUE  # warm-up / heartbeat rounds carry no metric
        hist = self._history.setdefault(trial.id, [])
        hist.append(self._score(result))
        step = len(hist)
        if step <= self.grace_period:
            return CONTINUE
        # running averages of OTHER trials truncated to this step
        others = [
            sum(h[:step]) / min(step, len(h))
            for tid, h in self._history.items()
            if tid != trial.id and len(h) >= 1
        ]
        if len(others) < self.min_samples:
            return CONTINUE
        others.sort()
        median = others[len(others) // 2]
        mine = sum(hist) / len(hist)
        return STOP if mine < median else CONTINUE


class HyperBandScheduler(FIFOScheduler):
    """Synchronous-flavor HyperBand approximated asynchronously: trials are
    assigned round-robin to brackets with different starting rungs, each
    bracket running successive halving (reference: schedulers/hyperband.py;
    asynchronous assignment like ASHA so stragglers can't stall a bracket)."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 max_t: int = 81, reduction_factor: int = 3):
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.rf = reduction_factor
        # bracket b starts halving at rung rf^b
        # integer loop, not int(log()): FP rounds log(243, 3) down to
        # 4.999..., silently losing the no-early-stopping bracket
        self.num_brackets = 1
        t = reduction_factor
        while t <= max_t:
            self.num_brackets += 1
            t *= reduction_factor
        self._brackets: List[List[_Rung]] = []
        for b in range(self.num_brackets):
            milestones = []
            t = reduction_factor ** b
            while t <= max_t:
                milestones.append(t)
                t *= reduction_factor
            self._brackets.append([_Rung(m) for m in reversed(milestones)])
        self._assignment: Dict[str, int] = {}
        self._next_bracket = 0

    def _score(self, result) -> float:
        v = float(result.get(self.metric, 0.0))
        return v if self.mode == "max" else -v

    def on_trial_result(self, controller, trial, result) -> str:
        if self.metric not in result:
            return CONTINUE  # warm-up / heartbeat rounds carry no metric
        b = self._assignment.get(trial.id)
        if b is None:
            b = self._next_bracket % self.num_brackets
            self._next_bracket += 1
            self._assignment[trial.id] = b
        step = int(result.get("training_iteration", trial.iteration))
        score = self._score(result)
        decision = CONTINUE
        for rung in self._brackets[b]:  # highest milestone first
            if step >= rung.milestone and trial.id not in rung.recorded:
                rung.recorded[trial.id] = score
                cutoff = rung.cutoff(self.rf)
                if cutoff is not None and score < cutoff:
                    decision = STOP
                break
        if step >= self.max_t:
            decision = STOP
        return decision
