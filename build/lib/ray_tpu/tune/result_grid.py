"""ResultGrid: the return value of Tuner.fit (reference: tune/result_grid.py)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ray_tpu.train._checkpoint import Checkpoint


class TrialResult:
    def __init__(self, trial):
        self.trial_id = trial.id
        self.config: Dict[str, Any] = trial.config
        self.metrics: Dict[str, Any] = trial.last_result or {}
        self.metrics_history: List[Dict[str, Any]] = trial.metrics_history
        self.error: Optional[str] = trial.error
        self.path = trial.local_dir
        self.checkpoint: Optional[Checkpoint] = (
            Checkpoint(trial.latest_checkpoint)
            if trial.latest_checkpoint else None
        )

    def __repr__(self):
        return (f"TrialResult({self.trial_id}, metrics={self.metrics!r}, "
                f"error={self.error!r})")


class ResultGrid:
    def __init__(self, trials, experiment_path: str):
        self._results = [TrialResult(t) for t in trials]
        self.experiment_path = experiment_path

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> TrialResult:
        return self._results[i]

    def __iter__(self):
        return iter(self._results)

    @property
    def errors(self) -> List[str]:
        return [r.error for r in self._results if r.error]

    def get_best_result(self, metric: str, mode: str = "max") -> TrialResult:
        assert mode in ("max", "min")
        candidates = [r for r in self._results
                      if r.error is None and metric in r.metrics]
        if not candidates:
            raise ValueError(f"no successful trial reported metric {metric!r}")
        sign = 1 if mode == "max" else -1
        return max(candidates, key=lambda r: sign * float(r.metrics[metric]))

    def get_dataframe(self):
        rows = []
        for r in self._results:
            row = {"trial_id": r.trial_id, "error": r.error}
            row.update({f"config/{k}": v for k, v in r.config.items()})
            row.update(r.metrics)
            rows.append(row)
        return rows
