"""Per-node dashboard agent (dashboard/agent.py): spawn by the raylet,
GCS registration, node stats / metrics / profile fan-out via the dashboard
head, and death detection + restart + failure reporting.

Reference behaviors mirrored: python/ray/dashboard/agent.py:25 (per-node
agent process), modules/reporter/reporter_agent.py:314 (host + per-worker
stats), the raylet<->agent fate-sharing/death-reporting contract."""

import json
import time
import urllib.request

import pytest

import ray_tpu


@pytest.fixture
def agent_cluster(monkeypatch):
    monkeypatch.setenv("RTPU_dashboard_agent", "1")
    ray_tpu.init(num_cpus=2)
    yield
    ray_tpu.shutdown()


def _gcs_client():
    from ray_tpu._private.worker import get_global_worker

    return get_global_worker().gcs


def _wait_agents(n, timeout=30):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        keys = _gcs_client().kv_keys(b"agents")
        if len(keys) >= n:
            recs = {}
            for k in keys:
                raw = _gcs_client().kv_get(b"agents", k)
                if raw:
                    recs[k.decode()] = json.loads(raw)
            if len(recs) >= n:
                return recs
        time.sleep(0.3)
    raise TimeoutError("agent never registered")


def _get_json(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as r:
        return json.loads(r.read())


def test_agent_stats_metrics_profile_and_restart(agent_cluster):
    from ray_tpu._private.worker import get_global_worker
    from ray_tpu.dashboard.head import start_dashboard

    agents = _wait_agents(1)
    (node_hex, rec), = agents.items()
    assert rec["host"] and rec["port"] and rec["pid"]

    # a live worker so per-worker stats and profiling have a target
    @ray_tpu.remote
    class Busy:
        def spin(self, s):
            t0 = time.time()
            while time.time() - t0 < s:
                sum(range(1000))
            return b"done"

        def pid(self):
            import os

            return os.getpid()

    b = Busy.remote()
    worker_pid = ray_tpu.get(b.pid.remote())

    gcs_address = get_global_worker().gcs_address
    _head, port = start_dashboard(gcs_address)
    # --- node stats through the head's agent fan-out
    stats = _get_json(port, "/api/node_stats")
    assert stats["agent_count"] == 1 and not stats["errors"]
    node = stats["nodes"][0]
    assert node["node_id"] == node_hex
    assert node["mem"]["total"] > 0 and node["cpu_count"] >= 1
    assert any(w["pid"] == worker_pid for w in node["workers"])

    one = _get_json(port, f"/api/node_stats?node_id={node_hex}")
    assert one["node_id"] == node_hex

    # --- prometheus text from the agent
    metrics = _get_json(port, "/api/agent_metrics")["text"]
    assert "ray_tpu_agent_cpu_percent" in metrics
    assert "ray_tpu_agent_worker_rss_bytes" in metrics

    # --- profile a busy worker via the agent routing
    fut = b.spin.remote(4)
    time.sleep(0.3)
    prof = _get_json(
        port,
        f"/api/profile?pid={worker_pid}&node_id={node_hex}&duration=1")
    folded = prof.get("folded", "") or json.dumps(prof)
    assert "spin" in folded
    ray_tpu.get(fut)

    # --- kill the agent: death is reported and the raylet restarts it
    import os
    import signal

    os.kill(rec["pid"], signal.SIGKILL)
    deadline = time.monotonic() + 30
    reported = False
    new_rec = None
    while time.monotonic() < deadline:
        failures = get_global_worker().gcs.call(
            "GetWorkerFailures", {"limit": 200})["failures"]
        reported = any(
            "dashboard agent exited" in f.get("reason", "")
            for f in failures)
        raw = _gcs_client().kv_get(b"agents", node_hex.encode())
        if raw:
            cand = json.loads(raw)
            if cand["pid"] != rec["pid"]:
                new_rec = cand
        if reported and new_rec:
            break
        time.sleep(0.5)
    assert reported, "agent death never reported to GCS"
    assert new_rec, "agent was not restarted"
    # the restarted agent serves stats again
    stats = _get_json(port, f"/api/node_stats?node_id={node_hex}")
    assert stats["node_id"] == node_hex
