"""DQN tests (reference: rllib/algorithms/dqn tests + tuned_examples
threshold runs)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import DQNConfig
from ray_tpu.rllib.algorithms.dqn import ReplayBuffer


@pytest.fixture(scope="module")
def rl_cluster():
    ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


def test_numpy_q_forward_matches_flax():
    import jax
    import jax.numpy as jnp

    from ray_tpu.rllib.core.rl_module import QModule, numpy_q_forward

    mod = QModule(num_actions=3, hidden=(16, 16))
    params = mod.init_params(obs_dim=4, seed=0)
    obs = np.random.default_rng(0).normal(size=(7, 4)).astype(np.float32)
    q_j = mod.apply({"params": params}, jnp.asarray(obs))
    q_n = numpy_q_forward(jax.tree.map(np.asarray, params), obs)
    np.testing.assert_allclose(q_n, np.asarray(q_j), atol=1e-5)


def test_replay_buffer_wraps():
    buf = ReplayBuffer(capacity=10, obs_dim=2)
    mk = lambda n, val: {
        "obs": np.full((n, 2), val, np.float32),
        "next_obs": np.full((n, 2), val, np.float32),
        "actions": np.zeros(n, np.int64),
        "rewards": np.full(n, val, np.float32),
        "dones": np.zeros(n, np.float32),
    }
    buf.add_batch(mk(6, 1.0))
    assert buf.size == 6
    buf.add_batch(mk(6, 2.0))  # wraps: 12 > 10
    assert buf.size == 10
    s = buf.sample(np.random.default_rng(0), 32)
    assert s["obs"].shape == (32, 2)
    # newest values must be present
    assert (s["rewards"] == 2.0).any()


def test_dqn_cartpole_learns(rl_cluster):
    """Learning test: CartPole mean return reaches 130 within the budget,
    with epsilon-greedy CPU rollouts and the double-DQN update jit'd on the
    8-device mesh."""
    algo = (
        DQNConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=8,
                     rollout_fragment_length=32)
        .training(lr=1e-3, train_batch_size=256, updates_per_iteration=64,
                  target_update_freq=2, epsilon_decay_iters=25,
                  learning_starts=500)
        .debugging(seed=0)
        .build()
    )
    try:
        best = 0.0
        for _ in range(80):
            result = algo.train()
            best = max(best, result["episode_return_mean"])
            if best >= 130:
                break
        assert best >= 130, f"DQN failed to learn CartPole: best={best:.1f}"
    finally:
        algo.stop()


def test_dqn_save_restore(rl_cluster, tmp_path):
    """Checkpointable surface: save -> from_checkpoint restores weights,
    target net and counters (reference: Algorithm.save/from_checkpoint)."""
    import jax

    from ray_tpu.rllib import DQN

    algo = (
        DQNConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=1, num_envs_per_env_runner=4,
                     rollout_fragment_length=16)
        .training(learning_starts=64, updates_per_iteration=4)
        .build()
    )
    try:
        for _ in range(3):
            algo.train()
        path = algo.save(str(tmp_path / "ck"))
        w0 = algo.get_weights()
        it0 = algo._iteration
    finally:
        algo.stop()

    algo2 = DQN.from_checkpoint(path)
    try:
        w1 = algo2.get_weights()
        for a, b in zip(jax.tree.leaves(w0), jax.tree.leaves(w1)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert algo2._iteration == it0
        r = algo2.train()  # resumes counting from the checkpoint
        assert r["training_iteration"] == it0 + 1
    finally:
        algo2.stop()
