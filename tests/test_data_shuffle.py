"""Distributed random_shuffle + operator fusion (reference:
data/_internal/planner/exchange/shuffle_task_spec.py and
data/_internal/logical/rules/operator_fusion.py)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rdata


@pytest.fixture(scope="module")
def data_cluster():
    ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


def test_random_shuffle_preserves_rows(data_cluster):
    ds = rdata.range(2000, override_num_blocks=8)
    shuffled = ds.random_shuffle(seed=7)
    rows = [r["id"] for r in shuffled.take_all()]
    assert sorted(rows) == list(range(2000))
    # actually permuted (probability of identity is ~0)
    assert rows != list(range(2000))
    # deterministic under the same seed
    rows2 = [r["id"] for r in ds.random_shuffle(seed=7).take_all()]
    assert rows == rows2
    # different seed, different order
    rows3 = [r["id"] for r in ds.random_shuffle(seed=8).take_all()]
    assert rows != rows3


def test_random_shuffle_mixes_across_blocks(data_cluster):
    """Every output partition should contain rows from many input blocks
    (the old driver-side implementation trivially had this; the exchange
    must too)."""
    ds = rdata.range(4000, override_num_blocks=8)
    out_blocks = list(
        ds.random_shuffle(seed=0)._iter_block_refs()
    )
    assert len(out_blocks) >= 2
    first = ray_tpu.get(out_blocks[0])
    ids = np.asarray(first["id"])
    # input block b held ids [b*500, (b+1)*500): a well-mixed partition
    # draws from nearly all 8 source blocks
    source_blocks = set(ids // 500)
    assert len(source_blocks) >= 6, source_blocks


def test_random_shuffle_driver_memory_ceiling(data_cluster):
    """The shuffle itself must not materialize the dataset in the driver:
    blocks are built worker-side, the exchange routes refs only."""
    import os

    import psutil

    row_bytes = 40_000
    n_rows = 2_000  # ~80 MB total, built by map tasks (never in the driver)

    def expand(batch):
        n = len(batch["id"])
        return {
            "id": batch["id"],
            "payload": np.ones((n, row_bytes // 8), np.float64),
        }

    ds = rdata.range(n_rows, override_num_blocks=8).map_batches(expand)
    refs = list(ds._iter_block_refs())  # materialize worker-side

    proc = psutil.Process(os.getpid())
    rss_before = proc.memory_info().rss
    shuffled_refs = list(rdata.Dataset(refs).random_shuffle(seed=3)
                         ._iter_block_refs())
    rss_after = proc.memory_info().rss
    grew = rss_after - rss_before
    total = n_rows * row_bytes
    assert grew < total // 2, (
        f"driver RSS grew {grew / 1e6:.0f} MB shuffling a "
        f"{total / 1e6:.0f} MB dataset — looks driver-materializing"
    )
    # all rows survived (count via tasks, not driver concat)
    counts = ray_tpu.get([
        _rows.remote(r) for r in shuffled_refs
    ])
    assert sum(counts) == n_rows


@ray_tpu.remote
def _rows(block):
    from ray_tpu.data.block import block_num_rows

    return block_num_rows(block)


def test_operator_fusion_plan(data_cluster):
    from ray_tpu.data._streaming import (
        FusedMapOperator,
        MapOperator,
        RechunkOperator,
        fuse_operators,
    )

    mk = lambda name: MapOperator(  # noqa: E731
        lambda b: b, is_batch_fn=True, name=name
    )
    actor_op = MapOperator(lambda b: b, is_batch_fn=True, compute_actors=2,
                           name="Actors")
    ops = [mk("A"), mk("B"), RechunkOperator(10), mk("C"), mk("D"),
           actor_op, mk("E")]
    fused = fuse_operators(ops)
    # A+B fuse; Rechunk barrier; C+D fuse; actor stage passes through; E solo
    assert len(fused) == 5
    assert isinstance(fused[0], FusedMapOperator)
    assert fused[0].name == "A+B"
    assert isinstance(fused[1], RechunkOperator)
    assert isinstance(fused[2], FusedMapOperator)
    assert fused[2].name == "C+D"
    assert fused[3] is actor_op
    assert fused[4].name == "E"


def test_operator_fusion_task_count_and_results(data_cluster):
    """A 3-op chain over K blocks launches K tasks (counted via the GCS
    task-event sink), and row/batch semantics survive fusion."""
    import time

    ds = (
        rdata.range(400, override_num_blocks=4)
        .map(lambda r: {"id": r["id"], "x": r["id"] * 2})
        .filter(lambda r: r["x"] % 4 == 0)
        .map_batches(lambda b: {"x": np.asarray(b["x"]) + 1})
    )
    out = sorted(r["x"] for r in ds.take_all())
    assert out == [x * 2 + 1 for x in range(400) if (x * 2) % 4 == 0]

    # count executed map tasks for a tagged run via the task-event sink
    tag = f"fusion_probe_{time.time_ns()}"

    def tagged(batch):
        return batch

    tagged.__name__ = tag
    probe = (
        rdata.range(400, override_num_blocks=4)
        .map_batches(tagged)
        .map(lambda r: r)
        .filter(lambda r: True)
    )
    probe.take_all()
    from ray_tpu._private.worker import get_global_worker

    deadline = time.time() + 15
    n_tasks = None
    while time.time() < deadline:
        events = get_global_worker().gcs.call(
            "GetTaskEvents", {"limit": 10_000}
        )["events"]
        names = {e["task_id"]: e["name"] for e in events
                 if tag in e.get("name", "")}
        if names:
            n_tasks = len(names)
            # events flush asynchronously; settle briefly
            time.sleep(1.5)
            events = get_global_worker().gcs.call(
                "GetTaskEvents", {"limit": 10_000}
            )["events"]
            names = {e["task_id"]: e["name"] for e in events
                     if tag in e.get("name", "")}
            n_tasks = len(names)
            break
        time.sleep(0.5)
    # 4 blocks -> exactly 4 fused tasks (the tagged stage's name appears in
    # the fused task name); without fusion the chain would launch 12
    assert n_tasks == 4, f"expected 4 fused tasks, saw {n_tasks}"
